"""Pipeline parallelism: stage-sharded models via collective microbatching.

The reference framework is DP-only (SURVEY.md §2c — pipeline parallelism
is "absent from all 448 lines"), but a TPU framework at its scale must
let one trial's model exceed one chip. This module implements GPipe-style
pipeline parallelism the SPMD way: every device runs the *same* jitted
program under ``shard_map``; the stage dimension of the weights is
sharded over a ``pipe`` mesh axis, microbatches march through the stages
with non-cyclic ``jax.lax.ppermute`` neighbor hops (ICI-adjacent by
construction — see ``setup_groups(pipeline_parallel=...)``), and the
whole schedule is a single differentiable ``lax.scan``, so ``jax.grad``
of a loss on the pipeline output *is* the backward pipeline — no
hand-written backward schedule, no recompilation per stage.

Schedule: the classic GPipe fill/steady/drain loop — with M microbatches
and S stages, the scan runs ``M + S - 1`` ticks; stage 0 injects
microbatch ``t`` at tick ``t``, stage ``S-1`` emits microbatch
``t-(S-1)`` at tick ``t``. Bubble fraction ``(S-1)/(M+S-1)`` — pick
``num_microbatches >> num_stages`` to amortize, exactly as in the GPipe
paper. Composes with data parallelism: on a ``(data, pipe)`` submesh the
batch dimension is additionally sharded over ``data`` and XLA reduces
gradients over both axes.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multidisttorch_tpu.utils.compat import shard_map as compat_shard_map
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, TrialMesh


def _resolve_mesh(trial: TrialMesh | Mesh) -> Mesh:
    return trial.mesh if isinstance(trial, TrialMesh) else trial


def stage_params_sharding(trial: TrialMesh | Mesh) -> NamedSharding:
    """Sharding for stacked per-stage weights: leading (stage) axis split
    over the ``pipe`` mesh axis, so each device holds exactly its own
    stage's parameters."""
    mesh = _resolve_mesh(trial)
    return NamedSharding(mesh, P(PIPE_AXIS))


def _pipeline_local(
    stage_params,
    batch,
    *,
    stage_fn: Callable,
    num_stages: int,
    num_microbatches: int,
    pipe_axis: str,
    vary_axes: tuple[str, ...],
):
    """Per-device body under shard_map.

    ``stage_params`` leaves arrive with a leading stage axis of local
    extent 1 (their global leading axis is sharded over ``pipe``);
    ``batch`` is this device's data shard, replicated across the pipe
    axis (every stage sees it; only stage 0 reads it).
    """
    my_params = jax.tree.map(lambda x: x[0], stage_params)
    stage_id = jax.lax.axis_index(pipe_axis)
    is_first = stage_id == 0
    is_last = stage_id == num_stages - 1

    n = batch.shape[0]
    mb = n // num_microbatches
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    # Probe the stage output shape once (abstractly — no FLOPs at runtime)
    # so the carry/output buffers can be allocated. Pipeline stages must
    # be shape-preserving in the activation (equal-width stages), the
    # standard GPipe restriction that makes the ppermute well-typed.
    out_aval = jax.eval_shape(stage_fn, my_params, micro[0])
    if out_aval.shape != micro[0].shape:
        raise ValueError(
            f"pipeline stages must preserve activation shape; stage maps "
            f"{micro[0].shape} -> {out_aval.shape}"
        )

    # Carries start as constants but become device-varying through the
    # loop (pipe via ppermute/axis_index, data via the batch shard —
    # but NOT model, over which stages are replicated); annotate up
    # front (shard_map VMA typing).
    from multidisttorch_tpu.parallel.collectives import pvary

    state0 = pvary(jnp.zeros(micro[0].shape, out_aval.dtype), vary_axes)
    out0 = pvary(jnp.zeros(micro.shape, out_aval.dtype), vary_axes)

    # Non-cyclic shift: stage i hands its activation to stage i+1; stage
    # S-1's send is dropped, stage 0 receives zeros (and ignores them).
    shift = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        state, outs = carry
        inj = micro[jnp.clip(t, 0, num_microbatches - 1)]
        x = jnp.where(is_first, inj.astype(state.dtype), state)
        y = stage_fn(my_params, x)
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(is_last, out_idx >= 0)
        slot = jnp.clip(out_idx, 0, num_microbatches - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, prev), slot, axis=0
        )
        state = jax.lax.ppermute(y, pipe_axis, shift)
        return (state, outs), None

    ticks = jnp.arange(num_microbatches + num_stages - 1)
    (_, outs), _ = jax.lax.scan(tick, (state0, out0), ticks)

    # Only the last stage holds real outputs; psum over the pipe axis
    # broadcasts them (everyone else contributes zeros), making the
    # result pipe-invariant so it can leave the shard_map replicated.
    outs = jax.lax.psum(jnp.where(is_last, outs, jnp.zeros_like(outs)), pipe_axis)
    return outs.reshape((n,) + outs.shape[2:])


def pipeline_apply(
    trial: TrialMesh | Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    num_microbatches: int,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Build a pipelined forward ``apply(stage_params, batch) -> out``.

    - ``stage_fn(params_one_stage, x) -> y`` is the per-stage compute; it
      must preserve the activation shape (equal-width stages).
    - ``stage_params`` is a pytree whose every leaf has leading axis
      ``num_stages``; place it with :func:`stage_params_sharding` so each
      pipe-axis device owns one stage.
    - ``batch`` has leading axis divisible by ``num_microbatches`` (per
      data shard, if the submesh also has a ``data`` axis).

    The returned function is pure and differentiable — wrap it in a loss
    and ``jax.grad``/``jax.jit`` exactly like any other forward. Under
    jit, GSPMD additionally reduces gradients over the ``data`` axis,
    giving DP x PP from one program.
    """
    mesh = _resolve_mesh(trial)
    if PIPE_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh has no '{PIPE_AXIS}' axis (axes: {tuple(mesh.shape)}); "
            "carve one with setup_groups(..., pipeline_parallel=S)"
        )
    num_stages = int(mesh.shape[PIPE_AXIS])
    if num_microbatches < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")
    has_data = DATA_AXIS in mesh.shape
    data_size = int(mesh.shape[DATA_AXIS]) if has_data else 1
    batch_spec = P(DATA_AXIS) if has_data else P()

    def apply(stage_params, batch):
        n_leading = jax.tree.leaves(stage_params)[0].shape[0]
        if n_leading != num_stages:
            raise ValueError(
                f"stage_params leading axis {n_leading} != pipe axis "
                f"extent {num_stages}"
            )
        shard_n, rem = divmod(batch.shape[0], data_size)
        if rem or shard_n % num_microbatches:
            raise ValueError(
                f"batch leading axis {batch.shape[0]} must divide into "
                f"{data_size} data shard(s) x {num_microbatches} "
                "microbatches of equal size"
            )
        return compat_shard_map(
            partial(
                _pipeline_local,
                stage_fn=stage_fn,
                num_stages=num_stages,
                num_microbatches=num_microbatches,
                pipe_axis=PIPE_AXIS,
                vary_axes=(
                    ((DATA_AXIS,) if has_data else ()) + (PIPE_AXIS,)
                ),
            ),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(PIPE_AXIS), stage_params), batch_spec),
            out_specs=batch_spec,
        )(stage_params, batch)

    return apply


def sequential_reference(stage_fn, stage_params, batch):
    """Single-device reference: run the stages back to back (for tests)."""
    x = batch
    num_stages = jax.tree.leaves(stage_params)[0].shape[0]
    for s in range(num_stages):
        x = stage_fn(jax.tree.map(lambda p: p[s], stage_params), x)
    return x


# --- shape-heterogeneous stages (real models) -------------------------------
#
# :func:`pipeline_apply` requires equal-width stages — fine for scan-over-
# layers transformer stacks, useless for the models this repo actually
# ships (a ResNet halves its spatial dims while doubling channels; a
# ConvVAE narrows to a latent bottleneck). The general SPMD form below
# lifts the restriction with two devices-run-one-program tricks:
#
# - **padded flat carry**: every activation travels between stages as a
#   ``(microbatch, A)`` float32 buffer, ``A`` = the widest per-sample
#   activation in the chain; each stage unpads/reshapes its true input
#   and re-pads its output. The ppermute stays well-typed because every
#   hop has the one static shape.
# - **lax.switch on the stage index**: stage bodies differ, but SPMD
#   needs one program — each device selects its own stage's branch with
#   its pipe-axis coordinate. Branch s statically unpacks stage s's
#   params from the packed row and runs its compute; control flow is a
#   device-local scalar conditional, so no collective may appear inside
#   a stage body (document-level contract, same as GPipe kernels).
# - **packed params**: per-stage param pytrees (different structures!)
#   flatten+concat+pad into one ``(S, Pmax)`` float32 array sharded over
#   ``pipe`` — each device physically holds only its own stage's row,
#   which is the memory point of pipeline parallelism. The optimizer
#   runs directly on the packed array (Adam is elementwise), so the
#   sharding survives training with zero extra machinery.


def pack_stage_params(stage_trees: Sequence[Any]) -> tuple[jax.Array, tuple]:
    """Pack per-stage param pytrees into one ``(S, Pmax)`` float32 array.

    Returns ``(packed, metas)``; place ``packed`` with
    :func:`stage_params_sharding` so each pipe device owns its row.
    ``metas`` is static unpack metadata for :func:`unpack_stage_params`
    and :func:`pipeline_apply_stages`.
    """
    metas, rows = [], []
    for tree in stage_trees:
        leaves, treedef = jax.tree.flatten(tree)
        for leaf in leaves:
            if leaf.dtype != jnp.float32:
                raise ValueError(
                    f"packed stage params must be float32, got {leaf.dtype} "
                    "(keep param_dtype=float32; compute dtype is the "
                    "stage_fn's business)"
                )
        metas.append((treedef, tuple(tuple(l.shape) for l in leaves)))
        rows.append(
            jnp.concatenate([jnp.ravel(l) for l in leaves])
            if leaves
            else jnp.zeros((0,), jnp.float32)
        )
    pmax = max((int(r.shape[0]) for r in rows), default=0)
    packed = jnp.stack([jnp.pad(r, (0, pmax - r.shape[0])) for r in rows])
    return packed, tuple(metas)


def unpack_stage_params(row: jax.Array, meta) -> Any:
    """Rebuild one stage's param pytree from its packed row (static
    slicing — safe inside a ``lax.switch`` branch)."""

    treedef, shapes = meta
    leaves, off = [], 0
    for shape in shapes:
        size = math.prod(shape)
        leaves.append(row[off : off + size].reshape(shape))
        off += size
    return jax.tree.unflatten(treedef, leaves)


def _pipeline_stages_local(
    packed_params,
    batch,
    *,
    stage_fns,
    metas,
    in_shapes,
    out_shape,
    width,
    num_stages,
    num_microbatches,
    pipe_axis,
    vary_axes,
):
    """Per-device body for heterogeneous stages (see module comment)."""
    from multidisttorch_tpu.parallel.collectives import pvary

    my_row = packed_params[0]  # this device's stage row, (Pmax,)
    stage_id = jax.lax.axis_index(pipe_axis)
    is_first = stage_id == 0
    is_last = stage_id == num_stages - 1

    n = batch.shape[0]
    mb = n // num_microbatches
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    def flat_pad(x):
        f = x.reshape(x.shape[0], -1).astype(jnp.float32)
        return jnp.pad(f, ((0, 0), (0, width - f.shape[1])))

    def make_branch(s):

        in_size = math.prod(in_shapes[s])

        def branch(row, buf):
            p = unpack_stage_params(row, metas[s])
            a = buf[:, :in_size].reshape((mb,) + in_shapes[s])
            return flat_pad(stage_fns[s](p, a))

        return branch

    branches = [make_branch(s) for s in range(num_stages)]

    state0 = pvary(jnp.zeros((mb, width), jnp.float32), vary_axes)
    out0 = pvary(
        jnp.zeros((num_microbatches, mb, width), jnp.float32), vary_axes
    )
    shift = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        state, outs = carry
        inj = flat_pad(micro[jnp.clip(t, 0, num_microbatches - 1)])
        x = jnp.where(is_first, inj, state)
        y = jax.lax.switch(stage_id, branches, my_row, x)
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(is_last, out_idx >= 0)
        slot = jnp.clip(out_idx, 0, num_microbatches - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, prev), slot, axis=0
        )
        state = jax.lax.ppermute(y, pipe_axis, shift)
        return (state, outs), None

    ticks = jnp.arange(num_microbatches + num_stages - 1)
    (_, outs), _ = jax.lax.scan(tick, (state0, out0), ticks)

    outs = jax.lax.psum(
        jnp.where(is_last, outs, jnp.zeros_like(outs)), pipe_axis
    )

    out_size = math.prod(out_shape)
    return outs[:, :, :out_size].reshape((n,) + out_shape)


def pipeline_apply_stages(
    trial: TrialMesh | Mesh,
    stage_fns: Sequence[Callable[[Any, jax.Array], jax.Array]],
    stage_params: Sequence[Any],
    *,
    num_microbatches: int,
) -> tuple[Callable[[Any, jax.Array], jax.Array], jax.Array]:
    """GPipe for **shape-heterogeneous** stages — real models.

    - ``stage_fns[s](params_s, x) -> y``: per-stage compute; input/output
      shapes may differ per stage (a conv stage may halve spatial dims,
      the last stage may emit class logits). Stage bodies must be
      collective-free (each device executes only its own branch).
    - ``stage_params[s]``: stage s's param pytree (float32 leaves;
      structures may differ per stage).

    Returns ``(apply, packed)``: place ``packed`` with
    :func:`stage_params_sharding`, then ``apply(packed, batch) -> out``
    is pure and differentiable — grad w.r.t. ``packed`` keeps the
    per-stage sharding, and an elementwise optimizer (Adam) applied to
    the packed array trains the pipeline directly. On a ``(data, pipe)``
    submesh GSPMD additionally reduces gradients over ``data``: DP x PP
    from one jitted program.
    """

    mesh = _resolve_mesh(trial)
    if PIPE_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh has no '{PIPE_AXIS}' axis (axes: {tuple(mesh.shape)}); "
            "carve one with setup_groups(..., pipeline_parallel=S)"
        )
    num_stages = int(mesh.shape[PIPE_AXIS])
    if len(stage_fns) != num_stages or len(stage_params) != num_stages:
        raise ValueError(
            f"{len(stage_fns)} stage_fns / {len(stage_params)} stage_params "
            f"for a pipe axis of extent {num_stages}"
        )
    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}"
        )
    has_data = DATA_AXIS in mesh.shape
    data_size = int(mesh.shape[DATA_AXIS]) if has_data else 1
    batch_spec = P(DATA_AXIS) if has_data else P()

    packed, metas = pack_stage_params(stage_params)
    param_avals = [
        jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), tree
        )
        for tree in stage_params
    ]

    def apply(packed_arr, batch):
        shard_n, rem = divmod(batch.shape[0], data_size)
        if rem or shard_n % num_microbatches:
            raise ValueError(
                f"batch leading axis {batch.shape[0]} must divide into "
                f"{data_size} data shard(s) x {num_microbatches} "
                "microbatches of equal size"
            )
        mb = shard_n // num_microbatches
        # Probe the stage shape chain abstractly (no FLOPs): stage s's
        # output shape is stage s+1's input shape.
        in_shapes = [tuple(batch.shape[1:])]
        for s in range(num_stages):
            out_aval = jax.eval_shape(
                stage_fns[s],
                param_avals[s],
                jax.ShapeDtypeStruct((mb,) + in_shapes[s], jnp.float32),
            )
            in_shapes.append(tuple(out_aval.shape[1:]))
        width = max(math.prod(s) for s in in_shapes)

        return compat_shard_map(
            partial(
                _pipeline_stages_local,
                stage_fns=tuple(stage_fns),
                metas=metas,
                in_shapes=tuple(in_shapes[:num_stages]),
                out_shape=in_shapes[num_stages],
                width=width,
                num_stages=num_stages,
                num_microbatches=num_microbatches,
                pipe_axis=PIPE_AXIS,
                vary_axes=(
                    ((DATA_AXIS,) if has_data else ()) + (PIPE_AXIS,)
                ),
            ),
            mesh=mesh,
            in_specs=(P(PIPE_AXIS), batch_spec),
            out_specs=batch_spec,
        )(packed_arr, batch)

    return apply, packed


def sequential_stages_reference(stage_fns, stage_params, batch):
    """Single-device reference for heterogeneous stages (for tests)."""
    x = batch
    for fn, p in zip(stage_fns, stage_params):
        x = fn(p, x)
    return x
