"""Pipeline parallelism: stage-sharded models via collective microbatching.

The reference framework is DP-only (SURVEY.md §2c — pipeline parallelism
is "absent from all 448 lines"), but a TPU framework at its scale must
let one trial's model exceed one chip. This module implements GPipe-style
pipeline parallelism the SPMD way: every device runs the *same* jitted
program under ``shard_map``; the stage dimension of the weights is
sharded over a ``pipe`` mesh axis, microbatches march through the stages
with non-cyclic ``jax.lax.ppermute`` neighbor hops (ICI-adjacent by
construction — see ``setup_groups(pipeline_parallel=...)``), and the
whole schedule is a single differentiable ``lax.scan``, so ``jax.grad``
of a loss on the pipeline output *is* the backward pipeline — no
hand-written backward schedule, no recompilation per stage.

Schedule: the classic GPipe fill/steady/drain loop — with M microbatches
and S stages, the scan runs ``M + S - 1`` ticks; stage 0 injects
microbatch ``t`` at tick ``t``, stage ``S-1`` emits microbatch
``t-(S-1)`` at tick ``t``. Bubble fraction ``(S-1)/(M+S-1)`` — pick
``num_microbatches >> num_stages`` to amortize, exactly as in the GPipe
paper. Composes with data parallelism: on a ``(data, pipe)`` submesh the
batch dimension is additionally sharded over ``data`` and XLA reduces
gradients over both axes.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multidisttorch_tpu.utils.compat import shard_map as compat_shard_map
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, TrialMesh


def _resolve_mesh(trial: TrialMesh | Mesh) -> Mesh:
    return trial.mesh if isinstance(trial, TrialMesh) else trial


def stage_params_sharding(trial: TrialMesh | Mesh) -> NamedSharding:
    """Sharding for stacked per-stage weights: leading (stage) axis split
    over the ``pipe`` mesh axis, so each device holds exactly its own
    stage's parameters."""
    mesh = _resolve_mesh(trial)
    return NamedSharding(mesh, P(PIPE_AXIS))


def _pipeline_local(
    stage_params,
    batch,
    *,
    stage_fn: Callable,
    num_stages: int,
    num_microbatches: int,
    pipe_axis: str,
    vary_axes: tuple[str, ...],
):
    """Per-device body under shard_map.

    ``stage_params`` leaves arrive with a leading stage axis of local
    extent 1 (their global leading axis is sharded over ``pipe``);
    ``batch`` is this device's data shard, replicated across the pipe
    axis (every stage sees it; only stage 0 reads it).
    """
    my_params = jax.tree.map(lambda x: x[0], stage_params)
    stage_id = jax.lax.axis_index(pipe_axis)
    is_first = stage_id == 0
    is_last = stage_id == num_stages - 1

    n = batch.shape[0]
    mb = n // num_microbatches
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    # Probe the stage output shape once (abstractly — no FLOPs at runtime)
    # so the carry/output buffers can be allocated. Pipeline stages must
    # be shape-preserving in the activation (equal-width stages), the
    # standard GPipe restriction that makes the ppermute well-typed.
    out_aval = jax.eval_shape(stage_fn, my_params, micro[0])
    if out_aval.shape != micro[0].shape:
        raise ValueError(
            f"pipeline stages must preserve activation shape; stage maps "
            f"{micro[0].shape} -> {out_aval.shape}"
        )

    # Carries start as constants but become device-varying through the
    # loop (pipe via ppermute/axis_index, data via the batch shard —
    # but NOT model, over which stages are replicated); annotate up
    # front (shard_map VMA typing).
    from multidisttorch_tpu.parallel.collectives import pvary

    state0 = pvary(jnp.zeros(micro[0].shape, out_aval.dtype), vary_axes)
    out0 = pvary(jnp.zeros(micro.shape, out_aval.dtype), vary_axes)

    # Non-cyclic shift: stage i hands its activation to stage i+1; stage
    # S-1's send is dropped, stage 0 receives zeros (and ignores them).
    shift = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        state, outs = carry
        inj = micro[jnp.clip(t, 0, num_microbatches - 1)]
        x = jnp.where(is_first, inj.astype(state.dtype), state)
        y = stage_fn(my_params, x)
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(is_last, out_idx >= 0)
        slot = jnp.clip(out_idx, 0, num_microbatches - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, prev), slot, axis=0
        )
        state = jax.lax.ppermute(y, pipe_axis, shift)
        return (state, outs), None

    ticks = jnp.arange(num_microbatches + num_stages - 1)
    (_, outs), _ = jax.lax.scan(tick, (state0, out0), ticks)

    # Only the last stage holds real outputs; psum over the pipe axis
    # broadcasts them (everyone else contributes zeros), making the
    # result pipe-invariant so it can leave the shard_map replicated.
    outs = jax.lax.psum(jnp.where(is_last, outs, jnp.zeros_like(outs)), pipe_axis)
    return outs.reshape((n,) + outs.shape[2:])


def pipeline_apply(
    trial: TrialMesh | Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    num_microbatches: int,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Build a pipelined forward ``apply(stage_params, batch) -> out``.

    - ``stage_fn(params_one_stage, x) -> y`` is the per-stage compute; it
      must preserve the activation shape (equal-width stages).
    - ``stage_params`` is a pytree whose every leaf has leading axis
      ``num_stages``; place it with :func:`stage_params_sharding` so each
      pipe-axis device owns one stage.
    - ``batch`` has leading axis divisible by ``num_microbatches`` (per
      data shard, if the submesh also has a ``data`` axis).

    The returned function is pure and differentiable — wrap it in a loss
    and ``jax.grad``/``jax.jit`` exactly like any other forward. Under
    jit, GSPMD additionally reduces gradients over the ``data`` axis,
    giving DP x PP from one program.
    """
    mesh = _resolve_mesh(trial)
    if PIPE_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh has no '{PIPE_AXIS}' axis (axes: {tuple(mesh.shape)}); "
            "carve one with setup_groups(..., pipeline_parallel=S)"
        )
    num_stages = int(mesh.shape[PIPE_AXIS])
    if num_microbatches < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")
    has_data = DATA_AXIS in mesh.shape
    data_size = int(mesh.shape[DATA_AXIS]) if has_data else 1
    batch_spec = P(DATA_AXIS) if has_data else P()

    def apply(stage_params, batch):
        n_leading = jax.tree.leaves(stage_params)[0].shape[0]
        if n_leading != num_stages:
            raise ValueError(
                f"stage_params leading axis {n_leading} != pipe axis "
                f"extent {num_stages}"
            )
        shard_n, rem = divmod(batch.shape[0], data_size)
        if rem or shard_n % num_microbatches:
            raise ValueError(
                f"batch leading axis {batch.shape[0]} must divide into "
                f"{data_size} data shard(s) x {num_microbatches} "
                "microbatches of equal size"
            )
        return compat_shard_map(
            partial(
                _pipeline_local,
                stage_fn=stage_fn,
                num_stages=num_stages,
                num_microbatches=num_microbatches,
                pipe_axis=PIPE_AXIS,
                vary_axes=(
                    ((DATA_AXIS,) if has_data else ()) + (PIPE_AXIS,)
                ),
            ),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(PIPE_AXIS), stage_params), batch_spec),
            out_specs=batch_spec,
        )(stage_params, batch)

    return apply


def sequential_reference(stage_fn, stage_params, batch):
    """Single-device reference: run the stages back to back (for tests)."""
    x = batch
    num_stages = jax.tree.leaves(stage_params)[0].shape[0]
    for s in range(num_stages):
        x = stage_fn(jax.tree.map(lambda p: p[s], stage_params), x)
    return x


# --- shape-heterogeneous stages (real models) -------------------------------
#
# :func:`pipeline_apply` requires equal-width stages — fine for scan-over-
# layers transformer stacks, useless for the models this repo actually
# ships (a ResNet halves its spatial dims while doubling channels; a
# ConvVAE narrows to a latent bottleneck). The general SPMD form below
# lifts the restriction with two devices-run-one-program tricks:
#
# - **padded flat carry**: every activation travels between stages as a
#   ``(microbatch, A)`` float32 buffer, ``A`` = the widest per-sample
#   activation in the chain; each stage unpads/reshapes its true input
#   and re-pads its output. The ppermute stays well-typed because every
#   hop has the one static shape.
# - **lax.switch on the stage index**: stage bodies differ, but SPMD
#   needs one program — each device selects its own stage's branch with
#   its pipe-axis coordinate. Branch s statically unpacks stage s's
#   params from the packed row and runs its compute; control flow is a
#   device-local scalar conditional, so no collective may appear inside
#   a stage body (document-level contract, same as GPipe kernels).
# - **packed params**: per-stage param pytrees (different structures!)
#   flatten+concat+pad into one ``(S, Pmax)`` float32 array sharded over
#   ``pipe`` — each device physically holds only its own stage's row,
#   which is the memory point of pipeline parallelism. The optimizer
#   runs directly on the packed array (Adam is elementwise), so the
#   sharding survives training with zero extra machinery.


def pack_stage_params(stage_trees: Sequence[Any]) -> tuple[jax.Array, tuple]:
    """Pack per-stage param pytrees into one ``(S, Pmax)`` float32 array.

    Returns ``(packed, metas)``; place ``packed`` with
    :func:`stage_params_sharding` so each pipe device owns its row.
    ``metas`` is static unpack metadata for :func:`unpack_stage_params`
    and :func:`pipeline_apply_stages`.
    """
    metas, rows = [], []
    for tree in stage_trees:
        leaves, treedef = jax.tree.flatten(tree)
        for leaf in leaves:
            if leaf.dtype != jnp.float32:
                raise ValueError(
                    f"packed stage params must be float32, got {leaf.dtype} "
                    "(keep param_dtype=float32; compute dtype is the "
                    "stage_fn's business)"
                )
        metas.append((treedef, tuple(tuple(l.shape) for l in leaves)))
        rows.append(
            jnp.concatenate([jnp.ravel(l) for l in leaves])
            if leaves
            else jnp.zeros((0,), jnp.float32)
        )
    pmax = max((int(r.shape[0]) for r in rows), default=0)
    packed = jnp.stack([jnp.pad(r, (0, pmax - r.shape[0])) for r in rows])
    return packed, tuple(metas)


def unpack_stage_params(row: jax.Array, meta) -> Any:
    """Rebuild one stage's param pytree from its packed row (static
    slicing — safe inside a ``lax.switch`` branch)."""

    treedef, shapes = meta
    leaves, off = [], 0
    for shape in shapes:
        size = math.prod(shape)
        leaves.append(row[off : off + size].reshape(shape))
        off += size
    return jax.tree.unflatten(treedef, leaves)


def _pipeline_stages_local(
    packed_params,
    batch,
    *,
    stage_fns,
    metas,
    in_shapes,
    out_shape,
    width,
    num_stages,
    num_microbatches,
    pipe_axis,
    vary_axes,
):
    """Per-device body for heterogeneous stages (see module comment)."""
    from multidisttorch_tpu.parallel.collectives import pvary

    my_row = packed_params[0]  # this device's stage row, (Pmax,)
    stage_id = jax.lax.axis_index(pipe_axis)
    is_first = stage_id == 0
    is_last = stage_id == num_stages - 1

    n = batch.shape[0]
    mb = n // num_microbatches
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    def flat_pad(x):
        f = x.reshape(x.shape[0], -1).astype(jnp.float32)
        return jnp.pad(f, ((0, 0), (0, width - f.shape[1])))

    def make_branch(s):

        in_size = math.prod(in_shapes[s])

        def branch(row, buf):
            p = unpack_stage_params(row, metas[s])
            a = buf[:, :in_size].reshape((mb,) + in_shapes[s])
            return flat_pad(stage_fns[s](p, a))

        return branch

    branches = [make_branch(s) for s in range(num_stages)]

    state0 = pvary(jnp.zeros((mb, width), jnp.float32), vary_axes)
    out0 = pvary(
        jnp.zeros((num_microbatches, mb, width), jnp.float32), vary_axes
    )
    shift = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        state, outs = carry
        inj = flat_pad(micro[jnp.clip(t, 0, num_microbatches - 1)])
        x = jnp.where(is_first, inj, state)
        y = jax.lax.switch(stage_id, branches, my_row, x)
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(is_last, out_idx >= 0)
        slot = jnp.clip(out_idx, 0, num_microbatches - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, prev), slot, axis=0
        )
        state = jax.lax.ppermute(y, pipe_axis, shift)
        return (state, outs), None

    ticks = jnp.arange(num_microbatches + num_stages - 1)
    (_, outs), _ = jax.lax.scan(tick, (state0, out0), ticks)

    outs = jax.lax.psum(
        jnp.where(is_last, outs, jnp.zeros_like(outs)), pipe_axis
    )

    out_size = math.prod(out_shape)
    return outs[:, :, :out_size].reshape((n,) + out_shape)


def pipeline_apply_stages(
    trial: TrialMesh | Mesh,
    stage_fns: Sequence[Callable[[Any, jax.Array], jax.Array]],
    stage_params: Sequence[Any],
    *,
    num_microbatches: int,
) -> tuple[Callable[[Any, jax.Array], jax.Array], jax.Array]:
    """GPipe for **shape-heterogeneous** stages — real models.

    - ``stage_fns[s](params_s, x) -> y``: per-stage compute; input/output
      shapes may differ per stage (a conv stage may halve spatial dims,
      the last stage may emit class logits). Stage bodies must be
      collective-free (each device executes only its own branch).
    - ``stage_params[s]``: stage s's param pytree (float32 leaves;
      structures may differ per stage).

    Returns ``(apply, packed)``: place ``packed`` with
    :func:`stage_params_sharding`, then ``apply(packed, batch) -> out``
    is pure and differentiable — grad w.r.t. ``packed`` keeps the
    per-stage sharding, and an elementwise optimizer (Adam) applied to
    the packed array trains the pipeline directly. On a ``(data, pipe)``
    submesh GSPMD additionally reduces gradients over ``data``: DP x PP
    from one jitted program.
    """

    mesh = _resolve_mesh(trial)
    if PIPE_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh has no '{PIPE_AXIS}' axis (axes: {tuple(mesh.shape)}); "
            "carve one with setup_groups(..., pipeline_parallel=S)"
        )
    num_stages = int(mesh.shape[PIPE_AXIS])
    if len(stage_fns) != num_stages or len(stage_params) != num_stages:
        raise ValueError(
            f"{len(stage_fns)} stage_fns / {len(stage_params)} stage_params "
            f"for a pipe axis of extent {num_stages}"
        )
    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}"
        )
    has_data = DATA_AXIS in mesh.shape
    data_size = int(mesh.shape[DATA_AXIS]) if has_data else 1
    batch_spec = P(DATA_AXIS) if has_data else P()

    packed, metas = pack_stage_params(stage_params)
    param_avals = [
        jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), tree
        )
        for tree in stage_params
    ]

    def apply(packed_arr, batch):
        shard_n, rem = divmod(batch.shape[0], data_size)
        if rem or shard_n % num_microbatches:
            raise ValueError(
                f"batch leading axis {batch.shape[0]} must divide into "
                f"{data_size} data shard(s) x {num_microbatches} "
                "microbatches of equal size"
            )
        mb = shard_n // num_microbatches
        # Probe the stage shape chain abstractly (no FLOPs): stage s's
        # output shape is stage s+1's input shape.
        in_shapes = [tuple(batch.shape[1:])]
        for s in range(num_stages):
            out_aval = jax.eval_shape(
                stage_fns[s],
                param_avals[s],
                jax.ShapeDtypeStruct((mb,) + in_shapes[s], jnp.float32),
            )
            in_shapes.append(tuple(out_aval.shape[1:]))
        width = max(math.prod(s) for s in in_shapes)

        return compat_shard_map(
            partial(
                _pipeline_stages_local,
                stage_fns=tuple(stage_fns),
                metas=metas,
                in_shapes=tuple(in_shapes[:num_stages]),
                out_shape=in_shapes[num_stages],
                width=width,
                num_stages=num_stages,
                num_microbatches=num_microbatches,
                pipe_axis=PIPE_AXIS,
                vary_axes=(
                    ((DATA_AXIS,) if has_data else ()) + (PIPE_AXIS,)
                ),
            ),
            mesh=mesh,
            in_specs=(P(PIPE_AXIS), batch_spec),
            out_specs=batch_spec,
        )(packed_arr, batch)

    return apply, packed


def sequential_stages_reference(stage_fns, stage_params, batch):
    """Single-device reference for heterogeneous stages (for tests)."""
    x = batch
    for fn, p in zip(stage_fns, stage_params):
        x = fn(p, x)
    return x


# --- cross-submesh MPMD pipeline parallelism ------------------------
#
# Everything above is SPMD pipelining: one mesh, one program, the stage
# dimension a mesh axis. The MPMD form (arXiv 2412.14374) drops both
# constraints: each stage owns its OWN submesh and runs its OWN
# compiled programs — a 2-stage trial is a *vector* of slice requests
# to the service scheduler (all-or-nothing multi-block placement,
# ``service/scheduler.py``), per-stage programs are first-class ``kind``s
# in the compile registry (``compile/programs.py``), and the host drives
# the classic GPipe fill/steady/drain schedule with explicit
# ``jax.device_put`` transfers carrying activations (forward) and
# cotangents (backward) between stage submeshes.
#
# Contract per stage:
#   - ``stage_fns[s](params_s, acts, rng) -> acts'`` for s < S-1, where
#     ``acts`` is a tuple of batch-major arrays (stage 0 receives
#     ``(batch,)``);
#   - ``last_fn(params_{S-1}, acts) -> loss`` (per-sample mean over the
#     microbatch) closes the chain.
# The backward pass is recompute-vjp per stage (GPipe's activation
# policy: only the stage INPUTS are stashed between phases; the vjp
# re-runs the stage forward), so per-stage programs are:
# fwd / last-forward (loss metric) / bwd (cotangent in, grads out) /
# last-bwd / update (per-stage Adam; optionally ZeRO-sharded over the
# stage submesh's data axis — ``parallel/fsdp.py``'s sharded-update
# composes per stage unchanged).
#
# Schedule: two phases of ``M + S - 1`` ticks each (forward fill/drain,
# then backward fill/drain), microbatch gradients accumulated in
# arrival order — the same ascending-microbatch summation as
# ``train.steps.accumulate_gradients``, which is what makes the
# single-mesh reference (:func:`make_mpmd_reference_step`) the parity
# anchor. Bubble fraction: each stage is busy 2M of the 2(M+S-1) ticks,
# so the schedule's idle fraction is (S-1)/(M+S-1) — the books record
# busy/idle per dispatch (a MEASURED schedule property, not the
# formula), and `bench.py --pipeline` gates the two against each other.


def make_vae_stage_fns(model, beta: float):
    """The flagship VAE as a 2-stage MPMD chain.

    Stage 0 (encoder + reparameterization): ``(x,) -> (z, mu, logvar,
    x_flat)`` — mu/logvar and the flattened input ride the activation
    tuple because the ELBO at the far end needs them. Stage 1 (decoder
    + loss): logits from z, per-sample-mean negative ELBO.

    The reparameterization draws ``eps = normal(rng, ...)`` from the
    microbatch's explicit key rather than flax's ``make_rng`` fold, so
    the same math composes unchanged into the single-mesh reference
    step (:func:`make_mpmd_reference_step`) — the parity contract is
    between the pipelined and un-pipelined execution of THIS forward,
    with identical per-microbatch noise by construction.

    Returns ``(stage_fns, last_fn, stage_param_keys)`` where
    ``stage_param_keys`` names each stage's top-level param modules
    (:func:`split_stage_params`).
    """
    from multidisttorch_tpu.ops.losses import elbo_loss_sum

    def encode_stage(params, acts, rng):
        (x,) = acts
        mu, logvar = model.apply({"params": params}, x, method="encode")
        eps = jax.random.normal(rng, mu.shape, dtype=jnp.float32).astype(
            mu.dtype
        )
        z = mu + eps * jnp.exp(0.5 * logvar)
        flat = x.reshape(x.shape[0], -1).astype(jnp.float32)
        return (z, mu, logvar, flat)

    def decode_loss_stage(params, acts):
        z, mu, logvar, flat = acts
        logits = model.apply({"params": params}, z, method="decode")
        m = flat.shape[0]
        return elbo_loss_sum(logits, flat, mu, logvar, beta) / m

    return [encode_stage], decode_loss_stage, (
        ("fc1", "fc21", "fc22"),
        ("fc3", "fc4"),
    )


def make_vae_stage_eval_fns(model, beta: float):
    """Posterior-mean eval split along the same 2-stage boundary:
    ``enc_eval(params0, batch) -> (mu, logvar, flat)`` on stage 0,
    ``dec_eval(params1, acts, weights) -> weighted loss_sum`` on the
    last stage — the pipelined sibling of the driver's masked
    ``make_eval_step``."""
    from multidisttorch_tpu.ops.losses import elbo_loss_weighted_sum

    def enc_eval(params, batch):
        mu, logvar = model.apply({"params": params}, batch, method="encode")
        flat = batch.reshape(batch.shape[0], -1).astype(jnp.float32)
        return (mu, logvar, flat)

    def dec_eval(params, acts, weights):
        mu, logvar, flat = acts
        logits = model.apply({"params": params}, mu, method="decode")
        return elbo_loss_weighted_sum(
            logits, flat, mu, logvar, weights, beta
        ).astype(jnp.float32)

    return enc_eval, dec_eval


def split_stage_params(params, stage_param_keys) -> list:
    """Split a full param tree into per-stage trees by top-level module
    name. The split is exact and disjoint — training the stage trees
    with per-stage Adam is elementwise-identical to training the full
    tree (Adam has no cross-leaf coupling)."""
    seen = [k for keys in stage_param_keys for k in keys]
    if sorted(seen) != sorted(params):
        raise ValueError(
            f"stage split {stage_param_keys} does not partition the "
            f"param tree {sorted(params)}"
        )
    return [{k: params[k] for k in keys} for keys in stage_param_keys]


def merge_stage_params(stage_trees) -> dict:
    """Inverse of :func:`split_stage_params` (checkpoint export, PBT
    exchange across pipelined trials)."""
    out: dict = {}
    for tree in stage_trees:
        out.update(tree)
    return out


def analytic_bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """The GPipe schedule model: idle fraction (S-1)/(S-1+M)."""
    s, m = int(num_stages), int(num_microbatches)
    return (s - 1) / (s - 1 + m) if s > 1 else 0.0


def _tree_bytes(tree) -> int:
    return sum(
        int(leaf.size) * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree)
    )


def _avals_of(tree):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype), tree
    )


class MpmdPipeline:
    """One pipelined trial: S stages on S distinct submeshes.

    Owns per-stage :class:`~multidisttorch_tpu.train.steps.TrainState`s
    and compiled programs, and drives the GPipe microbatch schedule
    with ``device_put`` transfers between stage submeshes. Single
    controller (the service daemon's world); per-stage programs compile
    through the process-lifetime executable registry when
    ``registry_keys`` are supplied (retries and bucket-twin trials
    never recompile a stage).

    ``zero_update=True`` additionally places each stage's optimizer
    state ZeRO-sharded over that stage submesh's data axis
    (``parallel.fsdp.place_zero_state``) — pipeline parallelism across
    submeshes, data parallelism + sharded weight update within each.
    """

    def __init__(
        self,
        stages: Sequence,  # [TrialMesh, ...]
        stage_fns: Sequence[Callable],
        last_fn: Callable,
        stage_params: Sequence[Any],
        *,
        lr: float,
        microbatches: int,
        zero_update: bool = False,
        registry_keys: Optional[dict] = None,
        eval_fns: Optional[tuple] = None,
    ):
        import optax

        from multidisttorch_tpu.parallel.fsdp import place_zero_state
        from multidisttorch_tpu.train.steps import TrainState

        self.stages = list(stages)
        S = self.S = len(self.stages)
        if S < 2:
            raise ValueError(
                f"an MPMD pipeline needs >= 2 stages, got {S} (a 1-stage "
                "trial is a plain submesh trial)"
            )
        if len(stage_fns) != S - 1 or len(stage_params) != S:
            raise ValueError(
                f"{len(stage_fns)} stage_fns / {len(stage_params)} "
                f"stage_params for {S} stages (need S-1 fns + last_fn)"
            )
        self.M = int(microbatches)
        if self.M < 1:
            raise ValueError(f"microbatches must be >= 1, got {self.M}")
        self._stage_fns = list(stage_fns)
        self._last_fn = last_fn
        self._tx = optax.adam(float(lr))
        self.zero_update = bool(zero_update)

        # Per-stage states: split-tree Adam — elementwise-identical to
        # full-tree Adam on the merged params.
        self.states = []
        self.state_shardings = []
        for trial, p in zip(self.stages, stage_params):
            st = TrainState(
                params=p,
                opt_state=self._tx.init(p),
                step=jnp.zeros((), jnp.int32),
            )
            if self.zero_update and trial.data_size > 1:
                st, sh = place_zero_state(trial, st)
            else:
                st = trial.device_put(st)
                sh = jax.tree.map(lambda _: trial.replicated_sharding, st)
            self.states.append(st)
            self.state_shardings.append(sh)

        self._build_programs(registry_keys or {}, eval_fns)

        # Schedule books: busy/idle measured at dispatch time.
        self.books = {
            "steps": 0,
            "ticks": 0,
            "busy": 0,
            "stage_busy": [0] * S,
            "transfers": 0,
            "transfer_bytes": 0,
        }
        # First-step argument SHAPES per program — the device cost
        # books' input (telemetry/device.record_pipeline_cost); shapes
        # only, so donated buffers are never retained.
        self.cost_args: dict = {}

    # -- program construction ----------------------------------------

    def _registry_compile(self, key, jit_fn, avals):
        """Compile one stage program through the executable registry
        (one ``lower→compile`` per (kind, bucket, stage, submesh) ever;
        concurrent same-key callers coalesce). Falls back to the plain
        jit fn on any registry failure — MPMD execution must not hinge
        on the compile subsystem."""
        if key is None:
            return jit_fn
        try:
            from multidisttorch_tpu.compile.registry import (
                READY,
                SOURCE_INLINE,
                get_executable_registry,
            )

            reg = get_executable_registry()
            ex = reg.take(key)
            if ex is not None:
                return ex
            if reg.claim(key):
                e = reg.compile_now(
                    key, jit_fn, avals, source=SOURCE_INLINE
                )
                if e.status == READY:
                    ex = reg.take(key)
                    if ex is not None:
                        return ex
        except Exception:  # noqa: BLE001 — registry is an optimization
            pass
        return jit_fn

    def _build_programs(self, keys: dict, eval_fns) -> None:
        S, M = self.S, self.M
        self._fwd = [None] * S
        self._bwd = [None] * S
        self._update = [None] * S

        # Probe the activation shape chain abstractly: stage s's output
        # avals are stage s+1's input avals. Shapes are per-MICROBATCH.
        p_avals = [
            jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), st.params
            )
            for st in self.states
        ]
        rng_aval = jax.eval_shape(lambda: jax.random.key(0))
        self._acts_avals: list = [None] * S  # input acts per stage

        for s in range(S):
            trial = self.stages[s]
            repl = trial.replicated_sharding
            batch_sh = trial.batch_sharding
            if s < S - 1:
                fn = self._stage_fns[s]

                def fwd(params, acts, rng, _fn=fn):
                    return _fn(params, acts, rng)

                def bwd(params, acts, rng, cot, _fn=fn):
                    _, vjp = jax.vjp(
                        lambda p, a: _fn(p, a, rng), params, acts
                    )
                    gp, ga = vjp(cot)
                    return ga, gp

                self._fwd[s] = jax.jit(
                    fwd,
                    in_shardings=(
                        self.state_shardings[s].params, batch_sh, repl
                    ),
                    out_shardings=batch_sh,
                )
                self._bwd[s] = jax.jit(
                    bwd,
                    in_shardings=(
                        self.state_shardings[s].params, batch_sh, repl,
                        batch_sh,
                    ),
                    out_shardings=(batch_sh, repl),
                )
            else:
                last = self._last_fn

                def last_fwd(params, acts, _fn=last):
                    return _fn(params, acts)

                def last_bwd(params, acts, _fn=last):
                    gp, ga = jax.grad(_fn, argnums=(0, 1))(params, acts)
                    return ga, gp

                self._fwd[s] = jax.jit(
                    last_fwd,
                    in_shardings=(
                        self.state_shardings[s].params, batch_sh
                    ),
                    out_shardings=repl,
                )
                self._bwd[s] = jax.jit(
                    last_bwd,
                    in_shardings=(
                        self.state_shardings[s].params, batch_sh
                    ),
                    out_shardings=(batch_sh, repl),
                )

            def update(st, gsum, _tx=self._tx, _M=M):
                from multidisttorch_tpu.train.steps import TrainState

                grads = jax.tree.map(lambda g: g / _M, gsum)
                updates, new_opt = _tx.update(
                    grads, st.opt_state, st.params
                )
                import optax as _optax

                new_params = _optax.apply_updates(st.params, updates)
                return TrainState(
                    params=new_params, opt_state=new_opt, step=st.step + 1
                )

            self._update[s] = jax.jit(
                update,
                in_shardings=(self.state_shardings[s], repl),
                out_shardings=self.state_shardings[s],
                donate_argnums=(0,),
            )

        # Registry admission (timed, attributed, shared): needs concrete
        # avals, which depend on the microbatch shape — resolved on
        # first step via _admit_programs.
        self._keys = dict(keys)
        self._admitted = False
        self._p_avals = p_avals
        self._rng_aval = rng_aval

        # Eval programs (posterior-mean, masked): forward-only chain.
        self._eval_enc = self._eval_dec = None
        if eval_fns is not None:
            enc_eval, dec_eval = eval_fns
            first, last_m = self.stages[0], self.stages[-1]
            self._eval_enc = jax.jit(
                enc_eval,
                in_shardings=(
                    self.state_shardings[0].params, first.batch_sharding
                ),
                out_shardings=first.batch_sharding,
            )
            self._eval_dec = jax.jit(
                dec_eval,
                in_shardings=(
                    self.state_shardings[-1].params,
                    last_m.batch_sharding,
                    last_m.batch_sharding,
                ),
                out_shardings=last_m.replicated_sharding,
            )

    def _admit_programs(self, mb_shape, batch_dtype) -> None:
        """First-step registry admission: with the microbatch shape
        known, derive each stage program's avals and route the jit fns
        through the executable registry (one compile per program key
        ever — a retried/re-placed trial's stages come back as
        ``cache_hit``s)."""
        if self._admitted:
            return
        self._admitted = True
        S = self.S
        acts_aval = (jax.ShapeDtypeStruct(mb_shape, batch_dtype),)
        for s in range(S):
            self._acts_avals[s] = acts_aval
            if s < S - 1:
                out_aval = jax.eval_shape(
                    self._stage_fns[s],
                    self._p_avals[s],
                    acts_aval,
                    self._rng_aval,
                )
                self._fwd[s] = self._registry_compile(
                    self._keys.get(("fwd", s)),
                    self._fwd[s],
                    (self._p_avals[s], acts_aval, self._rng_aval),
                )
                self._bwd[s] = self._registry_compile(
                    self._keys.get(("bwd", s)),
                    self._bwd[s],
                    (
                        self._p_avals[s], acts_aval, self._rng_aval,
                        out_aval,
                    ),
                )
                acts_aval = out_aval
            else:
                self._fwd[s] = self._registry_compile(
                    self._keys.get(("fwd", s)),
                    self._fwd[s],
                    (self._p_avals[s], acts_aval),
                )
                self._bwd[s] = self._registry_compile(
                    self._keys.get(("bwd", s)),
                    self._bwd[s],
                    (self._p_avals[s], acts_aval),
                )
            state_aval = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                self.states[s],
            )
            gsum_aval = self._p_avals[s]
            self._update[s] = self._registry_compile(
                self._keys.get(("update", s)),
                self._update[s],
                (state_aval, gsum_aval),
            )

    # -- the schedule -------------------------------------------------

    def _transfer(self, tree, trial) -> Any:
        """One inter-stage hop: place the activation/cotangent tuple on
        the destination stage's submesh, batch-sharded over its data
        axis."""
        self.books["transfers"] += 1
        self.books["transfer_bytes"] += _tree_bytes(tree)
        return jax.device_put(tree, trial.batch_sharding)

    def step(self, batch, rng) -> dict:
        """One optimizer step: M microbatches through the two-phase
        GPipe schedule, per-stage gradient accumulation, per-stage
        update. ``batch`` lives on stage 0's submesh; ``rng`` is the
        step key (split into per-microbatch keys exactly like
        ``accumulate_gradients``'s caller). Returns
        ``{"loss_sum": <async device scalar on the last stage>}``."""
        M, S = self.M, self.S
        n = int(batch.shape[0])
        if n % M:
            raise ValueError(
                f"batch size {n} not divisible by microbatches={M}"
            )
        mb = n // M
        self._admit_programs((mb,) + tuple(batch.shape[1:]), batch.dtype)
        rngs = jax.random.split(rng, M)
        # Per-stage copies of the microbatch keys (the recompute-vjp
        # backward needs the stage's forward noise).
        stage_rngs = [
            [
                jax.device_put(rngs[m], self.stages[s].replicated_sharding)
                for m in range(M)
            ]
            for s in range(S - 1)
        ]
        stash: list = [[None] * M for _ in range(S)]
        cot: list = [[None] * M for _ in range(S)]
        gsum: list = [None] * S
        losses = []
        books = self.books
        ticks = M + S - 1

        # Forward phase: stage s runs microbatch t-s at tick t; output
        # transfers to stage s+1's submesh. Dispatches are async — the
        # host enqueues the whole tick and moves on; XLA's dependency
        # order IS the pipeline.
        for t in range(ticks):
            books["ticks"] += 1
            for s in range(S):
                m = t - s
                if not (0 <= m < M):
                    continue
                books["busy"] += 1
                books["stage_busy"][s] += 1
                if s == 0:
                    # Re-pin the slice's sharding: a sliced sharded
                    # array comes back with whatever layout XLA chose,
                    # and the stage program's in_shardings are exact.
                    acts = jax.device_put(
                        (batch[m * mb:(m + 1) * mb],),
                        self.stages[0].batch_sharding,
                    )
                else:
                    acts = stash[s][m]
                if s < S - 1:
                    args = (self.states[s].params, acts, stage_rngs[s][m])
                    out = self._fwd[s](*args)
                    stash[s][m] = acts
                    stash[s + 1][m] = self._transfer(
                        out, self.stages[s + 1]
                    )
                else:
                    args = (self.states[s].params, acts)
                    losses.append(self._fwd[s](*args))
                    stash[s][m] = acts
                if books["steps"] == 0 and m == 0:
                    self.cost_args[("fwd", s)] = _avals_of(args)

        # Backward phase: microbatch m starts at the LAST stage and
        # cotangents hop backward; per-stage grads accumulate in
        # ascending-m order (the accumulate_gradients order — parity).
        for t in range(ticks):
            books["ticks"] += 1
            for s in reversed(range(S)):
                m = t - (S - 1 - s)
                if not (0 <= m < M):
                    continue
                books["busy"] += 1
                books["stage_busy"][s] += 1
                if s == S - 1:
                    args = (self.states[s].params, stash[s][m])
                else:
                    args = (
                        self.states[s].params,
                        stash[s][m],
                        stage_rngs[s][m],
                        cot[s][m],
                    )
                if books["steps"] == 0 and m == 0:
                    self.cost_args[("bwd", s)] = _avals_of(args)
                cot_in, gp = self._bwd[s](*args)
                gsum[s] = (
                    gp
                    if gsum[s] is None
                    else jax.tree.map(jnp.add, gsum[s], gp)
                )
                if s > 0:
                    cot[s - 1][m] = self._transfer(
                        cot_in, self.stages[s - 1]
                    )
                stash[s][m] = None

        for s in range(S):
            if books["steps"] == 0:
                self.cost_args[("update", s)] = _avals_of(
                    (self.states[s], gsum[s])
                )
            self.states[s] = self._update[s](self.states[s], gsum[s])
        books["steps"] += 1

        loss_mean = losses[0]
        for extra in losses[1:]:
            loss_mean = loss_mean + extra
        loss_mean = loss_mean / M
        return {"loss_sum": (loss_mean * n).astype(jnp.float32)}

    def eval_batch(self, batch, weights):
        """Masked posterior-mean eval of one padded batch: encode on
        stage 0, one transfer, decode+loss on the last stage. Returns
        the weighted ``loss_sum`` (async device scalar)."""
        if self._eval_enc is None:
            raise ValueError("pipeline built without eval_fns")
        acts = self._eval_enc(self.states[0].params, batch)
        acts = self._transfer(acts, self.stages[-1])
        w = jax.device_put(weights, self.stages[-1].batch_sharding)
        return self._eval_dec(self.states[-1].params, acts, w)

    # -- books --------------------------------------------------------

    def measured_bubble(self) -> Optional[float]:
        """Idle fraction of the schedule actually driven: 1 −
        busy-dispatches / (S × ticks), counted per dispatch as the
        host loop runs. Gated against
        :func:`analytic_bubble_fraction` — and be precise about what
        that gate pins: a correctly-driven loop yields the analytic
        value BY CONSTRUCTION, so the gate is a schedule-STRUCTURE
        regression guard (wrong tick set, a skipped or double-driven
        stage, a mis-sized phase), not a device-overlap measurement.
        Wall-clock overlap across stages — the bubble a chip actually
        pays — needs real parallel hardware; the standing MFU caveat
        applies until open item 5's TPU run."""
        if self.books["ticks"] == 0:
            return None
        return 1.0 - self.books["busy"] / (self.S * self.books["ticks"])

    def schedule_books(self) -> dict:
        return {
            **{
                k: (list(v) if isinstance(v, list) else v)
                for k, v in self.books.items()
            },
            "stages": self.S,
            "microbatches": self.M,
            "measured_bubble": self.measured_bubble(),
            "analytic_bubble": analytic_bubble_fraction(self.S, self.M),
            "zero_update": self.zero_update,
        }

    def cost_parts(self) -> list:
        """The device cost books' input
        (``telemetry.device.record_pipeline_cost``): every per-stage
        program with its first-step arg shapes, stage devices, and
        per-optimizer-step multiplicity (forward/backward run once per
        microbatch, the update once). Empty before the first step."""
        fns = {"fwd": self._fwd, "bwd": self._bwd, "update": self._update}
        parts = []
        for s in range(self.S):
            for which, mult in (
                ("fwd", self.M), ("bwd", self.M), ("update", 1),
            ):
                args = self.cost_args.get((which, s))
                if args is None:
                    return []
                parts.append(
                    (fns[which][s], args, self.stages[s].devices, mult)
                )
        return parts

    def optimizer_state_bytes(self) -> dict:
        """Summed per-stage optimizer books (``parallel.fsdp``'s
        analytic accounting): what one device of each stage holds, and
        the replicated-equivalent total."""
        from multidisttorch_tpu.parallel.fsdp import optimizer_state_bytes

        per_dev = 0
        total = 0
        for st in self.states:
            b = optimizer_state_bytes(st)
            per_dev += b["per_device_bytes"]
            total += b["total_bytes"]
        return {"per_device_bytes": per_dev, "total_bytes": total}


def make_mpmd_reference_step(
    trial,
    stage_fns: Sequence[Callable],
    last_fn: Callable,
    tx,
    *,
    microbatches: int,
):
    """The single-mesh parity anchor for an MPMD pipeline: the SAME
    stage chain and the SAME per-microbatch keys, composed into one
    jitted step on one submesh with scan-based gradient accumulation
    (``train.steps.accumulate_gradients`` — ascending-microbatch
    summation, the pipeline's order). ``bench.py --pipeline`` gates the
    pipelined trial's losses against this step's.

    Returns ``step(state, batch, rng) -> (state, {"loss_sum"})`` with
    the driver's metric contract (summed loss over the batch).
    """
    import optax

    from multidisttorch_tpu.train.steps import (
        TrainState,
        accumulate_gradients,
    )

    M = int(microbatches)

    def micro_loss(params, mb_batch, mb_rng):
        acts = (mb_batch,)
        for fn in stage_fns:
            acts = fn(params, acts, mb_rng)
        return last_fn(params, acts)

    def step_fn(state: TrainState, batch, rng):
        n = batch.shape[0]
        if M == 1:
            loss, grads = jax.value_and_grad(micro_loss)(
                state.params, batch, rng
            )
        else:
            loss, _, grads = accumulate_gradients(
                trial,
                lambda p, mbb, r: (micro_loss(p, mbb, r), ()),
                state.params,
                (batch,),
                (jax.random.split(rng, M),),
                grad_accum=M,
            )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        return new_state, {"loss_sum": (loss * n).astype(jnp.float32)}

    repl = trial.replicated_sharding
    return jax.jit(
        step_fn,
        in_shardings=(repl, trial.batch_sharding, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )
