"""Cluster-environment detection and distributed-runtime bring-up.

TPU-native replacement for the reference's launcher/rendezvous layer
(``/root/reference/utils.py:9-144``). The reference must (a) learn its
world size/rank from MPI or SLURM env vars, (b) elect a rendezvous master
host, (c) pin a NIC for the Gloo transport, and (d) run a TCP rendezvous
via ``dist.init_process_group``. On TPU none of that machinery survives:
devices are addressed through ``jax.devices()``, and multi-host jobs need
only ``jax.distributed.initialize`` (which itself autodetects TPU
metadata). What *does* carry over is the launcher-env detection contract —
the same jobs the reference runs under (mpirun/jsrun on Summit-likes,
srun on SLURM clusters) must be recognized here, so every env-var
priority chain from the reference is preserved, with honest error
handling instead of the reference's dead ``except KeyError`` fallback
(``utils.py:141-142``, quirk Q8 in SURVEY.md).
"""

from __future__ import annotations

import os
import re
import socket
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ProcessEnv:
    """Launcher-provided process coordinates, before runtime init.

    Mirrors the return contract of ``init_comm_size_and_rank``
    (``/root/reference/utils.py:9-26``): ``(1, 0)`` when no launcher env
    is present (sequential mode). ``source`` records which detector won.
    """

    num_processes: int
    process_id: int
    source: str  # "openmpi" | "slurm" | "tpu" | "jax" | "local"


def detect_process_env(environ: Optional[dict] = None) -> ProcessEnv:
    """Detect world size / rank from the launcher environment.

    Priority chain extends the reference's (``utils.py:13-24``):
    OpenMPI (Summit-style ``OMPI_COMM_WORLD_*``) → SLURM
    (``SLURM_NPROCS``/``SLURM_PROCID``) → Cloud TPU multi-host env
    (``TPU_WORKER_ID`` + ``TPU_WORKER_HOSTNAMES``) → generic JAX
    coordinates (``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``) → local
    single-process fallback ``(1, 0)``.
    """
    env = os.environ if environ is None else environ

    if env.get("OMPI_COMM_WORLD_SIZE") and env.get("OMPI_COMM_WORLD_RANK"):
        return ProcessEnv(
            int(env["OMPI_COMM_WORLD_SIZE"]),
            int(env["OMPI_COMM_WORLD_RANK"]),
            "openmpi",
        )
    if env.get("SLURM_NPROCS") and env.get("SLURM_PROCID"):
        return ProcessEnv(
            int(env["SLURM_NPROCS"]), int(env["SLURM_PROCID"]), "slurm"
        )
    if env.get("TPU_WORKER_ID") and env.get("TPU_WORKER_HOSTNAMES"):
        hostnames = [h for h in env["TPU_WORKER_HOSTNAMES"].split(",") if h]
        return ProcessEnv(len(hostnames), int(env["TPU_WORKER_ID"]), "tpu")
    if env.get("JAX_NUM_PROCESSES") and env.get("JAX_PROCESS_ID"):
        return ProcessEnv(
            int(env["JAX_NUM_PROCESSES"]), int(env["JAX_PROCESS_ID"]), "jax"
        )
    return ProcessEnv(1, 0, "local")


# Matches one hostlist block: a prefix optionally followed by a bracketed
# index group, e.g. "or-condo-g[05,07-08,13]" or a bare "or-condo-g04".
_BLOCK_RE = re.compile(r"([\w-]+(?:\[[\d,\-]+\])?)")
_BRACKET_RE = re.compile(r"^(?P<prefix>[\w\-]+)\[(?P<indices>[\d,\-]+)\]$")
_RANGE_RE = re.compile(r"^(\d+)-(\d+)$")


def parse_slurm_nodelist(nodelist: str) -> list[str]:
    """Expand a SLURM compressed nodelist into an explicit host list.

    Behavioral parity with ``/root/reference/utils.py:59-90`` (same
    accepted grammar, same zero-padding preservation): e.g.
    ``"or-condo-g[05,07-08,13],or-condo-h[01,12]"`` expands to
    ``["or-condo-g05", "or-condo-g07", "or-condo-g08", "or-condo-g13",
    "or-condo-h01", "or-condo-h12"]``. The first element is used as the
    coordinator host (the reference used it as the rendezvous master,
    ``utils.py:117-119``).
    """
    hosts: list[str] = []
    for block in _BLOCK_RE.findall(nodelist):
        m = _BRACKET_RE.match(block)
        if m is None:
            hosts.append(block)
            continue
        prefix = m.group("prefix")
        for piece in m.group("indices").split(","):
            rng = _RANGE_RE.match(piece)
            if rng is None:
                hosts.append(prefix + piece)
            else:
                lo, hi = rng.groups()
                width = len(lo)
                hosts.extend(
                    f"{prefix}{i:0{width}d}" for i in range(int(lo), int(hi) + 1)
                )
    return hosts


def coordinator_address(environ: Optional[dict] = None, port: Optional[int] = None) -> str:
    """Elect the coordinator host:port for ``jax.distributed.initialize``.

    Preserves the reference's master-address priority chain
    (``/root/reference/utils.py:108-119``): ``LSB_HOSTS`` token [1]
    (Summit jsrun) → ``LSB_MCPU_HOSTS`` token [2] → first host of the
    expanded ``SLURM_NODELIST`` → ``MASTER_ADDR`` env → ``127.0.0.1``.
    Port comes from the ``port`` argument, then ``MASTER_PORT``, then the
    reference's default 8889 (``utils.py:109``).
    """
    env = os.environ if environ is None else environ

    if env.get("LSB_HOSTS") is not None:
        host = env["LSB_HOSTS"].split()[1]
    elif env.get("LSB_MCPU_HOSTS") is not None:
        host = env["LSB_MCPU_HOSTS"].split()[2]
    elif env.get("SLURM_NODELIST"):
        nodes = parse_slurm_nodelist(env["SLURM_NODELIST"])
        if not nodes:
            raise ValueError(
                f"SLURM_NODELIST={env['SLURM_NODELIST']!r} parsed to an "
                "empty host list"
            )
        host = nodes[0]
    else:
        host = env.get("MASTER_ADDR", "127.0.0.1")

    resolved_port = port if port is not None else int(env.get("MASTER_PORT", "8889"))
    return f"{host}:{resolved_port}"


def find_ifname(address: str) -> Optional[str]:
    """Resolve an IP/hostname to the local NIC name carrying it.

    Parity helper for ``/root/reference/utils.py:40-56``. The reference
    needs this to pin Gloo's TCP transport to the right NIC
    (``GLOO_SOCKET_IFNAME``, ``utils.py:128-131``); a TPU runtime has no
    transport to pin (ICI/DCN routing is XLA's job), so this survives
    only as a diagnostics helper for debugging DCN/host networking.
    Returns ``None`` if no local NIC owns the address or psutil is
    unavailable.
    """
    try:
        import psutil
    except ImportError:
        return None
    try:
        ipaddr = socket.gethostbyname(address)
    except socket.gaierror:
        return None
    for nic, addrs in psutil.net_if_addrs().items():
        for addr in addrs:
            if addr.address == ipaddr:
                return nic
    return None


def select_platform(
    environ: Optional[dict] = None, default: Optional[str] = None
) -> Optional[str]:
    """Honor the ``MDT_PLATFORM`` backend override; returns it (or None).

    The operator's escape hatch, mirroring the reference's
    ``DDP_BACKEND`` env override (``/root/reference/utils.py:96-97``)
    which forces a torch backend ahead of autodetection. Here the
    analogous knob forces the JAX platform (``cpu``/``tpu``/a plugin
    name) *before* backend initialization — e.g. ``MDT_PLATFORM=cpu``
    keeps a job off a wedged TPU plugin entirely. An empty/unset var
    means "no override" (falls back to ``default``, usually None).

    Must be called before anything touches a JAX backend: raises an
    honest error — *without* mutating global config — if the backend
    already initialized to a different platform (``jax.config.update``
    silently ignores late changes, so pretending would mask the no-op).
    """
    env = os.environ if environ is None else environ
    platform = env.get("MDT_PLATFORM") or default
    if not platform:
        return None
    import jax

    try:
        from jax._src import xla_bridge

        already_initialized = bool(xla_bridge._backends)
    except Exception:
        # Private probe gone (jax upgrade): we can no longer tell whether
        # a late override would silently no-op. Say so instead of
        # guessing — the whole point of this knob is no silent no-ops.
        import warnings

        warnings.warn(
            "cannot verify JAX backend-init state (jax internals moved); "
            f"MDT_PLATFORM={platform!r} may silently not take effect if "
            "a backend was already initialized",
            RuntimeWarning,
            stacklevel=2,
        )
        already_initialized = False
    if already_initialized:
        if jax.default_backend() != platform.split(",")[0]:
            raise RuntimeError(
                f"MDT_PLATFORM={platform!r} requested but the JAX backend "
                f"already initialized as {jax.default_backend()!r}; set "
                "the override before first device use"
            )
        return platform  # already effective; nothing to change
    jax.config.update("jax_platforms", platform)
    return platform


_initialized_env: Optional[ProcessEnv] = None


def initialize_runtime(
    coordinator: Optional[str] = None,
    environ: Optional[dict] = None,
) -> tuple[int, int]:
    """Bring up the distributed runtime; returns ``(num_processes, process_id)``.

    TPU-native replacement for ``setup_ddp`` (``/root/reference/
    utils.py:93-144``). Differences by design:

    - No backend selection: there is no NCCL/Gloo choice to make — XLA
      emits ICI/DCN collectives directly. (Reference: ``utils.py:96-103``.)
    - No env-var exports, no rendezvous server, no NIC pinning
      (reference: ``utils.py:122-131``): single-process jobs need nothing
      at all, multi-process jobs need one ``jax.distributed.initialize``
      call with the coordinator elected by :func:`coordinator_address`.
    - Honest errors (fixes quirk Q8, ``utils.py:141-142``): failures from
      ``jax.distributed.initialize`` propagate instead of being silently
      downgraded to "sequential mode".

    Safe to call more than once; subsequent calls return the cached
    coordinates (mirroring the reference's ``is_initialized()`` guard,
    ``utils.py:138``).
    """
    global _initialized_env
    if _initialized_env is not None:
        return _initialized_env.num_processes, _initialized_env.process_id

    select_platform(environ)
    penv = detect_process_env(environ)
    if penv.num_processes > 1:
        import jax

        env = os.environ if environ is None else environ
        env_elects_master = any(
            env.get(k) is not None
            for k in ("LSB_HOSTS", "LSB_MCPU_HOSTS", "SLURM_NODELIST", "MASTER_ADDR")
        )
        if coordinator is None and penv.source == "tpu" and not env_elects_master:
            # Cloud TPU pods publish coordinator metadata JAX already
            # knows how to read; none of the reference's master-election
            # env vars (LSB_*/SLURM_*/MASTER_ADDR) exist there, so the
            # elected fallback would be 127.0.0.1 — wrong on every
            # non-zero worker. Let JAX autodetect instead.
            jax.distributed.initialize()
        elif coordinator is None and penv.source == "jax" and not env_elects_master:
            # Generic JAX coordinates: respect JAX_COORDINATOR_ADDRESS
            # (JAX reads it only when coordinator_address is None) rather
            # than electing a 127.0.0.1 fallback on every worker.
            jax.distributed.initialize(
                coordinator_address=None,
                num_processes=penv.num_processes,
                process_id=penv.process_id,
            )
        else:
            jax.distributed.initialize(
                coordinator_address=(
                    coordinator
                    if coordinator is not None
                    else coordinator_address(environ)
                ),
                num_processes=penv.num_processes,
                process_id=penv.process_id,
            )
    _initialized_env = penv
    return penv.num_processes, penv.process_id


class AgreementTimeout(TimeoutError):
    """A deadline-bounded cross-process coordination call expired.

    A dedicated subclass, NOT a bare ``TimeoutError``: on Python >= 3.10
    ``socket.timeout`` IS ``TimeoutError``, so supervision matching the
    builtin would misclassify any transient network/NFS timeout inside a
    trial as a lost peer and kill the whole sweep. Only THIS type means
    "the distributed state can no longer be trusted; restart against
    the ledger" (``hpo/supervision.py`` classifies it like preemption).
    """


class WedgedCollective(AgreementTimeout):
    """A device-sync point (host barrier, submesh agreement, epoch-loss
    fetch, completion ``block_until_ready``) wedged past its deadline.

    The watchdog's verdict on a stuck cross-host collective: a peer
    stopped dispatching (wedged, preempted, dead NIC) and this process
    is blocked on a result that will never arrive. Subclasses
    :class:`AgreementTimeout`, so supervision classifies it as
    preemption (die, restart against the ledger) — the extra type names
    *which* failure mode for the exit-code contract: a supervised
    worker catching this exits with :data:`PREEMPTION_EXIT_CODE` so an
    elastic supervisor (``tools/sweep_supervisor.py``) can tell
    "healthy host, lost world" from a genuine crash.
    """


# The exit-code contract (docs/RESILIENCE.md "Elastic multi-host"):
# a supervised worker that dies because the *world* failed around it —
# host preemption, a wedged collective, a graceful SIGTERM drain —
# exits with this code (BSD EX_TEMPFAIL: "try again"). The supervisor
# re-admits such hosts into the next, possibly smaller, world; any
# other non-zero exit marks the host itself as lost.
PREEMPTION_EXIT_CODE = 75


def call_with_timeout(
    fn,
    timeout_s: Optional[float],
    what: str,
    *,
    error_cls: type = AgreementTimeout,
):
    """Run ``fn()`` with a wall-clock deadline; raise a *diagnosable*
    :class:`AgreementTimeout` naming ``what`` instead of hanging
    forever.

    The failure mode this exists for: a dead/hung peer process leaves a
    cross-process collective (barrier, health reduction) blocked with no
    error — the reference's exact steady-state on a lost rank
    (SURVEY.md §5). A blocked C-level collective cannot be interrupted
    from Python, so the deadline runs ``fn`` on a watchdog thread and
    abandons it on expiry: the stuck thread leaks (daemon — it dies with
    the process), which is the honest trade for turning an indefinite
    hang into an actionable error. ``timeout_s=None`` or <= 0 means no
    deadline (direct call).

    ``error_cls`` selects the raised type (must accept one message
    argument): the driver's device-sync watchdogs pass
    :class:`WedgedCollective` so the failure names itself; the default
    stays :class:`AgreementTimeout` for generic coordination calls.

    The runner thread MUST be a daemon: on expiry the blocked ``fn`` is
    abandoned mid-call, and a non-daemon leak would make interpreter
    shutdown join a thread that never returns — the process would
    survive its own timeout just to hang at exit (regression-tested in
    tests/test_elastic.py).
    """
    if timeout_s is None or timeout_s <= 0:
        return fn()
    import threading

    box: dict = {}

    def runner():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            box["error"] = e

    t = threading.Thread(target=runner, daemon=True, name=f"watchdog:{what}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise error_cls(
            f"{what} did not complete within {timeout_s:g}s — a "
            "participating process is likely dead, preempted, or hung. "
            "The blocked collective was abandoned on a daemon thread; "
            "treat this process's distributed state as unusable and "
            "restart the job (the sweep ledger makes the restart cheap)."
        )
    if "error" in box:
        raise box["error"]
    return box.get("value")


def _env_timeout(env_var: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(env_var)
    if raw is None or raw == "":
        return default
    return float(raw)


def coordination_client():
    """The distributed runtime's coordination-service client, or None
    (single-process, or jax's internals moved).

    The sideband channel for cross-host agreement that must work even
    when the accelerator backend cannot (a wedged TPU plugin, or
    XLA:CPU's missing multiprocess computations): a host barrier and a
    key-value store served by the coordinator process, independent of
    any compiled collective."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover — jax internals moved
        return None


_UNBOUNDED_MS = 2**31 - 1  # "no deadline" for coordination-service waits


def agree_min_int(
    name: str,
    value: int,
    participants,
    *,
    timeout_s: Optional[float],
    what: str,
    error_cls: type = None,
) -> int:
    """Agree on the MINIMUM of a per-process integer across
    ``participants`` (process indices) via the coordination-service
    key-value store — the **sideband agreement** primitive.

    Unlike an on-mesh reduction (``collectives.group_min_scalar``) this
    never touches a compiled collective, so it works during recovery —
    exactly when the device world may be the thing that is broken —
    and on backends without cross-process XLA computations (CPU). Keys
    are scoped by ``name``; callers make names unique per agreement
    instance (the driver uses ``trial:attempt``), and every world
    restart gets a fresh coordinator so stale keys cannot leak across
    worlds.

    A participant that never shows up turns into ``error_cls``
    (default :class:`WedgedCollective`) within ``timeout_s`` — the
    no-hang contract. Single-process (or a single participant) returns
    ``value`` unchanged.
    """
    if error_cls is None:
        error_cls = WedgedCollective
    participants = sorted(int(p) for p in participants)
    import jax

    if len(participants) <= 1 or jax.process_count() == 1:
        return int(value)
    client = coordination_client()
    if client is None:
        raise error_cls(
            f"{what}: no coordination-service client available for the "
            f"sideband agreement {name!r} (distributed runtime not "
            "initialized?)"
        )
    pid = jax.process_index()
    timeout_ms = (
        int(timeout_s * 1000)
        if timeout_s and timeout_s > 0
        else _UNBOUNDED_MS
    )
    try:
        client.key_value_set(f"{name}:p{pid}", str(int(value)))
        values = [
            int(client.blocking_key_value_get(f"{name}:p{q}", timeout_ms))
            for q in participants
        ]
    except Exception as e:
        raise error_cls(
            f"{what} did not complete within "
            f"{(timeout_ms / 1000.0):g}s — a participant of the sideband "
            f"agreement {name!r} (processes {participants}) is missing: "
            "likely dead, preempted, or wedged. Treat this process's "
            "distributed state as unusable and restart against the "
            "sweep ledger."
        ) from e
    return min(values)


import itertools as _itertools

# Barrier ids must be unique per invocation; processes call sync_hosts
# at the same points (the documented collective-cadence contract), so a
# per-process counter yields matching ids everywhere.
_sync_barrier_counter = _itertools.count()

# Backend-capability verdict, cached after the first probe: whether
# this process's backend can run cross-process XLA computations at all
# (XLA:CPU cannot). Constant per process — re-probing would pay a
# doomed collective compile + a leaked watchdog thread on EVERY CPU
# barrier.
_xla_sync_unsupported = False


def sync_hosts(name: str = "sync", *, timeout_s: Optional[float] = None) -> None:
    """Barrier across host processes (multi-controller only).

    The analog of the reference's ``dist.barrier()`` — but deliberately
    NOT used anywhere in the trial path (the reference's world-scoped
    barriers serialize the sweep, quirk Q3). Provided for host-side
    coordination such as "download data once before dispatch"
    (``vae-hpo.py:133-144``) and end-of-job collection. No-op
    single-controller.

    ``timeout_s`` (default: ``MDT_SYNC_TIMEOUT_S`` env var, else 1800)
    bounds the wait: a dead peer turns into a descriptive
    :class:`WedgedCollective` naming the barrier instead of an
    indefinite hang — the reference's unbounded ``dist.barrier()`` is
    exactly the failure this guards against. The default is deliberately
    generous (30 min): this barrier's documented use is "wait while one
    host downloads the dataset", which is legitimately slow; jobs whose
    barriers wait even longer pass ``timeout_s`` explicitly or ``0`` /
    ``MDT_SYNC_TIMEOUT_S=0`` for the old unbounded behavior.

    Backend-agnostic: ``sync_global_devices`` compiles a cross-process
    collective, which XLA:CPU does not implement ("Multiprocess
    computations aren't implemented") — there the barrier degrades to
    the coordination-service host barrier, same semantics for host-side
    coordination, natively deadline-bounded (no watchdog thread to
    leak). The elastic chaos drills exercise the wedge path through
    exactly this barrier.
    """
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        if timeout_s is None:
            timeout_s = _env_timeout("MDT_SYNC_TIMEOUT_S", 1800.0)
        global _xla_sync_unsupported
        what = (
            f"host barrier {name!r} over {jax.process_count()} processes"
        )
        if not _xla_sync_unsupported:
            try:
                call_with_timeout(
                    lambda: multihost_utils.sync_global_devices(name),
                    timeout_s,
                    what,
                    # A stuck barrier IS a wedged collective: name it so
                    # the exit-code contract (and the supervisor) react.
                    error_cls=WedgedCollective,
                )
                return
            except WedgedCollective:
                raise
            except Exception as e:  # noqa: BLE001 — capability probe
                if "Multiprocess computations" not in str(e):
                    raise
                # XLA:CPU: fall back to the coordination-service
                # barrier, and remember the verdict — it is constant
                # per process. Every process of a CPU world raises
                # identically, so all participants fall back together.
                _xla_sync_unsupported = True
        client = coordination_client()
        if client is None:
            raise RuntimeError(
                f"{what}: backend cannot run multiprocess computations "
                "and no coordination-service client is available"
            )
        bid = f"mdt:sync:{name}:{next(_sync_barrier_counter)}"
        timeout_ms = (
            int(timeout_s * 1000)
            if timeout_s and timeout_s > 0
            else _UNBOUNDED_MS
        )
        try:
            client.wait_at_barrier(bid, timeout_ms)
        except Exception as e:
            raise WedgedCollective(
                f"{what} did not complete within "
                f"{(timeout_ms / 1000.0):g}s — a participating process "
                "is likely dead, preempted, or wedged. Treat this "
                "process's distributed state as unusable and restart "
                "the job (the sweep ledger makes the restart cheap)."
            ) from e


def process_world() -> tuple[int, int]:
    """Process count and index, ``(size, rank)``.

    Analog of ``get_comm_size_and_rank`` (``/root/reference/
    utils.py:28-38``). Unlike torch's side-effect-free
    ``dist.is_initialized()`` probe, querying JAX's process coordinates
    initializes the XLA backend — which would poison a later
    ``jax.distributed.initialize``. So this calls
    :func:`initialize_runtime` first (idempotent), making it safe in any
    order, exactly like the reference's query.
    """
    import jax

    initialize_runtime()
    return jax.process_count(), jax.process_index()
