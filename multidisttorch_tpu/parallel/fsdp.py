"""ZeRO/FSDP-style parameter+optimizer sharding over the data axis.

The reference replicates the full model and optimizer on every rank
(plain DDP, ``/root/reference/vae-hpo.py:130-131`` — SURVEY.md §2c lists
ZeRO/FSDP as absent). On TPU the capability costs almost nothing to add
the XLA way: annotate each parameter leaf with a ``NamedSharding`` that
splits its largest divisible axis over the submesh's ``data`` axis, and
GSPMD inserts the all-gathers before use and reduce-scatters after the
gradient — the ZeRO-3 execution pattern — while the Adam moments
(eagerly initialized, computation-follows-data) inherit the same shards,
cutting state memory by the data-axis extent. No wrapper class, no
hooks: the sharding *is* the feature.

Composes with the rest of the framework unchanged: the sharded state
threads through ``make_train_step(..., shardings=state_shardings(state))``
exactly like a tensor-parallel state does.
"""

from __future__ import annotations

from typing import Any

import jax

from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh


def fsdp_param_shardings(
    trial: TrialMesh, params: Any, *, min_size: int = 1024
) -> Any:
    """Per-leaf shardings splitting each parameter over the data axis.

    For every leaf, shard the largest axis divisible by the submesh's
    data extent; leaves smaller than ``min_size`` elements (biases,
    norm scales — where a shard would be less than one lane tile and
    the gather latency outweighs the memory) stay replicated.

    Returns a pytree of ``NamedSharding`` matching ``params`` — pass to
    ``create_train_state(..., param_shardings=...)`` /
    ``create_classifier_state``.

    Implemented as the composition rule over an all-replicated base, so
    the 1-D and layered (ZeRO-over-TP) paths share ONE dim-selection
    rule and cannot drift.
    """
    repl = trial.sharding()
    return fsdp_compose_shardings(
        trial, params, jax.tree.map(lambda _: repl, params),
        min_size=min_size,
    )


def fsdp_compose_shardings(
    trial: TrialMesh, params: Any, base_shardings: Any, *,
    min_size: int = 1024,
) -> Any:
    """Layer ZeRO data-axis sharding on top of an existing sharding tree.

    The Megatron + ZeRO-3 composition: ``base_shardings`` (typically a
    tensor-parallel tree like ``vae_tp_shardings`` /
    ``transformer_tp_shardings``) says which dims ride the ``model``
    axis; this adds ``data``-axis sharding on the largest
    data-divisible dim each base spec leaves unsharded, so parameters
    and Adam moments split over BOTH axes of a 2-D submesh. Leaves the
    base untouched where it already covers every dim, where the leaf is
    small (< ``min_size`` elements), or where no free dim divides the
    data extent. GSPMD turns the annotations into the all-gather /
    reduce-scatter schedule exactly as in the 1-D case.
    """
    n = trial.data_size

    def rule(leaf, base):
        if leaf.size < min_size:
            return base
        spec = list(base.spec) + [None] * (leaf.ndim - len(base.spec))
        free = [
            (dim, i) for i, dim in enumerate(leaf.shape)
            if spec[i] is None and dim % n == 0
        ]
        if not free:
            return base
        _, axis = max(free)
        spec[axis] = DATA_AXIS
        return trial.sharding(*spec)

    return jax.tree.map(rule, params, base_shardings)
