"""ZeRO/FSDP-style parameter+optimizer sharding over the data axis.

The reference replicates the full model and optimizer on every rank
(plain DDP, ``/root/reference/vae-hpo.py:130-131`` — SURVEY.md §2c lists
ZeRO/FSDP as absent). On TPU the capability costs almost nothing to add
the XLA way: annotate each parameter leaf with a ``NamedSharding`` that
splits its largest divisible axis over the submesh's ``data`` axis, and
GSPMD inserts the all-gathers before use and reduce-scatters after the
gradient — the ZeRO-3 execution pattern — while the Adam moments
(eagerly initialized, computation-follows-data) inherit the same shards,
cutting state memory by the data-axis extent. No wrapper class, no
hooks: the sharding *is* the feature.

Composes with the rest of the framework unchanged: the sharded state
threads through ``make_train_step(..., shardings=state_shardings(state))``
exactly like a tensor-parallel state does.
"""

from __future__ import annotations

from typing import Any

import jax

from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh


def fsdp_param_shardings(
    trial: TrialMesh, params: Any, *, min_size: int = 1024
) -> Any:
    """Per-leaf shardings splitting each parameter over the data axis.

    For every leaf, shard the largest axis divisible by the submesh's
    data extent; leaves smaller than ``min_size`` elements (biases,
    norm scales — where a shard would be less than one lane tile and
    the gather latency outweighs the memory) stay replicated.

    Returns a pytree of ``NamedSharding`` matching ``params`` — pass to
    ``create_train_state(..., param_shardings=...)`` /
    ``create_classifier_state``.

    Implemented as the composition rule over an all-replicated base, so
    the 1-D and layered (ZeRO-over-TP) paths share ONE dim-selection
    rule and cannot drift.
    """
    repl = trial.sharding()
    return fsdp_compose_shardings(
        trial, params, jax.tree.map(lambda _: repl, params),
        min_size=min_size,
    )


def fsdp_compose_shardings(
    trial: TrialMesh, params: Any, base_shardings: Any, *,
    min_size: int = 1024,
) -> Any:
    """Layer ZeRO data-axis sharding on top of an existing sharding tree.

    The Megatron + ZeRO-3 composition: ``base_shardings`` (typically a
    tensor-parallel tree like ``vae_tp_shardings`` /
    ``transformer_tp_shardings``) says which dims ride the ``model``
    axis; this adds ``data``-axis sharding on the largest
    data-divisible dim each base spec leaves unsharded, so parameters
    and Adam moments split over BOTH axes of a 2-D submesh. Leaves the
    base untouched where it already covers every dim, where the leaf is
    small (< ``min_size`` elements), or where no free dim divides the
    data extent. GSPMD turns the annotations into the all-gather /
    reduce-scatter schedule exactly as in the 1-D case.
    """
    n = trial.data_size

    def rule(leaf, base):
        if leaf.size < min_size:
            return base
        spec = list(base.spec) + [None] * (leaf.ndim - len(base.spec))
        free = [
            (dim, i) for i, dim in enumerate(leaf.shape)
            if spec[i] is None and dim % n == 0
        ]
        if not free:
            return base
        _, axis = max(free)
        spec[axis] = DATA_AXIS
        return trial.sharding(*spec)

    return jax.tree.map(rule, params, base_shardings)


# --- ZeRO-style sharded weight update (optimizer-state sharding) ----
#
# The functions above shard the PARAMETERS (ZeRO-3: all-gather weights
# before use). The sharded-update mode below is the ZeRO-1/2 point in
# the trade space (arXiv 2004.13336): parameters stay replicated — the
# forward/backward is the plain DDP program, bit-compatible with the
# replicated reference — but the Adam moments are partitioned over the
# data axis, so each device updates only the shard of the state it
# owns. Under GSPMD the annotation IS the protocol: with moments
# pinned data-sharded and params pinned replicated in the step's
# out_shardings, XLA reduce-scatters the gradient into the moment
# update and all-gathers the fresh parameters after `apply_updates` —
# the canonical reduce-scatter → shard-update → all-gather schedule,
# with per-device optimizer memory cut to ~1/n_data of replicated.
# Selected per-TrialConfig (`zero_update=True`, hpo/driver.py); losses
# match the replicated reference within a pinned tolerance (the grad
# reduction reassociates across devices — regression-tested, and gated
# by `bench.py --pipeline`).


def zero_update_shardings(
    trial: TrialMesh, state: Any, *, min_size: int = 1024
) -> Any:
    """Sharding tree for the sharded-update TrainState variant:
    ``params``/``step`` replicated, each ``opt_state`` leaf split over
    the data axis by :func:`fsdp_param_shardings`'s dim-selection rule
    — ONE rule for the parameter path (ZeRO-3 annotations) and the
    optimizer-state path, so the two cannot drift on which leaves
    shard (leaves smaller than ``min_size`` elements — Adam's count
    scalar, bias moments — stay replicated; the gather would cost more
    than the bytes).

    Returns a pytree of ``NamedSharding`` with ``state``'s structure —
    pass to ``make_train_step(..., shardings=...)`` to pin the layout
    across steps, and to checkpoint restore so a resumed state lands
    sharded."""
    repl = trial.sharding()
    return state.replace(
        params=jax.tree.map(lambda _: repl, state.params),
        opt_state=fsdp_param_shardings(
            trial, state.opt_state, min_size=min_size
        ),
        step=repl,
    )


def place_zero_state(
    trial: TrialMesh, state: Any, *, min_size: int = 1024
) -> tuple[Any, Any]:
    """Place a (host or replicated) TrainState in sharded-update form:
    ``(state, shardings)`` with the optimizer leaves physically split
    over the submesh's data axis. Multi-controller safe via
    ``TrialMesh.device_put`` (each process materializes only its
    addressable shards)."""
    sh = zero_update_shardings(trial, state, min_size=min_size)
    if jax.process_count() == 1:
        return jax.device_put(state, sh), sh
    return trial.device_put(state, sh), sh


def describe_shardings(shardings: Any) -> dict:
    """Flatten a shardings pytree into ``{leaf-key: spec-string}`` —
    the checkpoint manifest's layout record (docs/RESILIENCE.md
    "Checkpoint format v2"): the on-disk format names the
    ``NamedSharding`` layout the state trained under, so a reader (or
    a restore-parity check) can see which leaves the runtime sharded
    without reconstructing the mesh. The same flattening rule as the
    manifest builder's, so keys line up with manifest leaf keys."""
    from flax import serialization

    from multidisttorch_tpu.train.ckpt_store import _flatten_state_dict

    out: dict[str, str] = {}
    for key, sh in _flatten_state_dict(
        serialization.to_state_dict(shardings)
    ):
        spec = getattr(sh, "spec", None)
        if spec is not None:
            out[key] = str(spec)
    return out


def optimizer_state_bytes(state: Any) -> dict:
    """Analytic optimizer-memory book from a placed TrainState:
    ``per_device_bytes`` (what one chip actually holds, from each opt
    leaf's concrete sharding) and ``total_bytes`` (the replicated-
    equivalent footprint — what the same state costs per device with
    no sharding). The ratio is the ZeRO win the memory books and the
    ``bench.py --pipeline`` gate surface; works on CPU where
    ``memory_stats()`` does not exist."""
    import math

    per_dev = 0
    total = 0
    for leaf in jax.tree.leaves(state.opt_state):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        nbytes = int(size) * dtype.itemsize
        total += nbytes
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shard = sharding.shard_shape(tuple(leaf.shape))
            per_dev += int(math.prod(shard)) * dtype.itemsize
        else:
            per_dev += nbytes
    return {"per_device_bytes": per_dev, "total_bytes": total}
