"""Device-group carving: one global mesh → N disjoint trial submeshes.

This is the TPU-native rebuild of the reference's core capability,
``setup_ddp_groups`` (``/root/reference/utils.py:146-163``): partition
the world into N equal contiguous groups, each a first-class
communicator. In torch.distributed that requires a world-collective
``dist.new_group`` handshake per group, executed on *every* rank
(``utils.py:155-157``; the commented-out broken member-only variant at
``example-subgroup.py:10-19`` is the reference's own lesson). In JAX a
sub-communicator is pure host-side metadata: a ``jax.sharding.Mesh``
built over a slice of ``jax.devices()``. Creation involves no
cross-process event; XLA materializes the actual ICI/DCN collectives at
compile time from shardings referencing the submesh.

Deliberate fixes over the reference (SURVEY.md §2d):

- Q5: a world that doesn't divide evenly by ``num_groups`` raises
  immediately instead of silently orphaning trailing ranks (which hangs
  the reference's world-scoped barriers).
- Q2's API shape is preserved — every process gets handles to *all*
  groups and tests membership per group — but the collective-creation
  constraint disappears.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Axis name used for the data-parallel dimension of every trial submesh.
DATA_AXIS = "data"
# Axis name for the optional model/tensor-parallel dimension (2-D submeshes).
MODEL_AXIS = "model"
# Axis name for the optional pipeline-stage dimension (parallel/pipeline.py).
PIPE_AXIS = "pipe"


def device_world(devices: Optional[Sequence[jax.Device]] = None) -> tuple[int, int]:
    """``(num_devices, first_local_device_index)`` over the global device list.

    The reference's "world" is processes (one GPU per rank); the TPU
    analog of a rank is a device. Returns the global device count and the
    index of this process's first addressable device (0 in
    single-controller mode).
    """
    devs = list(jax.devices()) if devices is None else list(devices)
    local = [i for i, d in enumerate(devs) if d.process_index == jax.process_index()]
    return len(devs), (local[0] if local else -1)


def global_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis: str = DATA_AXIS
) -> Mesh:
    """Build the 1-D global mesh over all devices (axis name ``axis``)."""
    devs = np.array(list(jax.devices()) if devices is None else list(devices))
    return Mesh(devs, (axis,))


@dataclass(frozen=True)
class TrialMesh:
    """One carved device group — the analog of a torch process subgroup.

    Wraps a disjoint contiguous slice of the global device list as a 1-D
    ``Mesh`` with a ``data`` axis, plus the membership/rank queries the
    reference exposes on group handles (``dist.get_rank(group)``,
    ``utils.py:160``; ``dist.get_world_size(group)``, ``vae-hpo.py:126``).
    """

    group_id: int
    mesh: Mesh
    global_ranks: tuple[int, ...]  # indices into the global device list

    @property
    def devices(self) -> tuple[jax.Device, ...]:
        return tuple(self.mesh.devices.ravel().tolist())

    @property
    def size(self) -> int:
        """Device count in this group (``dist.get_world_size(group)``)."""
        return int(self.mesh.devices.size)

    @property
    def data_size(self) -> int:
        """Extent of the data-parallel axis (== ``size`` on 1-D groups)."""
        return int(self.mesh.shape[DATA_AXIS])

    @property
    def model_size(self) -> int:
        """Extent of the model-parallel axis (1 on 1-D groups)."""
        return int(dict(self.mesh.shape).get(MODEL_AXIS, 1))

    @property
    def pipe_size(self) -> int:
        """Extent of the pipeline-stage axis (1 unless carved with
        ``pipeline_parallel > 1``)."""
        return int(dict(self.mesh.shape).get(PIPE_AXIS, 1))

    @property
    def is_local_member(self) -> bool:
        """Whether this process owns any device of the group.

        The analog of the reference's membership test
        ``dist.get_rank(group) >= 0`` (``vae-hpo.py:201``): in
        multi-controller SPMD, a process participates in a trial iff it
        has addressable devices in the trial's submesh.
        """
        pid = jax.process_index()
        return any(d.process_index == pid for d in self.devices)

    @property
    def local_rank(self) -> int:
        """Group-rank of this process's first device in the group, or -1.

        Mirrors ``dist.get_rank(group)`` returning -1 for non-members.
        """
        pid = jax.process_index()
        for i, d in enumerate(self.devices):
            if d.process_index == pid:
                return i
        return -1

    def rank_of(self, device: jax.Device) -> int:
        """Group-rank of ``device``, or -1 if it is not a member."""
        for i, d in enumerate(self.devices):
            if d == device:
                return i
        return -1

    @property
    def owner_processes(self) -> frozenset[int]:
        """Process indices owning at least one device of this group —
        global device metadata, so every process computes the same set."""
        return frozenset(d.process_index for d in self.devices)

    @property
    def spans_processes(self) -> bool:
        """Whether this group's devices live on more than one process
        (when True, per-trial failure handling needs the cross-process
        agreement in ``collectives.group_all_ok``)."""
        return len(self.owner_processes) > 1

    # --- shardings: the pjit-native face of "this group's communicator" ---

    @property
    def batch_sharding(self) -> NamedSharding:
        """Shard dim 0 over the group's data axis (true within-trial DP —
        fixes quirk Q1, where the reference fed every rank of a group the
        identical shard, ``vae-hpo.py:146``)."""
        return NamedSharding(self.mesh, P(DATA_AXIS))

    @property
    def replicated_sharding(self) -> NamedSharding:
        """Replicate across the group (model/optimizer state, DDP-style)."""
        return NamedSharding(self.mesh, P())

    def sharding(self, *spec) -> NamedSharding:
        """Arbitrary ``PartitionSpec`` over this group's mesh axes —
        e.g. ``trial.sharding(None, MODEL_AXIS)`` for a column-sharded
        weight on a 2-D (data × model) submesh."""
        return NamedSharding(self.mesh, P(*spec))

    @property
    def is_writer_process(self) -> bool:
        """Whether this process is the group's designated artifact writer
        (the owner of the group's first device). Exactly one process per
        group: the multi-controller guard that keeps images, checkpoints,
        and metrics written once per trial instead of once per owner
        process (the reference's every-rank-writes behavior is quirk Q4's
        second half, ``vae-hpo.py:156-158``)."""
        return self.devices[0].process_index == jax.process_index()

    def device_put(self, tree, sharding: Optional[NamedSharding] = None):
        """Place a host pytree onto this group's devices (replicated by
        default).

        Multi-controller safe: when the submesh spans processes (or this
        process owns none of it), placement goes through
        ``make_array_from_callback`` so each process materializes only
        its addressable shards — every process must call this with the
        same values (host-side determinism), the same contract as the
        data path (``data/sampler.py``)."""
        sh = self.replicated_sharding if sharding is None else sharding
        if jax.process_count() == 1:
            return jax.device_put(tree, sh)

        def put_leaf(x, leaf_sh):
            dt = getattr(x, "dtype", None)
            if dt is not None and jax.dtypes.issubdtype(
                dt, jax.dtypes.prng_key
            ):
                # Typed PRNG keys (PBT base_rngs, the explore key)
                # cannot round-trip through np.asarray: place the raw
                # uint32 key data and rewrap. Keys only ever place
                # replicated here, and a replicated spec is
                # rank-agnostic, so the same sharding serves the key
                # data's extra trailing dim.
                impl = jax.random.key_impl(x)
                data = np.asarray(jax.random.key_data(x))
                placed = jax.make_array_from_callback(
                    data.shape, leaf_sh, lambda idx: data[idx]
                )
                return jax.random.wrap_key_data(placed, impl=impl)
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, leaf_sh, lambda idx: x[idx]
            )

        if isinstance(sh, NamedSharding):
            return jax.tree.map(lambda x: put_leaf(x, sh), tree)
        return jax.tree.map(put_leaf, tree, sh)

    def __repr__(self) -> str:  # keep dataclass-frozen hash/eq, short repr
        return (
            f"TrialMesh(group_id={self.group_id}, size={self.size}, "
            f"global_ranks={self.global_ranks})"
        )


def setup_groups(
    num_groups: int,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    allow_uneven: bool = False,
    model_parallel: int = 1,
    pipeline_parallel: int = 1,
) -> list[TrialMesh]:
    """Carve the device world into ``num_groups`` contiguous disjoint groups.

    API mirror of ``setup_ddp_groups`` (``/root/reference/
    utils.py:146-163``): contiguous rank blocks ``[g*k .. g*k+k-1]``,
    every process receives handles to all groups. Differences:

    - creation is metadata-only (no world-collective ``new_group``
      handshake — quirk Q2 evaporates);
    - a non-divisible world raises ``ValueError`` unless
      ``allow_uneven=True`` explicitly opts into dropping the remainder
      devices (the reference silently orphans them and then hangs on its
      world barriers — quirk Q5);
    - ``model_parallel=m`` makes each group a 2-D ``(data, model)``
      submesh of shape ``(k/m, m)`` for within-trial tensor parallelism
      (beyond the reference, which is DP-only — SURVEY.md §2c). The
      model axis occupies the *fastest-varying* device positions so TP
      collectives ride adjacent ICI links.
    - ``pipeline_parallel=p`` adds a ``pipe`` axis (see
      ``parallel/pipeline.py``) between ``data`` and ``model``:
      each group becomes a ``(k/(p*m), p[, m])`` grid. Pipe-axis
      neighbors are ``m`` device positions apart — adjacent when
      ``m == 1`` — so GPipe's stage-to-stage ppermute hops stay on
      short ICI paths.
    """
    devs = list(jax.devices()) if devices is None else list(devices)
    world = len(devs)
    if num_groups < 1:
        raise ValueError(f"num_groups must be >= 1, got {num_groups}")
    if world < num_groups:
        raise ValueError(
            f"Number of groups {num_groups} requested exceeds number of "
            f"total devices {world} available"
        )
    per_group, remainder = divmod(world, num_groups)
    if remainder and not allow_uneven:
        raise ValueError(
            f"World of {world} devices does not divide into {num_groups} "
            f"groups ({remainder} devices would be orphaned, which in the "
            "reference design hangs the job — SURVEY.md Q5). Pass "
            "allow_uneven=True to deliberately drop the remainder."
        )

    if model_parallel < 1:
        raise ValueError(f"model_parallel must be >= 1, got {model_parallel}")
    if pipeline_parallel < 1:
        raise ValueError(
            f"pipeline_parallel must be >= 1, got {pipeline_parallel}"
        )
    inner = model_parallel * pipeline_parallel
    if per_group % inner:
        raise ValueError(
            f"group size {per_group} does not divide into pipeline_parallel="
            f"{pipeline_parallel} x model_parallel={model_parallel} (each "
            "group needs a full (data, pipe, model) grid)"
        )

    # Axis layout: model fastest-varying (adjacent ICI for TP
    # collectives), then pipe, then data. Size-1 pipe/model axes are
    # dropped so the default carve stays the 1-D (data,) mesh.
    dims = [
        (DATA_AXIS, per_group // inner),
        (PIPE_AXIS, pipeline_parallel),
        (MODEL_AXIS, model_parallel),
    ]
    kept = [(name, n) for name, n in dims if n > 1 or name == DATA_AXIS]

    groups = []
    for g in range(num_groups):
        ranks = tuple(range(g * per_group, (g + 1) * per_group))
        grid = np.array([devs[r] for r in ranks])
        submesh = Mesh(
            grid.reshape(tuple(n for _, n in kept)),
            tuple(name for name, _ in kept),
        )
        groups.append(TrialMesh(group_id=g, mesh=submesh, global_ranks=ranks))
    return groups
