"""Sideband heartbeat membership for elastic multi-host sweeps.

The multi-host failure detector must NOT ride the thing it is
detecting failures of: a collective-based health check wedges exactly
when the world it probes wedges (the reference's all-or-nothing
steady-state, SURVEY.md §5). So membership here is pure sideband state
— per-host **lease files** on the run directory's shared filesystem,
one append-only JSONL stream per host slot:

    {run_dir}/membership/host-{slot}.jsonl
    {"slot": 1, "pid": 4242, "ts": ..., "seq": 17, "status": "alive",
     "world_epoch": 0, "world_size": 3, "hostname": "..."}

Appends either land whole or tear the final line; readers skip
undecodable lines (the sweep ledger's crash model, ``hpo/ledger.py``).
A host is **lost** when its newest decodable lease is older than the
detection deadline and does not say ``"left"`` — dead processes,
SIGKILLed hosts, and wedged processes whose heartbeat thread stopped
making progress all look identical here, which is the point: the
supervisor (``tools/sweep_supervisor.py``) needs one verdict, "this
host is not coming back", without touching a collective.

The writer side is a tiny daemon thread (:class:`Heartbeat`); the
fault injector's WEDGE kind calls :func:`suspend_heartbeat` so a
simulated stuck host goes lease-stale exactly like a real one. No jax
import at module level — the supervisor process uses this without a
device runtime.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Optional

MEMBERSHIP_DIRNAME = "membership"
LEASE_PREFIX = "host-"
WORLDS_NAME = "worlds.jsonl"

ALIVE = "alive"
DRAINING = "draining"
LEFT = "left"


def membership_dir(run_dir: str) -> str:
    return os.path.join(run_dir, MEMBERSHIP_DIRNAME)


def lease_path(run_dir: str, slot: int) -> str:
    return os.path.join(membership_dir(run_dir), f"{LEASE_PREFIX}{slot}.jsonl")


def emit_event(kind: str, **data) -> None:
    """Typed membership telemetry (``host_lost`` / ``world_shrunk`` /
    ``trial_migrated`` ride this seam) — zero-cost-when-off contract.
    Public: the supervisor emits its verdicts through the same seam."""
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    if bus is not None:
        bus.emit(kind, **data)


def read_lease(path: str) -> list[dict]:
    """All decodable lease records, in append order; a torn final line
    (host died mid-append) is skipped, never fatal."""
    if not os.path.exists(path):
        return []
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail
    except OSError:
        return out
    return out


def latest_lease(path: str, *, tail_bytes: int = 8192) -> Optional[dict]:
    """Newest decodable lease record — read from the file's TAIL only.

    The supervisor polls this several times a second while heartbeats
    append ~4 records/s/host indefinitely; re-parsing the whole stream
    per poll would grow linearly with sweep age. Seeking to the last
    ``tail_bytes`` and decoding backwards is O(1) per poll: the first
    (possibly partial) tail line is skipped by the same torn-tolerant
    decode that guards crash tears."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - tail_bytes))
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(chunk.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail, or the seek landed mid-line
    return None


class Heartbeat:
    """Per-host lease writer: one JSONL append every ``interval_s`` on a
    daemon thread. ``suspend()`` freezes the beat (the WEDGE fault's
    simulation of a stuck process); ``stop()`` writes a final record —
    ``"left"`` for a clean exit, so the supervisor never classifies a
    deliberate departure as a lost host."""

    def __init__(
        self,
        run_dir: str,
        slot: int,
        *,
        interval_s: float = 0.25,
        world_epoch: int = 0,
        world_size: int = 1,
    ):
        self.path = lease_path(run_dir, slot)
        self.slot = int(slot)
        self.interval_s = float(interval_s)
        self.world_epoch = int(world_epoch)
        self.world_size = int(world_size)
        self._seq = 0
        self._stop = threading.Event()
        self._suspended = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _append(self, status: str) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        rec = {
            "slot": self.slot,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "ts": time.time(),
            # Paired monotonic anchor: a reader comparing consecutive
            # (ts, mono) deltas can tell a wall-clock STEP (NTP jump,
            # operator date change) from real elapsed time — the fleet
            # merge (telemetry/fleet.py) uses the pairs as its per-host
            # clock-sanity evidence.
            "mono": time.monotonic(),
            "seq": self._seq,
            "status": status,
            "world_epoch": self.world_epoch,
            "world_size": self.world_size,
        }
        self._seq += 1
        # flush, no fsync: staleness detection tolerates losing the last
        # beat (the NEXT one refreshes the lease), and an fsync every
        # quarter-second would hammer a shared filesystem for nothing.
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self._suspended.is_set():
                continue
            try:
                self._append(ALIVE)
            except OSError:
                # A failing beat must never kill the trial thread's
                # host; a persistently unwritable lease simply reads as
                # lost — the honest verdict for a host that cannot
                # reach the shared run dir.
                pass

    def start(self) -> "Heartbeat":
        self._append(ALIVE)  # lease exists before the first interval
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"heartbeat:{self.slot}"
        )
        self._thread.start()
        return self

    def suspend(self) -> None:
        """Freeze the beat without stopping the thread — the lease goes
        stale like a wedged process's would."""
        self._suspended.set()

    def resume(self) -> None:
        self._suspended.clear()

    def stop(self, status: str = LEFT) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)
            self._thread = None
        try:
            self._append(status)
        except OSError:
            pass


# Process-wide current heartbeat: the fault injector's WEDGE kind (and
# any drain path) needs to reach "this host's lease" without threading
# the object through every seam.
_current: Optional[Heartbeat] = None


def start_heartbeat(
    run_dir: str, slot: int, **kwargs
) -> Heartbeat:
    """Start (and register as current) this process's lease writer."""
    global _current
    if _current is not None:
        _current.stop()
    _current = Heartbeat(run_dir, slot, **kwargs).start()
    return _current


def current_heartbeat() -> Optional[Heartbeat]:
    return _current


def suspend_heartbeat() -> bool:
    """Freeze the current heartbeat (WEDGE simulation); True if one was
    running."""
    if _current is None:
        return False
    _current.suspend()
    return True


def stop_heartbeat(status: str = LEFT) -> None:
    global _current
    if _current is not None:
        _current.stop(status)
        _current = None


class MembershipView:
    """Read-side membership: fold every host slot's lease stream into a
    liveness verdict. Collective-free by construction — plain file
    reads over the shared run dir."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self.dir = membership_dir(run_dir)
        # host_lost telemetry fires on the stale TRANSITION only: a
        # polling caller (deadline loop) must not emit one duplicate
        # event per poll for a host that stays lost. A host that beats
        # again (recovered lease) re-arms its transition.
        self._reported_lost: set[int] = set()

    def slots(self) -> list[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for name in names:
            if name.startswith(LEASE_PREFIX) and name.endswith(".jsonl"):
                try:
                    out.append(int(name[len(LEASE_PREFIX):-len(".jsonl")]))
                except ValueError:
                    continue
        return sorted(out)

    def hosts(self) -> dict[int, dict]:
        """slot -> newest decodable lease record."""
        out = {}
        for slot in self.slots():
            rec = latest_lease(lease_path(self.run_dir, slot))
            if rec is not None:
                out[slot] = rec
        return out

    def lost_hosts(
        self,
        deadline_s: float,
        *,
        now: Optional[float] = None,
        among: Optional[list[int]] = None,
    ) -> list[int]:
        """Slots whose lease went stale: newest record older than
        ``deadline_s`` and not a clean ``"left"``. ``among`` restricts
        the check to the slots the caller believes should be beating
        (the supervisor's currently-launched world) so long-departed
        slots from earlier worlds don't re-report forever."""
        t = time.time() if now is None else now
        lost = []
        for slot, rec in self.hosts().items():
            if among is not None and slot not in among:
                continue
            if rec.get("status") == LEFT:
                continue
            if t - float(rec.get("ts", 0.0)) > deadline_s:
                lost.append(slot)
                if slot not in self._reported_lost:
                    self._reported_lost.add(slot)
                    emit_event(
                        "host_lost",
                        slot=slot,
                        last_ts=rec.get("ts"),
                        stale_s=round(t - float(rec.get("ts", 0.0)), 3),
                        world_epoch=rec.get("world_epoch"),
                    )
            else:
                self._reported_lost.discard(slot)
        return sorted(lost)


def record_world(
    run_dir: str,
    *,
    epoch: int,
    hosts: list[int],
    lost: Optional[list[int]] = None,
    reason: str = "",
) -> dict:
    """Append one world-formation record to ``membership/worlds.jsonl``
    (torn-tail-tolerant like the leases). The durable world history:
    workers read it on restart to compute which trials migrated, and
    the drill report replays it for the shrink timeline."""
    os.makedirs(membership_dir(run_dir), exist_ok=True)
    rec = {
        "epoch": int(epoch),
        "hosts": sorted(int(h) for h in hosts),
        "lost": sorted(int(h) for h in (lost or [])),
        "reason": reason,
        "ts": time.time(),
    }
    path = os.path.join(membership_dir(run_dir), WORLDS_NAME)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    if lost:
        emit_event(
            "world_shrunk",
            epoch=rec["epoch"],
            hosts=rec["hosts"],
            lost=rec["lost"],
            reason=reason,
        )
    return rec


def world_history(run_dir: str) -> list[dict]:
    """All decodable world records, in formation order."""
    path = os.path.join(membership_dir(run_dir), WORLDS_NAME)
    return read_lease(path)  # same torn-tail-tolerant JSONL fold
