from multidisttorch_tpu.parallel.cluster import (
    PREEMPTION_EXIT_CODE,
    AgreementTimeout,
    ProcessEnv,
    WedgedCollective,
    coordinator_address,
    detect_process_env,
    find_ifname,
    initialize_runtime,
    parse_slurm_nodelist,
    process_world,
    sync_hosts,
)
from multidisttorch_tpu.parallel.collectives import (
    group_all_gather,
    group_pmean,
    group_psum,
)
from multidisttorch_tpu.parallel.fsdp import (
    fsdp_param_shardings,
    optimizer_state_bytes,
    place_zero_state,
    zero_update_shardings,
)
from multidisttorch_tpu.parallel.pipeline import (
    MpmdPipeline,
    analytic_bubble_fraction,
    make_mpmd_reference_step,
    make_vae_stage_fns,
    pack_stage_params,
    pipeline_apply,
    pipeline_apply_stages,
    split_stage_params,
    stage_params_sharding,
    unpack_stage_params,
)
from multidisttorch_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    TrialMesh,
    device_world,
    global_mesh,
    setup_groups,
)
