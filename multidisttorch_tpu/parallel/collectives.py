"""Group-scoped collectives over trial submeshes.

The reference reaches collectives through torch.distributed with a
``group=`` handle: ``dist.all_gather(..., group=subgroup)``
(``/root/reference/example-subgroup.py:27,32``) and DDP's implicit
bucketed gradient all-reduce (``vae-hpo.py:130``). The TPU-native form:
``jax.shard_map`` over the submesh's ``data`` axis, with
``jax.lax.all_gather`` / ``psum`` / ``pmean`` compiled by XLA onto ICI.
Two groups' collectives touch disjoint devices, so they proceed
concurrently and independently — same contract as the reference's two
concurrent subgroup gathers, with no NCCL communicator setup.

In most training code you will not call these directly: replicate params
and shard the batch with ``TrialMesh.{replicated,batch}_sharding`` and
XLA inserts the gradient reduction itself (the pjit analog of DDP).
These wrappers exist for explicit collective programming and for parity
with the reference's demo (`example-subgroup.py`).

Compiled executables are cached per (mesh, op) so repeated calls on a
hot path (e.g. a per-step psum) trace and compile exactly once.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh


def pvary(x, axis_names):
    """Annotate ``x`` as device-varying over ``axis_names`` under
    ``shard_map``'s varying-axis (VMA) typing.

    Needed when a loop carry starts as a mesh-invariant constant but
    becomes device-varying through the body (ppermute, axis_index, shard
    data) — the initial carry must already hold the annotation. Wraps
    the JAX API spelling drift: ``jax.lax.pcast(..., to="varying")``
    (current) vs ``jax.lax.pvary`` (older).
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to="varying")
    return jax.lax.pvary(x, axis_names)  # pragma: no cover


@lru_cache(maxsize=None)
def _gather_fn(mesh: Mesh):
    # check_vma=False: the gathered result is device-invariant by
    # construction, but shard_map's varying-axis inference cannot prove
    # replication through all_gather.
    return jax.jit(
        jax.shard_map(
            lambda s: jax.lax.all_gather(s, DATA_AXIS, axis=0, tiled=True),
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=P(),
            check_vma=False,
        )
    )


@lru_cache(maxsize=None)
def _reduce_fn(mesh: Mesh, op: str):
    reducer = {"psum": jax.lax.psum, "pmean": jax.lax.pmean}[op]
    # Each member device contributes one row of x; squeeze the per-device
    # shard's leading dim so the reduced result has shape x.shape[1:].
    return jax.jit(
        jax.shard_map(
            lambda s: reducer(jnp.squeeze(s, axis=0), DATA_AXIS),
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=P(),
        )
    )


def group_all_gather(trial: TrialMesh, x):
    """All-gather per-device shards within one trial group.

    ``x`` has leading dim == group size (one row per member device, the
    analog of each rank contributing one tensor). Returns the gathered
    array, identical on (replicated across) every member device —
    matching ``dist.all_gather``'s every-rank-gets-all contract
    (``example-subgroup.py:25-33``).
    """
    return _gather_fn(trial.mesh)(x)


def group_psum(trial: TrialMesh, x):
    """Sum per-device shards (leading dim == group size) across the group.

    The explicit form of DDP's gradient all-reduce scoped to a subgroup
    (``vae-hpo.py:130``). Every member device holds the full sum.
    """
    return _reduce_fn(trial.mesh, "psum")(x)


def group_pmean(trial: TrialMesh, x):
    """Mean per-device shards across the group (DDP gradient averaging)."""
    return _reduce_fn(trial.mesh, "pmean")(x)
