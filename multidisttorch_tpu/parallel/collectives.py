"""Group-scoped collectives over trial submeshes.

The reference reaches collectives through torch.distributed with a
``group=`` handle: ``dist.all_gather(..., group=subgroup)``
(``/root/reference/example-subgroup.py:27,32``) and DDP's implicit
bucketed gradient all-reduce (``vae-hpo.py:130``). The TPU-native form:
``jax.shard_map`` over the submesh's ``data`` axis, with
``jax.lax.all_gather`` / ``psum`` / ``pmean`` compiled by XLA onto ICI.
Two groups' collectives touch disjoint devices, so they proceed
concurrently and independently — same contract as the reference's two
concurrent subgroup gathers, with no NCCL communicator setup.

In most training code you will not call these directly: replicate params
and shard the batch with ``TrialMesh.{replicated,batch}_sharding`` and
XLA inserts the gradient reduction itself (the pjit analog of DDP).
These wrappers exist for explicit collective programming and for parity
with the reference's demo (`example-subgroup.py`).

Compiled executables are cached per (mesh, op) so repeated calls on a
hot path (e.g. a per-step psum) trace and compile exactly once.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from multidisttorch_tpu.utils.compat import shard_map as compat_shard_map
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh


def pvary(x, axis_names):
    """Annotate ``x`` as device-varying over ``axis_names`` under
    ``shard_map``'s varying-axis (VMA) typing.

    Needed when a loop carry starts as a mesh-invariant constant but
    becomes device-varying through the body (ppermute, axis_index, shard
    data) — the initial carry must already hold the annotation. Wraps
    the JAX API spelling drift: ``jax.lax.pcast(..., to="varying")``
    (current) vs ``jax.lax.pvary``; on jaxlibs that predate VMA typing
    altogether (0.4.x — shard_map's ``check_rep`` has no per-value
    annotation), the correct annotation is no annotation.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_names, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_names)  # pragma: no cover
    return x


@lru_cache(maxsize=None)
def _gather_fn(mesh: Mesh):
    # check_vma=False: the gathered result is device-invariant by
    # construction, but shard_map's varying-axis inference cannot prove
    # replication through all_gather.
    return jax.jit(
        compat_shard_map(
            lambda s: jax.lax.all_gather(s, DATA_AXIS, axis=0, tiled=True),
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=P(),
            check_vma=False,
        )
    )


@lru_cache(maxsize=None)
def _reduce_fn(mesh: Mesh, op: str):
    reducer = {"psum": jax.lax.psum, "pmean": jax.lax.pmean}[op]
    # Each member device contributes one row of x; squeeze the per-device
    # shard's leading dim so the reduced result has shape x.shape[1:].
    return jax.jit(
        compat_shard_map(
            lambda s: reducer(jnp.squeeze(s, axis=0), DATA_AXIS),
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=P(),
        )
    )


def group_all_gather(trial: TrialMesh, x):
    """All-gather per-device shards within one trial group.

    ``x`` has leading dim == group size (one row per member device, the
    analog of each rank contributing one tensor). Returns the gathered
    array, identical on (replicated across) every member device —
    matching ``dist.all_gather``'s every-rank-gets-all contract
    (``example-subgroup.py:25-33``).
    """
    return _gather_fn(trial.mesh)(x)


def group_psum(trial: TrialMesh, x):
    """Sum per-device shards (leading dim == group size) across the group.

    The explicit form of DDP's gradient all-reduce scoped to a subgroup
    (``vae-hpo.py:130``). Every member device holds the full sum.
    """
    return _reduce_fn(trial.mesh, "psum")(x)


def group_pmean(trial: TrialMesh, x):
    """Mean per-device shards across the group (DDP gradient averaging)."""
    return _reduce_fn(trial.mesh, "pmean")(x)


@lru_cache(maxsize=None)
def _sum_flags_fn(mesh: Mesh):
    from jax.sharding import NamedSharding

    return jax.jit(
        jnp.sum, out_shardings=NamedSharding(mesh, P())
    )


def group_all_ok(
    trial: TrialMesh,
    ok: bool,
    *,
    timeout_s: float | None = None,
    what: str = "group health agreement",
    error_cls: type | None = None,
) -> bool:
    """Cross-process health agreement scoped to ONE trial submesh.

    Returns True iff every process owning a device of this group called
    with ``ok=True``. The TPU-native failure-detection primitive: the
    health bit rides the same submesh the trial runs on — one tiny SPMD
    reduction over the group's devices, touching only the group's owner
    processes. No world-scoped barrier, so unrelated trials stay
    decoupled (quirk Q3 stays fixed; contrast the reference, where a
    failed rank simply hangs the world's collectives — SURVEY.md §5
    "failure detection").

    Collective contract: every owner process must call this at the same
    point in its dispatch sequence for this group (the HPO driver calls
    it at trial setup and at each epoch boundary — deterministic
    cadence).

    ``timeout_s`` bounds the wait on the reduction's result fetch: an
    owner process that died before contributing leaves the collective
    blocked forever — with a deadline it becomes a ``TimeoutError``
    naming ``what`` (``parallel.cluster.call_with_timeout`` semantics:
    the stuck collective is abandoned on a daemon thread; the caller
    should treat the group as lost and restart against the sweep
    ledger). ``None``/0 = unbounded, the pre-timeout behavior.
    ``error_cls`` names the raised type on expiry (default
    ``AgreementTimeout``; the HPO driver's device-sync points pass
    ``cluster.WedgedCollective`` for the exit-code contract).
    """
    import time

    import numpy as np

    from multidisttorch_tpu.parallel.cluster import (
        AgreementTimeout,
        call_with_timeout,
    )
    from multidisttorch_tpu.telemetry.events import get_bus

    if error_cls is None:
        error_cls = AgreementTimeout

    def agree() -> bool:
        n = trial.size
        # One element per member device, each process filling its
        # addressable shards with its own health bit.
        sharding = trial.sharding(tuple(trial.mesh.axis_names))
        local = np.zeros(1, np.float32) if ok else np.ones(1, np.float32)
        if jax.process_count() == 1:
            flags = jax.device_put(
                np.full(n, local[0], np.float32), sharding
            )
        else:
            flags = jax.make_array_from_callback(
                (n,), sharding, lambda idx: local
            )
        failed = _sum_flags_fn(trial.mesh)(flags)
        return float(failed) == 0.0

    bus = get_bus()
    if bus is None:
        return call_with_timeout(agree, timeout_s, what, error_cls=error_cls)
    # Telemetry seam: agreement latency is the sweep's cross-process
    # sync cost — a slow peer shows up here long before it times out.
    t0 = time.perf_counter()
    try:
        agreed = call_with_timeout(
            agree, timeout_s, what, error_cls=error_cls
        )
    except BaseException as e:
        bus.emit(
            "agreement",
            group_id=trial.group_id,
            what=what,
            outcome=f"error: {type(e).__name__}",
            wall_s=round(time.perf_counter() - t0, 6),
        )
        raise
    bus.emit(
        "agreement",
        group_id=trial.group_id,
        what=what,
        outcome="agreed" if agreed else "peer_failure",
        local_ok=ok,
        wall_s=round(time.perf_counter() - t0, 6),
    )
    return agreed


@lru_cache(maxsize=None)
def _min_flags_fn(mesh: Mesh):
    from jax.sharding import NamedSharding

    return jax.jit(jnp.min, out_shardings=NamedSharding(mesh, P()))


def group_min_scalar(
    trial: TrialMesh,
    value: int,
    *,
    timeout_s: float | None = None,
    what: str = "group min agreement",
    error_cls: type | None = None,
) -> int:
    """Agree on the MINIMUM of a per-process integer across one trial
    submesh's owner processes — the on-mesh sibling of
    :func:`group_all_ok` for value (not just health) agreement.

    Note the RECOVERY path deliberately does not use this: the
    cross-host restore agreement (``train.checkpoint.
    agreed_restore_step``) rides the coordination-service sideband
    (``cluster.agree_min_int``) instead, because it must work when the
    device world is the broken thing — and on backends without
    cross-process XLA computations. This on-mesh form is for healthy
    in-band coordination (e.g. agreeing a shared schedule knob on ICI
    without touching the coordinator).

    Same collective contract, timeout semantics, and ``error_cls``
    behavior as :func:`group_all_ok` (one tiny submesh-scoped
    reduction; no world barrier).
    """
    import numpy as np

    from multidisttorch_tpu.parallel.cluster import (
        AgreementTimeout,
        call_with_timeout,
    )

    if error_cls is None:
        error_cls = AgreementTimeout

    def agree() -> int:
        n = trial.size
        sharding = trial.sharding(tuple(trial.mesh.axis_names))
        local = np.full(1, int(value), np.int32)
        if jax.process_count() == 1:
            flags = jax.device_put(np.full(n, local[0], np.int32), sharding)
        else:
            flags = jax.make_array_from_callback(
                (n,), sharding, lambda idx: local
            )
        return int(_min_flags_fn(trial.mesh)(flags))

    return call_with_timeout(agree, timeout_s, what, error_cls=error_cls)
