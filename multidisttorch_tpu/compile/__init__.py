"""The compile subsystem: kill the compile tax (ROADMAP item 2).

Every new shape bucket used to pay the full ``lower→compile`` on the
driver's hot path, and the persistent XLA cache had been disabled since
PR 1 (deserialized XLA:CPU executables corrupt the heap on the pinned
jaxlib). Three layers re-attack it:

- :mod:`~multidisttorch_tpu.compile.registry` +
  :mod:`~multidisttorch_tpu.compile.programs` — a process-lifetime
  **executable registry** keyed by the program vocabulary (shape
  bucket + baked scalar hypers + submesh devices). One compile per
  program, ever; coalesced; timed; shared with the cost books.
- :mod:`~multidisttorch_tpu.compile.farm` — the **background AOT
  precompile farm**: ``run_hpo(precompile=True)`` (or
  ``MDT_PRECOMPILE=1``) walks the sweep's pending configs at entry and
  compiles every bucket's programs on worker threads, so trial
  admission never blocks the host loop on XLA.
- :mod:`~multidisttorch_tpu.compile.cache` — the **quarantined
  persistent cache**: CRC32 sidecars + a subprocess canary-execute
  protocol gate jax's on-disk executable cache; TPU enables after a
  passed canary, XLA:CPU stays quarantined-only (sacrificial
  processes excepted).
- :mod:`~multidisttorch_tpu.compile.coldstart` — the **cold-start
  books' benchmark**: ``bench.py --coldstart`` measures cold vs
  precompiled vs cache-warm admission latency with a bit-parity gate.

See docs/COMPILE.md for the safety model and protocols.
"""

from multidisttorch_tpu.compile import programs  # noqa: F401
from multidisttorch_tpu.compile.cache import (  # noqa: F401
    cache_probe,
    canary_quarantine,
    enable_quarantined_cache,
    scan_cache,
    seal_cache,
)
from multidisttorch_tpu.compile.farm import PrecompilePool  # noqa: F401
from multidisttorch_tpu.compile.registry import (  # noqa: F401
    ExecutableRegistry,
    get_executable_registry,
)
