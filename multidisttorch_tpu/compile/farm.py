"""Background AOT precompile farm: shape buckets compile off the hot path.

At ``run_hpo`` entry the driver knows every pending ``TrialConfig`` —
which means it knows every distinct train program the sweep will ever
compile (the shape-bucket key plus the single-path scalar hypers, see
:mod:`~multidisttorch_tpu.compile.programs`). The farm walks that plan
ONCE, derives each work item's programs for its *predicted* submesh
(the driver's initial queue order assigns item *j* to local group
``j % n_groups``; a mispredicted placement is just a registry miss —
the admission path compiles inline and the executable still lands in
the registry for the next same-program trial on that group), and
compiles them on worker threads via the registry's one compile routine.
XLA releases the GIL during compilation, so N workers genuinely overlap
— and overlap with the first trials' *training*, which is the whole
point: by the time submesh g finishes trial k, trial k+1's program is
already an executable.

Admission therefore **never blocks the host loop on XLA** when the farm
is on: a trial whose program is still ``COMPILING`` waits
*cooperatively* (its generator yields, other submeshes keep stepping);
a trial whose program the farm has not started yet ``claim()``s it and
compiles inline (exactly the pre-farm behavior, with books).

Torn-shutdown safety: ``shutdown()`` flips a flag workers check between
jobs — queued jobs are dropped, the in-flight compile (daemon thread)
finishes into the registry harmlessly, and nothing the driver holds is
invalidated. ``run_hpo`` shuts the farm down on every exit path.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Callable, Optional, Sequence

from multidisttorch_tpu.compile import programs as _programs
from multidisttorch_tpu.compile.registry import (
    PENDING,
    SOURCE_PRECOMPILE,
    ExecutableRegistry,
    get_executable_registry,
)
from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.telemetry.metrics import get_registry as _metrics


def default_workers() -> int:
    env = os.environ.get("MDT_PRECOMPILE_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(4, (os.cpu_count() or 2) - 1))


def _emit(kind: str, **data) -> None:
    bus = get_bus()
    if bus is not None:
        bus.emit(kind, **data)


class PrecompilePool:
    """Worker threads draining a deque of (key, builder) compile jobs
    into the executable registry."""

    def __init__(
        self,
        registry: Optional[ExecutableRegistry] = None,
        workers: Optional[int] = None,
    ):
        self.registry = registry or get_executable_registry()
        self.workers = workers or default_workers()
        self._jobs: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._threads: list[threading.Thread] = []
        self._shutdown = False
        self._in_flight = 0
        self.submitted = 0

    # -- job intake ---------------------------------------------------

    def submit(self, key: tuple, builder: Callable[[], tuple]) -> bool:
        """Queue one program: ``builder() -> (jit_fn, avals)`` runs on
        the worker (step-factory construction is itself host work worth
        keeping off the driver loop). Deduped on the registry entry —
        a program already scheduled/compiled/claimed is skipped."""
        if not self.registry.schedule(key):
            return False
        with self._lock:
            if self._shutdown:
                # Un-schedule: the entry just created would otherwise
                # sit PENDING forever (shutdown's release loop only
                # covers jobs that made it into the queue) and stall a
                # later admission on this key for the full wait.
                self.registry.release(key)
                return False
            self._jobs.append((key, builder))
            self.submitted += 1
            self._wake.notify()
            self._ensure_workers()
        _emit(
            "precompile_scheduled",
            program=_programs.program_label(key),
            program_kind=key[0],
        )
        return True

    def plan_sweep(
        self,
        items: Sequence[tuple],
        groups: Sequence,
        *,
        max_lanes: int = 8,
    ) -> int:
        """Derive and submit the whole sweep's compile jobs from the
        driver's work items (``("single"|"bucket", [(i, cfg), ...])``),
        predicting item *j*'s submesh as ``groups[j % len(groups)]``
        (the driver's initial pop order). Primary programs (the one the
        first dispatch needs — multi when fused, else train) are queued
        before tail/secondary programs so the farm's first finished
        executables are the ones admissions are waiting on."""
        from multidisttorch_tpu.hpo.driver import stack_bucket_key

        if not groups:
            return 0
        primary: list[tuple] = []
        secondary: list[tuple] = []
        for j, (kind, members) in enumerate(items):
            g = groups[j % len(groups)]
            cfg = members[0][1]
            bucket = stack_bucket_key(cfg)
            if kind == "bucket":
                lanes = min(len(members), max_lanes)
                tkey = _programs.stacked_train_key(g, bucket, lanes)
                mkey = _programs.stacked_multi_key(g, bucket, lanes)

                def sbuilder(which, g=g, cfg=cfg, lanes=lanes):
                    steps = _programs.build_stacked_steps(g, cfg)
                    avals = _programs.stacked_avals(cfg, lanes)
                    return steps[which], avals[which]

                if cfg.fused_steps > 1:
                    primary.append((mkey, lambda b=sbuilder: b("multi")))
                    secondary.append((tkey, lambda b=sbuilder: b("train")))
                else:
                    primary.append((tkey, lambda b=sbuilder: b("train")))
            else:
                tkey = _programs.single_train_key(g, cfg, bucket)
                mkey = _programs.single_multi_key(g, cfg, bucket)

                def builder(which, g=g, cfg=cfg):
                    steps = _programs.build_single_steps(g, cfg)
                    avals = _programs.single_avals(cfg)
                    return steps[which], avals[which]

                # The state-init program sits on the admission path
                # BEFORE the train program (``_TrialRun.__init__``
                # materializes state, then run() admits the steps), so
                # it is queued immediately ahead of the item's primary
                # — the worker finishes them in consumption order.
                ikey = _programs.single_init_key(g, cfg, bucket)
                primary.append((
                    ikey,
                    lambda cfg=cfg: (
                        _programs.build_init_fn(cfg),
                        _programs.init_avals(),
                    ),
                ))
                if cfg.fused_steps > 1:
                    primary.append((mkey, lambda b=builder: b("multi")))
                    secondary.append((tkey, lambda b=builder: b("train")))
                else:
                    primary.append((tkey, lambda b=builder: b("train")))
        n = 0
        for key, builder in primary + secondary:
            if self.submit(key, builder):
                n += 1
        _emit("precompile_plan", jobs=n, items=len(items))
        reg = _metrics()
        if reg is not None:
            reg.counter("precompile_jobs").inc(n)
        return n

    # -- workers ------------------------------------------------------

    def _ensure_workers(self) -> None:
        # under self._lock
        while len(self._threads) < min(self.workers, len(self._jobs) or 1):
            t = threading.Thread(
                target=self._worker,
                name=f"mdt-precompile-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _worker(self) -> None:
        while True:
            with self._lock:
                while not self._jobs and not self._shutdown:
                    self._wake.wait(timeout=1.0)
                if self._shutdown and not self._jobs:
                    return
                if not self._jobs:
                    continue
                key, builder = self._jobs.popleft()
                self._in_flight += 1
            try:
                # A driver admission may have claimed the job (or an
                # identical-signature twin already compiled it) while
                # it sat queued — skip, don't duplicate the XLA work.
                if self.registry.status(key) != PENDING:
                    _emit(
                        "precompile_skipped",
                        program=_programs.program_label(key),
                    )
                    continue
                try:
                    fn, avals = builder()
                except Exception as e:  # noqa: BLE001 — a broken builder
                    # must not kill the worker; marking the entry FAILED
                    # (never leaving it PENDING) releases any admission
                    # cooperatively waiting on it to the jit fallback.
                    err = f"{type(e).__name__}: {e}"[:300]
                    self.registry.fail(key, err)
                    _emit(
                        "precompile_failed",
                        program=_programs.program_label(key),
                        error=err,
                    )
                    continue
                self.registry.compile_now(
                    key, fn, avals, source=SOURCE_PRECOMPILE
                )
            finally:
                with self._lock:
                    self._in_flight -= 1
                    self._wake.notify_all()

    # -- lifecycle ----------------------------------------------------

    def shutdown(self, wait: bool = False, timeout_s: float = 30.0) -> None:
        """Stop accepting and drop queued jobs. ``wait=True`` joins the
        in-flight compiles (bounded); the default leaves them to finish
        into the registry on their daemon threads — torn shutdown is
        safe by construction (the registry entry either becomes READY
        for a future sweep in this process, or stays COMPILING in a
        table nobody consults again)."""
        with self._lock:
            self._shutdown = True
            dropped_jobs = list(self._jobs)
            self._jobs.clear()
            self._wake.notify_all()
        # Release the dropped jobs' PENDING registry entries: an
        # admission waiting on "the farm will compile this" must see
        # the farm is gone and claim the program itself.
        for key, _ in dropped_jobs:
            self.registry.release(key)
        if dropped_jobs:
            _emit("precompile_dropped", jobs=len(dropped_jobs))
        if wait:
            for t in self._threads:
                t.join(timeout=timeout_s)

    def drain(self, timeout_s: float = 120.0) -> bool:
        """Block until every queued job has been compiled (tests/bench
        warmers). False on timeout."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        with self._lock:
            while self._jobs or self._in_flight:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._wake.wait(timeout=min(remaining, 0.5))
        return True
