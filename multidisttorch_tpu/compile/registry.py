"""Process-lifetime executable registry: one compile per program, ever.

The driver's compile tax had two shapes before this module existed:
every trial's first dispatch paid a full ``lower→compile`` inline on
the host loop (even when a bucket-twin had compiled the identical
program minutes earlier — jax's in-process caches do not connect a
fresh ``jax.jit`` closure to an existing executable), and the cost
books paid a SECOND lower+compile per program for ``cost_analysis``.
The registry is the one table both problems fold into:

- every compile of a driver train program goes through
  :meth:`ExecutableRegistry.compile_now` — timed, emitted as
  ``compile_start`` / ``compile_end`` events with per-program
  compile-seconds metrics, and coalesced (a second thread asking for a
  program mid-compile WAITS for the first instead of duplicating the
  XLA work);
- the resulting ``jax.stages.Compiled`` executable is held under the
  program key (:mod:`~multidisttorch_tpu.compile.programs`) so the next
  same-program admission — a bucket twin, a retry attempt, a refilled
  lane — takes it instantly (``cache_hit`` event);
- the cost books (``telemetry/device.py``) read
  ``compiled.cost_analysis()`` straight off the stored executable —
  the PR 4 re-lower+compile duplication is gone.

Ownership protocol (farm vs driver): a farm job starts ``PENDING``;
the worker moves it to ``COMPILING``; the driver's admission path
either ``take()``s a ``READY`` executable, cooperatively waits out a
``COMPILING`` one (yielding its submesh's host-loop slot, never
blocking other trials), or ``claim()``s a still-``PENDING`` job and
compiles inline itself (the farm worker sees ``CLAIMED`` and skips).
``FAILED`` is terminal and sticky — admission falls back to the plain
jit path and never retries a known-bad lowering.

Thread-safety: one registry lock guards the table; each entry carries
a condition for coalescing waits. Compiles themselves run OUTSIDE the
lock (XLA releases the GIL — farm workers genuinely overlap).

Size bound: the table is LRU-capped at ``MDT_REGISTRY_MAX_PROGRAMS``
(default 512) terminal entries, so a long-lived sweep *service* — many
``run_hpo`` calls over distinct baked-in hyperparameters — cannot grow
device-loaded executables without bound. An evicted program simply
recompiles on its next admission; within one sweep the cap is never
reached.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

from multidisttorch_tpu.compile.programs import program_label
from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.telemetry.metrics import get_registry as _metrics

PENDING = "pending"
COMPILING = "compiling"
READY = "ready"
FAILED = "failed"
CLAIMED = "claimed"

# How the executable came to exist — the `source` tag on compile
# events and the admission outcome vocabulary.
SOURCE_PRECOMPILE = "precompile"
SOURCE_INLINE = "inline"

# Registry size bound: a long-lived sweep service calling run_hpo over
# many hyperparameter values accumulates one device-loaded executable
# per distinct single-path program (lr/beta are baked into those keys)
# — without a cap that is unbounded resident host+device memory.
# Terminal entries (READY/FAILED) beyond the bound are dropped
# least-recently-used; the default is far above any one sweep, so
# within-sweep sharing (twins, retries, refills) never evicts.
MAX_PROGRAMS = int(os.environ.get("MDT_REGISTRY_MAX_PROGRAMS", "512"))


class Entry:
    """One program's lifecycle record. Public fields are read-mostly;
    mutations happen under the owning registry's lock."""

    __slots__ = (
        "key",
        "label",
        "status",
        "source",
        "compiled",
        "avals",
        "compile_s",
        "error",
        "cond",
        "hits",
        "seq",
    )

    def __init__(self, key: tuple, lock: threading.RLock):
        self.key = key
        self.label = program_label(key)
        self.status = PENDING
        self.source: Optional[str] = None
        self.compiled = None
        self.avals = None
        self.compile_s: Optional[float] = None
        self.error: Optional[str] = None
        self.cond = threading.Condition(lock)
        self.hits = 0
        self.seq = 0  # LRU stamp, bumped on every touch under the lock


def _emit(kind: str, **data) -> None:
    bus = get_bus()
    if bus is not None:
        # Submission-trace attribution (telemetry/trace.py): the
        # registry is program-keyed — a compile serves every member of
        # a co-packed placement — so the caller installs WHO is waiting
        # on it (trial ids + trace ids) in a thread-local and the
        # events ride it. Checked only when a bus exists: the
        # telemetry-off path never touches the thread-local.
        from multidisttorch_tpu.telemetry.trace import current_attribution

        attr = current_attribution()
        if attr is not None:
            data.setdefault("trial_ids", attr["trial_ids"])
            data.setdefault("traces", attr["traces"])
        bus.emit(kind, **data)


class ExecutableRegistry:
    """The process-wide program-key → compiled-executable table."""

    def __init__(self, max_programs: Optional[int] = None):
        self._lock = threading.RLock()
        self._entries: dict[tuple, Entry] = {}
        self._seq = 0
        self.max_programs = (
            MAX_PROGRAMS if max_programs is None else max_programs
        )
        self.evicted = 0

    # -- bookkeeping --------------------------------------------------

    def _touch(self, e: Entry) -> None:
        # under self._lock
        self._seq += 1
        e.seq = self._seq

    def _entry(self, key: tuple) -> Entry:
        e = self._entries.get(key)
        if e is None:
            e = self._entries[key] = Entry(key, self._lock)
            self._touch(e)
            self._maybe_evict()
        return e

    def _maybe_evict(self) -> None:
        # under self._lock. Only terminal entries are evictable:
        # PENDING/CLAIMED/COMPILING carry live farm/driver ownership
        # (and waiters on their condition), so they always survive.
        if self.max_programs <= 0 or len(self._entries) <= self.max_programs:
            return
        victims = sorted(
            (e for e in self._entries.values() if e.status in (READY, FAILED)),
            key=lambda e: e.seq,
        )
        for e in victims:
            if len(self._entries) <= self.max_programs:
                break
            del self._entries[e.key]
            self.evicted += 1
            reg = _metrics()
            if reg is not None:
                reg.counter("compile_registry_evictions").inc()

    def status(self, key: tuple) -> Optional[str]:
        with self._lock:
            e = self._entries.get(key)
            return e.status if e is not None else None

    def entry(self, key: tuple) -> Optional[Entry]:
        with self._lock:
            return self._entries.get(key)

    def schedule(self, key: tuple) -> bool:
        """Register a farm job: create the entry in ``PENDING``. False
        when the program already has an entry (ready, in flight, or
        claimed) — the farm submits once per distinct program."""
        with self._lock:
            if key in self._entries:
                return False
            self._entry(key)
            return True

    def release(self, key: tuple) -> bool:
        """Drop a still-PENDING entry (a farm shutdown returning its
        queued jobs): the program goes back to unknown, so the next
        admission claims and compiles it inline instead of waiting for
        a worker that will never come."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.status == PENDING:
                del self._entries[key]
                return True
            return False

    def claim(self, key: tuple) -> bool:
        """The driver takes ownership of a queued-but-unstarted job (or
        of a program the farm never saw): True means the caller should
        compile inline; the farm worker will skip a ``CLAIMED`` entry."""
        with self._lock:
            e = self._entry(key)
            if e.status == PENDING:
                e.status = CLAIMED
                return True
            return e.status == CLAIMED

    def fail(self, key: tuple, error: str) -> None:
        """Mark a program terminally FAILED (a farm builder that cannot
        even construct the jit fn/avals): waiters stop waiting, and
        every admission takes the jit fallback from here on."""
        with self._lock:
            e = self._entry(key)
            if e.status == READY:
                return
            e.status = FAILED
            e.error = error
            e.cond.notify_all()

    def begin(self, key: tuple, *, source: str) -> Optional[Entry]:
        """Move an entry to ``COMPILING`` (from PENDING/CLAIMED/new).
        None when someone else already owns it (compiling) or it is
        terminal (ready/failed) — the caller should coalesce or take."""
        with self._lock:
            e = self._entry(key)
            if e.status in (READY, FAILED, COMPILING):
                return None
            e.status = COMPILING
            e.source = source
            return e

    def take(self, key: tuple) -> Optional[Any]:
        """A READY program's executable, else None — the non-blocking
        admission read. Counts hits and emits ``cache_hit``."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.status != READY:
                return None
            e.hits += 1
            self._touch(e)
            label, source = e.label, e.source
        _emit("cache_hit", program=label, source=source)
        reg = _metrics()
        if reg is not None:
            reg.counter("compile_cache_hits", program=label).inc()
        return e.compiled

    def avals(self, key: tuple):
        with self._lock:
            e = self._entries.get(key)
            return e.avals if e is not None else None

    # -- the one compile routine --------------------------------------

    def compile_now(
        self,
        key: tuple,
        fn: Callable,
        avals: tuple,
        *,
        source: str = SOURCE_INLINE,
        wait_s: float = 600.0,
    ) -> Entry:
        """AOT-compile ``fn.lower(*avals).compile()`` under ``key``.

        Exactly one thread compiles a given key; a concurrent caller
        coalesces (waits on the entry condition, bounded by ``wait_s``)
        and returns the same entry — duplicate-signature farm jobs and
        a driver racing a farm worker cost ONE compile between them.
        Failures are recorded terminally (status FAILED, error text);
        the entry is returned either way — callers check ``status``.
        """
        with self._lock:
            e = self._entry(key)
            if e.status == READY or e.status == FAILED:
                return e
            if e.status == COMPILING:
                _emit("precompile_coalesced", program=e.label)
                reg = _metrics()
                if reg is not None:
                    reg.counter("compile_coalesced").inc()
                deadline = time.monotonic() + wait_s
                while e.status == COMPILING:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    e.cond.wait(timeout=min(remaining, 1.0))
                return e
            e.status = COMPILING
            e.source = source
        _emit(
            "compile_start", program=e.label, program_kind=key[0],
            source=source,
        )
        t0 = time.perf_counter()
        compiled = None
        error = None
        try:
            try:
                compiled = fn.lower(*avals).compile()
            except Exception as ex:  # noqa: BLE001 — a failed AOT
                # compile must degrade to the jit fallback, never kill
                # the sweep
                error = f"{type(ex).__name__}: {ex}"
        finally:
            # Terminal-status-always (even on BaseException, e.g. a
            # KeyboardInterrupt unwinding a farm worker): an entry
            # stuck COMPILING would spin every coalescing waiter to
            # its deadline.
            dt = time.perf_counter() - t0
            with self._lock:
                e.compile_s = dt
                if compiled is not None:
                    e.compiled = compiled
                    e.avals = avals
                    e.status = READY
                else:
                    e.error = error or "compile interrupted"
                    e.status = FAILED
                self._touch(e)
                e.cond.notify_all()
        _emit(
            "compile_end",
            program=e.label,
            program_kind=key[0],
            source=source,
            compile_s=round(dt, 4),
            ok=compiled is not None,
            **({"error": error[:300]} if error else {}),
        )
        reg = _metrics()
        if reg is not None:
            reg.counter("compiles", source=source).inc()
            reg.counter("compile_seconds", program=e.label).inc(dt)
            reg.counter("compile_seconds_total").inc(dt)
            if error:
                reg.counter("compile_failures").inc()
        return e

    # -- cost-book handoff (telemetry/device.py) ----------------------

    def executable_for_cost(self, key: tuple) -> Optional[Any]:
        """A READY executable for the cost books — no hit accounting,
        no events: this is the dedup read, not an admission."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.status != READY:
                return None
            self._touch(e)
            return e.compiled

    def snapshot(self) -> dict:
        """Per-program compile book: status, source, seconds, hits —
        the run summary / console's view of the registry."""
        with self._lock:
            return {
                e.label: {
                    "status": e.status,
                    "source": e.source,
                    "compile_s": e.compile_s,
                    "hits": e.hits,
                    "error": e.error,
                }
                for e in self._entries.values()
            }

    def reset(self) -> None:
        """Drop every entry (tests; also frees executables/devices)."""
        with self._lock:
            self._entries = {}


_registry = ExecutableRegistry()


def get_executable_registry() -> ExecutableRegistry:
    """The process singleton. Always exists — the registry is a perf
    layer, not telemetry; it only *emits* when a bus/metrics registry
    is live, and costs one dict lookup when idle."""
    return _registry
