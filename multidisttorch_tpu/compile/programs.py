"""Program specs: the one vocabulary for "which compiled step is this".

Every layer of the compile subsystem — the background precompile farm
(:mod:`~multidisttorch_tpu.compile.farm`), the driver's admission path
(``hpo/driver.py``), and the cost books
(``telemetry/device.py``) — must agree on three things about a train
program before an executable compiled by one can be used by another:

- its **key** (:func:`single_train_key` / :func:`stacked_train_key`
  etc.): the PR 4 memoization vocabulary — shape bucket + the scalar
  hypers that XLA bakes in as constants — EXTENDED with the submesh
  device fingerprint, because an executable is loaded onto specific
  devices and a bucket-twin compiled for group 0 cannot serve group 1
  (exception: the device-agnostic init program, whose output the
  driver places itself — :func:`single_init_key`);
- its **argument avals** (:func:`single_avals` / :func:`stacked_avals`):
  derived by ``jax.eval_shape`` over the SAME state constructors the
  driver materializes real states with (``train.steps.build_train_state``
  / ``build_stacked_train_state``), so a farm-compiled executable's
  input signature cannot drift from the arrays the driver will feed it;
- its **builder** (:func:`build_single_steps` / :func:`build_stacked_steps`):
  the literal ``make_*_step`` factory calls the driver makes, so the
  lowered HLO is the driver's program, not a reimplementation.

Scalar hypers matter for SINGLE-path keys: ``lr`` lives inside
``optax.adam``'s closures and ``beta`` multiplies the KL term — both
are compile-time constants, so two bucket-twins with different lr
compile to different executables. The stacked path passes hypers as
``(K,)`` arrays (``TrialHypers``), so ONE program serves the whole
bucket regardless of hypers — which is why its key carries the lane
count instead.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import TrialMesh
from multidisttorch_tpu.train.steps import (
    TrialHypers,
    build_stacked_train_state,
    build_train_state,
    make_multi_step,
    make_stacked_multi_step,
    make_stacked_train_step,
    make_train_step,
)

# Program kinds — the first element of every key, and the ``kind`` tag
# on every compile_* event.
SINGLE_TRAIN = "train"
SINGLE_MULTI = "multi"
SINGLE_INIT = "init"
STACKED_TRAIN = "stacked_train"
STACKED_MULTI = "stacked_multi"
# One whole PBT generation (S-step train scan + E-batch eval scan +
# in-program lane exchange) as one program: hpo/pbt.py's fused path.
PBT_GEN = "pbt_gen"
# MPMD pipeline stage programs (parallel/pipeline.py MpmdPipeline):
# each stage of a cross-submesh pipelined trial owns a DISTINCT
# (forward, backward, update) program triple pinned to that stage's
# submesh — per-stage programs are first-class registry citizens, so a
# re-placed/retried pipelined trial's stages come back as cache hits.
PIPE_FWD = "pipe_fwd"
PIPE_BWD = "pipe_bwd"
PIPE_UPDATE = "pipe_update"


def mesh_fingerprint(trial: TrialMesh) -> tuple:
    """The device identity an executable is pinned to: the ordered
    global device ids of the trial's submesh. Two groups with identical
    shapes still get distinct fingerprints — XLA loads an executable
    onto concrete devices, so sharing across groups is never legal."""
    return tuple(d.id for d in trial.devices)


def single_train_key(trial: TrialMesh, cfg, bucket_key: tuple) -> tuple:
    return (
        SINGLE_TRAIN,
        bucket_key,
        (float(cfg.lr), float(cfg.beta)),
        mesh_fingerprint(trial),
    )


def single_multi_key(trial: TrialMesh, cfg, bucket_key: tuple) -> tuple:
    return (
        SINGLE_MULTI,
        bucket_key,
        (float(cfg.lr), float(cfg.beta)),
        mesh_fingerprint(trial),
    )


def single_init_key(trial: TrialMesh, cfg, bucket_key: tuple) -> tuple:
    """The state-init program's key. Unlike the train programs, init
    never reads the scalar hypers (``optax.adam(lr).init`` is
    ``zeros_like``; lr only enters at update), so lr/beta twins SHARE
    one init executable — the extra slot is None to keep the key shape
    uniform. It is also the one DEVICE-AGNOSTIC program: the init fn
    is jitted with no sharding/device pinning (the driver
    ``device_put``s its output onto the trial's submesh afterward), so
    the mesh slot is empty and every group shares one compile instead
    of N groups each paying for a bit-identical lowering."""
    return (SINGLE_INIT, bucket_key, None, ())


def stacked_train_key(
    trial: TrialMesh, bucket_key: tuple, lanes: int
) -> tuple:
    return (STACKED_TRAIN, bucket_key, int(lanes), mesh_fingerprint(trial))


def stacked_multi_key(
    trial: TrialMesh, bucket_key: tuple, lanes: int
) -> tuple:
    return (STACKED_MULTI, bucket_key, int(lanes), mesh_fingerprint(trial))


def pbt_gen_key(
    trial: TrialMesh,
    bucket_key: tuple,
    *,
    lanes: int,
    steps_per_generation: int,
    eval_batches: int,
    n_exploit: int,
    perturb_factors,
    lr_min: float,
    lr_max: float,
) -> tuple:
    """The fused PBT generation program's key. Like the stacked train
    keys, per-lane lr/beta ride in as ``(K,)`` arrays, so hypers stay
    OUT; what XLA bakes in as constants here is the population
    *protocol* — lane count, scan lengths (train steps + eval batches),
    the exploit slot count, and the explore factor table / lr clip
    bounds inside :func:`~multidisttorch_tpu.train.steps.pbt_exchange`
    — so two populations sharing the protocol share one executable."""
    return (
        PBT_GEN,
        bucket_key,
        (
            int(lanes),
            int(steps_per_generation),
            int(eval_batches),
            int(n_exploit),
            tuple(float(f) for f in perturb_factors),
            float(lr_min),
            float(lr_max),
        ),
        mesh_fingerprint(trial),
    )


def pipeline_stage_keys(
    stage_meshes,
    cfg,
    bucket_key: tuple,
    *,
    microbatches: int,
) -> dict:
    """Registry keys for every program of an MPMD pipelined trial:
    ``{(which, stage): key}`` with ``which`` in fwd/bwd/update — the
    shape expected by ``MpmdPipeline(registry_keys=...)``. The extra
    slot bakes what XLA bakes: the stage index, stage count, microbatch
    count (the schedule's static shapes), the scalar hypers the
    single-path programs bake (lr enters the update closure, beta the
    loss), and the zero_update mode — a sharded-update trial's
    programs pin data-sharded opt-state layouts a replicated twin's
    executable cannot serve (the same hazard ``aot_eligible`` guards
    on the single path). Each key carries ITS stage's mesh
    fingerprint — stage 0's executable can never serve stage 1's
    submesh."""
    kinds = {"fwd": PIPE_FWD, "bwd": PIPE_BWD, "update": PIPE_UPDATE}
    out = {}
    n_stages = len(stage_meshes)
    for s, mesh in enumerate(stage_meshes):
        for which, kind in kinds.items():
            out[(which, s)] = (
                kind,
                bucket_key,
                (
                    int(s),
                    int(n_stages),
                    int(microbatches),
                    float(cfg.lr),
                    float(cfg.beta),
                    bool(getattr(cfg, "zero_update", False)),
                ),
                mesh_fingerprint(mesh),
            )
    return out


def program_label(key: tuple) -> str:
    """Human-readable program name for events/metrics/console — the
    bucket signature, lane count or hypers, and the anchor device, in
    one short string (e.g. ``stacked_train:bs128-h400-z20-f1-K4@d0``).
    Labels feed telemetry events, so an unexpected key shape degrades
    to ``repr`` instead of raising."""
    try:
        return _program_label(key)
    except Exception:  # noqa: BLE001 — a label must never raise
        return repr(key)


def _program_label(key: tuple) -> str:
    kind, bucket, extra, mesh = key
    bs, hidden, latent, fused, grad_accum, remat = bucket
    sig = f"bs{bs}-h{hidden}-z{latent}-f{fused}"
    if grad_accum and grad_accum != 1:
        sig += f"-ga{grad_accum}"
    if remat:
        sig += "-rm"
    if kind in (STACKED_TRAIN, STACKED_MULTI):
        sig += f"-K{extra}"
    elif kind in (PIPE_FWD, PIPE_BWD, PIPE_UPDATE):
        stage, n_stages, microbatches = extra[:3]
        sig += f"-s{stage}of{n_stages}-M{microbatches}"
    elif kind == PBT_GEN:
        lanes, spg, ebatches, n_exploit = extra[:4]
        sig += f"-K{lanes}-S{spg}-E{ebatches}-x{n_exploit}"
    elif kind == SINGLE_INIT:
        pass  # init bakes no hypers — lr/beta twins share it
    else:
        # Single-path programs bake lr/beta in as constants — two
        # bucket-twins with different hypers are different executables
        # and must not share a label (the snapshot/console key).
        lr, beta = extra
        sig += f"-lr{lr:g}"
        if beta != 1.0:
            sig += f"-b{beta:g}"
    # The init program carries no device pinning (empty mesh slot) —
    # its label says so instead of claiming an anchor device.
    return f"{kind}:{sig}@d{mesh[0]}" if mesh else f"{kind}:{sig}@shared"


def _rng_aval():
    return jax.eval_shape(lambda: jax.random.key(0))


def _leaf_sig(tree: Any) -> tuple:
    return tuple(
        (tuple(x.shape), str(x.dtype)) for x in jax.tree.leaves(tree)
    )


def avals_match(avals: Any, args: Any) -> bool:
    """Whether ``args`` (real arrays or avals, a tuple of the call's
    positional arguments) structurally match a compiled entry's
    ``avals`` — same leaf count, shapes, and dtypes. The admission
    guard: a mismatch means the builder vocabulary drifted from the
    driver's real arrays, and the right move is the jit fallback, not
    a call-time TypeError inside the sweep loop."""
    try:
        return _leaf_sig(avals) == _leaf_sig(args)
    except Exception:  # noqa: BLE001 — guard must never raise
        return False


def default_model(cfg) -> VAE:
    """The default trial model family (the only family the farm and
    stacking cover — custom ``model_builder`` trials compile inline)."""
    return VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)


def single_avals(cfg, model: Optional[VAE] = None) -> dict:
    """Argument avals for the classic path's programs, derived from the
    same constructors the driver materializes real args with:
    ``{"train": (state, batch, rng), "multi": (state, chunk, rng)|None}``.
    """
    model = model or default_model(cfg)
    tx = optax.adam(cfg.lr)
    state = jax.eval_shape(
        lambda: build_train_state(model, tx, jax.random.key(0))
    )
    rng = _rng_aval()
    batch = jax.ShapeDtypeStruct(
        (cfg.batch_size, model.input_dim), jnp.float32
    )
    out = {"train": (state, batch, rng), "multi": None}
    if cfg.fused_steps > 1:
        chunk = jax.ShapeDtypeStruct(
            (cfg.fused_steps, cfg.batch_size, model.input_dim), jnp.float32
        )
        out["multi"] = (state, chunk, rng)
    return out


def stacked_avals(template, lanes: int, model: Optional[VAE] = None) -> dict:
    """Argument avals for a stacked bucket's programs:
    ``{"train": (state, hypers, batch, base_rngs, lane_steps),
    "multi": (...)|None}`` — stacked state/hypers/rngs shaped by the
    same ``build_stacked_train_state`` / ``TrialHypers.stack`` the
    bucket runner uses."""
    model = model or default_model(template)
    lanes = int(lanes)
    state = jax.eval_shape(
        lambda: build_stacked_train_state(model, list(range(lanes)))
    )
    hypers = jax.eval_shape(
        lambda: TrialHypers.stack([1e-3] * lanes, [1.0] * lanes)
    )
    base_rngs = jax.eval_shape(
        lambda: jnp.stack([jax.random.key(i) for i in range(lanes)])
    )
    lane_steps = jax.ShapeDtypeStruct((lanes,), jnp.int32)
    batch = jax.ShapeDtypeStruct(
        (lanes, template.batch_size, model.input_dim), jnp.float32
    )
    out = {
        "train": (state, hypers, batch, base_rngs, lane_steps),
        "multi": None,
    }
    if template.fused_steps > 1:
        chunk = jax.ShapeDtypeStruct(
            (
                template.fused_steps,
                lanes,
                template.batch_size,
                model.input_dim,
            ),
            jnp.float32,
        )
        out["multi"] = (state, hypers, chunk, base_rngs, lane_steps)
    return out


def build_init_fn(cfg, model: Optional[VAE] = None):
    """The state-init program: ``jit(rng -> un-placed TrainState)`` —
    the same :func:`~multidisttorch_tpu.train.steps.build_train_state`
    the driver materializes with, jitted so the farm can AOT-compile
    it. Init is pure elementwise RNG sampling + ``zeros_like`` (no
    matmul reassociation surface), so the compiled program's state is
    bit-identical to the eager path's (regression-tested)."""
    model = model or default_model(cfg)
    tx = optax.adam(cfg.lr)
    return jax.jit(lambda rng: build_train_state(model, tx, rng))


def init_avals() -> tuple:
    """Argument avals for the init program: one typed rng key."""
    return (_rng_aval(),)


def build_single_steps(
    trial: TrialMesh, cfg, model: Optional[VAE] = None
) -> dict:
    """The classic path's jit step functions — the exact factory calls
    ``_TrialRun.__init__`` makes for the default family."""
    model = model or default_model(cfg)
    tx = optax.adam(cfg.lr)
    train = make_train_step(
        trial, model, tx, beta=cfg.beta, remat=cfg.remat,
        grad_accum=cfg.grad_accum,
    )
    multi = (
        make_multi_step(
            trial, model, tx, beta=cfg.beta, remat=cfg.remat,
            grad_accum=cfg.grad_accum,
        )
        if cfg.fused_steps > 1
        else None
    )
    return {"train": train, "multi": multi}


def build_stacked_steps(
    trial: TrialMesh, template, model: Optional[VAE] = None
) -> dict:
    """The stacked bucket's jit step functions — the exact factory
    calls ``_StackedBucketRun.__init__`` makes."""
    model = model or default_model(template)
    kw = dict(remat=template.remat, grad_accum=template.grad_accum)
    train = make_stacked_train_step(trial, model, **kw)
    multi = (
        make_stacked_multi_step(trial, model, **kw)
        if template.fused_steps > 1
        else None
    )
    return {"train": train, "multi": multi}


def pbt_gen_avals(
    model: VAE,
    *,
    lanes: int,
    steps_per_generation: int,
    eval_batches: int,
    batch_size: int,
) -> tuple:
    """Argument avals for the fused PBT generation program:
    ``(state, hypers, batches, eval_batches, eval_weights, base_rngs,
    lane_steps, gen, explore_key)`` — state/hypers/rngs shaped by the
    same constructors ``hpo/pbt.py`` materializes real arrays with."""
    lanes = int(lanes)
    state = jax.eval_shape(
        lambda: build_stacked_train_state(model, list(range(lanes)))
    )
    hypers = jax.eval_shape(
        lambda: TrialHypers.stack([1e-3] * lanes, [1.0] * lanes)
    )
    base_rngs = jax.eval_shape(
        lambda: jnp.stack([jax.random.key(i) for i in range(lanes)])
    )
    batches = jax.ShapeDtypeStruct(
        (steps_per_generation, lanes, batch_size, model.input_dim),
        jnp.float32,
    )
    eval_b = jax.ShapeDtypeStruct(
        (eval_batches, batch_size, model.input_dim), jnp.float32
    )
    eval_w = jax.ShapeDtypeStruct((eval_batches, batch_size), jnp.float32)
    lane_steps = jax.ShapeDtypeStruct((lanes,), jnp.int32)
    gen = jax.ShapeDtypeStruct((), jnp.int32)
    return (
        state, hypers, batches, eval_b, eval_w, base_rngs, lane_steps,
        gen, _rng_aval(),
    )


def build_pbt_generation(
    trial: TrialMesh,
    model: VAE,
    *,
    n_exploit: int,
    perturb_factors,
    lr_min: float,
    lr_max: float,
):
    """The fused PBT generation jit fn — the exact factory call the
    fused ``run_pbt`` path makes
    (:func:`~multidisttorch_tpu.train.steps.make_pbt_generation_step`)."""
    from multidisttorch_tpu.train.steps import make_pbt_generation_step

    return make_pbt_generation_step(
        trial,
        model,
        n_exploit=int(n_exploit),
        perturb_factors=tuple(float(f) for f in perturb_factors),
        lr_min=float(lr_min),
        lr_max=float(lr_max),
    )
