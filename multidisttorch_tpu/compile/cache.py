"""Quarantined persistent executable cache: trust is earned per run.

PR 1 had to disable jax's persistent compilation cache outright:
deserialized XLA:CPU executables corrupted the heap on the pinned
jaxlib (``utils/compile_cache.py`` — the seed suite's resume segfault),
and a corrupted heap fails *later, somewhere else*, so no in-process
check can clear it. This module re-opens the cache behind two
mechanical defenses plus a policy gate, so "is the cache safe here?"
stops being a guess:

1. **Per-entry CRC32 sidecars** (the checkpoint layer's pattern,
   ``train/checkpoint.py``): :func:`seal_cache` records each entry's
   CRC32+length in a ``*.mdtcrc`` sidecar; :func:`scan_cache` verifies
   every entry on the way in and MOVES failures (bit-rot, torn writes,
   unsealed files of unknown provenance) to ``quarantine/`` — jax sees
   a miss and cold-compiles, never a garbled blob.
2. **Subprocess canary-execute quarantine** (:func:`canary_quarantine`):
   before any trial process enables cache *reads*, three sacrificial
   children prove the full deserialize-and-run path on THIS toolchain:
   a cold child (cache off) banks the reference output bits; a warmup
   child (cache on) guarantees the canary entry exists on disk; a warm
   child (cache on) necessarily deserializes it, runs the canary batch,
   and must **bit-match** the cold reference. A crash, hang, or
   mismatch in the warm child is the PR 1 failure mode caught in a
   process we built to lose — the verdict quarantines the entries and
   the trial process never loads them.
3. **Backend gate** (:func:`cache_policy`): a passed canary enables the
   cache in-process on **TPU** (the production cold-start path). On
   **XLA:CPU the cache stays quarantined-only** even after a passed
   canary — the known corruption is nondeterministic-late, so
   deserialized CPU executables only ever run in processes explicitly
   marked sacrificial (``MDT_CACHE_SACRIFICIAL=1``, e.g. the coldstart
   bench's warm child, which is parity-gated against the cold child) or
   under the pre-existing force knob (``MDT_FORCE_COMPILE_CACHE=1``).

:func:`enable_quarantined_cache` composes the three into the one safe
opt-in: scan → canary → gate → enable (or a classified refusal). The
preflight engine (``utils/preflight.py``) reuses :func:`cache_probe`
for its compile-cache stage.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time
import zlib
from typing import Callable, Optional

from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.utils.compile_cache import default_cache_dir

SIDECAR_SUFFIX = ".mdtcrc"
QUARANTINE_DIR = "quarantine"

# Verdict taxonomy (closed): how an enable attempt resolved.
ENABLED = "enabled"
QUARANTINED_ONLY = "quarantined_only"  # canary passed; CPU policy says
# deserialized executables stay in sacrificial children
CANARY_MISMATCH = "canary_mismatch"
CANARY_CRASHED = "canary_crashed"
CANARY_TIMEOUT = "canary_timeout"
SCAN_ONLY = "scan_only"  # canary skipped; cache not enabled

CANARY_TIMEOUT_S = int(os.environ.get("MDT_CACHE_CANARY_TIMEOUT_S", "120"))


def _emit(kind: str, **data) -> None:
    bus = get_bus()
    if bus is not None:
        bus.emit(kind, **data)


# -- sidecars ---------------------------------------------------------


def _is_entry(name: str) -> bool:
    """Cache-entry files we seal: everything except our sidecars and
    jax's ``*-atime`` access markers (rewritten on every read — a CRC
    over them would churn without meaning)."""
    return not name.endswith(SIDECAR_SUFFIX) and not name.endswith("-atime")


def _entries(cache_dir: str) -> list[str]:
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return []
    return sorted(
        n
        for n in names
        if _is_entry(n) and os.path.isfile(os.path.join(cache_dir, n))
    )


def _crc_file(path: str) -> tuple[int, int]:
    """Chunked CRC32+length of a file — cache entries on the TPU path
    are serialized executables that can run to hundreds of MB, so the
    whole-blob read would spike RAM by the largest entry."""
    crc = 0
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            n += len(chunk)
    return crc, n


def seal_cache(cache_dir: str, *, only: Optional[set] = None) -> dict:
    """Write/refresh a CRC32+length sidecar for every cache entry.

    Run after a writer process finishes compiling (the canary warmup
    child, the coldstart bench's seed child, a TPU sweep that just
    populated the cache): only sealed entries survive the next
    :func:`scan_cache` — an unsealed entry is an entry whose writer we
    cannot vouch for. ``only`` restricts sealing to the named entries:
    a caller that wrote SOME entries (the canary warmup) must not
    vouch for strangers that happen to share the dir."""
    sealed = refreshed = 0
    for name in _entries(cache_dir):
        if only is not None and name not in only:
            continue
        path = os.path.join(cache_dir, name)
        try:
            crc, n = _crc_file(path)
            rec = {"crc32": crc, "nbytes": n}
            side = path + SIDECAR_SUFFIX
            prev = None
            if os.path.exists(side):
                try:
                    with open(side, "r") as f:
                        prev = json.load(f)
                except (OSError, json.JSONDecodeError):
                    prev = None
            if prev == rec:
                continue
            tmp = side + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, side)
            if prev is None:
                sealed += 1
            else:
                refreshed += 1
        except OSError:
            continue
    return {"entries": len(_entries(cache_dir)), "sealed": sealed,
            "refreshed": refreshed}


def _quarantine(cache_dir: str, name: str) -> None:
    qdir = os.path.join(cache_dir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    src = os.path.join(cache_dir, name)
    shutil.move(src, os.path.join(qdir, name))
    side = src + SIDECAR_SUFFIX
    if os.path.exists(side):
        shutil.move(
            side, os.path.join(qdir, name + SIDECAR_SUFFIX)
        )


def scan_cache(cache_dir: str, *, quarantine: bool = True) -> dict:
    """Verify every entry against its sidecar; move failures aside.

    Rejection reasons (each a quarantined entry when ``quarantine``):
    ``unsealed`` (no sidecar — unknown provenance), ``sidecar_unreadable``,
    ``size_mismatch`` (torn write), ``crc_mismatch`` (bit rot /
    corruption). jax treats a moved entry as a plain cache miss, so a
    failed scan costs a cold compile, never a garbled executable."""
    checked = ok = 0
    rejected: list[dict] = []
    for name in _entries(cache_dir):
        path = os.path.join(cache_dir, name)
        checked += 1
        reason = None
        side = path + SIDECAR_SUFFIX
        if not os.path.exists(side):
            reason = "unsealed"
        else:
            # A sidecar that parses but is not {crc32: int, nbytes:
            # int} (bit rot can produce VALID JSON of the wrong shape)
            # is exactly as untrustworthy as one that doesn't parse —
            # classify, never crash: this scanner runs inside the
            # corruption-containment path itself.
            try:
                with open(side, "r") as f:
                    rec = json.load(f)
                want_crc = int(rec["crc32"])
                want_n = int(rec["nbytes"])
            except (OSError, json.JSONDecodeError, KeyError, TypeError,
                    ValueError):
                reason = "sidecar_unreadable"
            if reason is None:
                try:
                    crc, n = _crc_file(path)
                except OSError:
                    reason = "unreadable"
                if reason is None:
                    if n != want_n:
                        reason = "size_mismatch"
                    elif crc != want_crc:
                        reason = "crc_mismatch"
        if reason is None:
            ok += 1
            continue
        rejected.append({"entry": name, "reason": reason})
        if quarantine:
            try:
                _quarantine(cache_dir, name)
            except OSError:
                pass
    report = {"checked": checked, "ok": ok, "rejected": rejected,
              "quarantined": len(rejected) if quarantine else 0}
    _emit("cache_scan", dir=cache_dir, **{
        "checked": checked, "ok": ok, "quarantined": report["quarantined"],
    })
    return report


# -- subprocess canary ------------------------------------------------

_CANARY_CODE = """
import sys
cache_dir = sys.argv[1]
if cache_dir != "-":
    import jax
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import jax, jax.numpy as jnp
import numpy as np

@jax.jit
def canary(x, k):
    y = jnp.tanh(x @ x.T)
    y = y + jax.random.normal(k, y.shape) * 1e-3
    return (y @ y).sum(axis=0)

x = jnp.linspace(0.0, 1.0, 32 * 16, dtype=jnp.float32).reshape(32, 16)
out = np.asarray(canary(x, jax.random.key(7)))
print("CANARYBITS|" + out.tobytes().hex())
"""


def _run_canary_child(
    mode: str,
    cache_dir: str,
    platform: Optional[str],
    timeout_s: float,
) -> dict:
    """One bounded sacrificial child: ``mode`` is ``cold`` (cache off —
    the trusted reference), ``warmup`` (cache on — guarantees the entry
    exists), or ``warm`` (cache on — necessarily deserializes). Shape
    mirrors ``utils/preflight.py``'s out-of-process probes: a wedged or
    crashing deserializer must never take the caller down."""
    env = dict(os.environ)
    # Each mode configures its cache via argv + jax.config ONLY — an
    # inherited cache env (a developer shell's JAX_COMPILATION_CACHE_DIR,
    # bench.py's CPU-fallback opt-in) would point the COLD child at the
    # suspect cache, and a cold reference that deserialized the same
    # corrupt entry as the warm child bit-matches it: the gate this
    # protocol exists for would pass the exact PR 1 failure.
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("MDT_FORCE_COMPILE_CACHE", None)
    if platform:
        env["JAX_PLATFORMS"] = platform
    arg = "-" if mode == "cold" else cache_dir
    t0 = time.perf_counter()
    try:
        p = subprocess.run(
            [sys.executable, "-c", _CANARY_CODE, arg],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return {
            "ok": False,
            "timeout": True,
            "error": f"canary {mode} child blocked past {timeout_s}s",
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    bits = None
    for line in p.stdout.splitlines():
        if line.startswith("CANARYBITS|"):
            bits = line[len("CANARYBITS|"):].strip()
    if p.returncode != 0 or bits is None:
        return {
            "ok": False,
            "timeout": False,
            "rc": p.returncode,
            "error": (
                f"canary {mode} child died rc={p.returncode} "
                "(deserialized-executable crash class)"
            ),
            "stderr_tail": p.stderr[-400:],
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    return {
        "ok": True,
        "bits": bits,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }


def canary_quarantine(
    cache_dir: str,
    *,
    platform: Optional[str] = None,
    timeout_s: float = CANARY_TIMEOUT_S,
    runner: Optional[Callable] = None,
    evict_on_failure: bool = True,
) -> dict:
    """The cold/warmup/warm bit-match protocol over ``cache_dir``.

    Returns ``{"passed": bool, "verdict": ..., "cold"/"warmup"/"warm":
    per-child records, "evicted": n}``. ``runner`` is injectable for
    tests (scripted children — a crash or mismatch can be drilled
    without a real broken jaxlib). On any warm-side failure the
    cache's entries are quarantined (``evict_on_failure``): a cache
    that cannot prove deserialize-and-run is a cache nobody loads.
    """
    run = runner or _run_canary_child
    out: dict = {"passed": False, "evicted": 0}
    cold = run("cold", cache_dir, platform, timeout_s)
    out["cold"] = cold
    if not cold.get("ok"):
        # Without a trusted reference there is no verdict to give —
        # classify on the cold child's own failure shape.
        out["verdict"] = (
            CANARY_TIMEOUT if cold.get("timeout") else CANARY_CRASHED
        )
        return out
    os.makedirs(cache_dir, exist_ok=True)
    before = set(_entries(cache_dir))
    warmup = run("warmup", cache_dir, platform, timeout_s)
    out["warmup"] = warmup
    # Seal ONLY the warmup child's own new entries: those are the ones
    # whose provenance this protocol just established. Pre-existing
    # unsealed strangers stay unsealed (the probe path scans without
    # quarantining, so they may still be present here).
    seal_cache(
        cache_dir, only={n for n in _entries(cache_dir) if n not in before}
    )
    if not warmup.get("ok"):
        out["verdict"] = (
            CANARY_TIMEOUT if warmup.get("timeout") else CANARY_CRASHED
        )
        if evict_on_failure:
            out["evicted"] = _evict_all(cache_dir)
        return out
    warm = run("warm", cache_dir, platform, timeout_s)
    out["warm"] = warm
    if not warm.get("ok"):
        out["verdict"] = (
            CANARY_TIMEOUT if warm.get("timeout") else CANARY_CRASHED
        )
        if evict_on_failure:
            out["evicted"] = _evict_all(cache_dir)
        return out
    if warm.get("bits") != cold.get("bits"):
        out["verdict"] = CANARY_MISMATCH
        if evict_on_failure:
            out["evicted"] = _evict_all(cache_dir)
        return out
    out["passed"] = True
    out["verdict"] = "passed"
    return out


def _evict_all(cache_dir: str) -> int:
    """Quarantine every entry: the deserializer itself failed the
    canary, so no entry in this dir may be loaded by anyone but a
    sacrificial child."""
    n = 0
    for name in _entries(cache_dir):
        try:
            _quarantine(cache_dir, name)
            n += 1
        except OSError:
            pass
    return n


# -- policy + the safe opt-in ----------------------------------------


def is_sacrificial_process() -> bool:
    """Whether this process has declared itself expendable — allowed to
    load deserialized executables on backends the policy otherwise
    quarantines (the coldstart bench's warm child sets this)."""
    return os.environ.get("MDT_CACHE_SACRIFICIAL") == "1"


def cache_policy(platform: str, *, sacrificial: Optional[bool] = None) -> str:
    """Where a passed canary leads: ``enabled`` (TPU — the production
    cold-start path this subsystem exists for; or a process that
    declared itself sacrificial / forced), ``quarantined_only``
    (XLA:CPU default — the known PR 1 corruption class fails late, so
    even a passed canary only licenses sacrificial children)."""
    if platform == "tpu":
        return ENABLED
    if sacrificial if sacrificial is not None else is_sacrificial_process():
        return ENABLED
    if os.environ.get("MDT_FORCE_COMPILE_CACHE") == "1":
        return ENABLED
    return QUARANTINED_ONLY


def _enable(cache_dir: str) -> bool:
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — the cache is an optimization
        return False
    return True


def cache_probe(
    cache_dir: Optional[str] = None,
    *,
    platform: Optional[str] = None,
    canary: bool = True,
    timeout_s: float = CANARY_TIMEOUT_S,
    runner: Optional[Callable] = None,
) -> dict:
    """Read-side probe without enabling anything: sidecar scan report +
    (optionally) one canary protocol run. The preflight engine's
    compile-cache stage (``utils/preflight.py``) and ``tools/preflight
    --compile-cache`` both consume this.

    The probe is non-destructive by design: the scan REPORTS rejects
    without quarantining them and a failed canary does NOT evict — a
    transient child timeout on a loaded host must not throw away a
    production cache's accumulated compiles. Mutation (quarantine +
    evict-on-failure) belongs to :func:`enable_quarantined_cache`,
    the path that would actually load the entries."""
    cache_dir = cache_dir or default_cache_dir()
    out: dict = {"cache_dir": cache_dir}
    out["scan"] = scan_cache(cache_dir, quarantine=False)
    if canary:
        out["canary"] = canary_quarantine(
            cache_dir, platform=platform, timeout_s=timeout_s,
            runner=runner, evict_on_failure=False,
        )
        out["usable"] = bool(out["canary"]["passed"])
    else:
        out["canary"] = None
        out["usable"] = False
    return out


def enable_quarantined_cache(
    cache_dir: Optional[str] = None,
    *,
    platform: Optional[str] = None,
    scan: bool = True,
    canary: bool = True,
    sacrificial: Optional[bool] = None,
    timeout_s: float = CANARY_TIMEOUT_S,
    runner: Optional[Callable] = None,
) -> dict:
    """The safe opt-in: scan → canary → backend gate → enable.

    Returns a verdict dict — ``{"enabled": bool, "verdict": one of
    enabled/quarantined_only/canary_*/scan_only, "scan": ...,
    "canary": ..., "cache_dir": ...}``. The invariant callers rely on:
    **this process's jax config points at the cache only when the
    verdict is** ``enabled`` **— which requires a passed canary** (or
    an explicit ``canary=False`` + force, which is the caller saying
    "I am the canary"). Everything else leaves the config untouched
    and the sweep cold-compiling, exactly as safe as PR 1's disable.
    """
    cache_dir = cache_dir or default_cache_dir()
    out: dict = {"cache_dir": cache_dir, "enabled": False}
    if platform is None:
        import jax

        platform = jax.default_backend()
    out["platform"] = platform
    if scan:
        out["scan"] = scan_cache(cache_dir)
    if not canary:
        out["verdict"] = SCAN_ONLY
        _emit("cache_quarantined", dir=cache_dir, reason=SCAN_ONLY)
        return out
    can = canary_quarantine(
        cache_dir, platform=platform, timeout_s=timeout_s, runner=runner,
    )
    out["canary"] = can
    _emit(
        "cache_canary",
        dir=cache_dir,
        verdict=can["verdict"],
        passed=can["passed"],
        evicted=can.get("evicted", 0),
    )
    if not can["passed"]:
        out["verdict"] = can["verdict"]
        _emit("cache_quarantined", dir=cache_dir, reason=can["verdict"])
        return out
    policy = cache_policy(platform or "", sacrificial=sacrificial)
    if policy != ENABLED:
        out["verdict"] = policy
        _emit("cache_quarantined", dir=cache_dir, reason=policy)
        return out
    if _enable(cache_dir):
        out["enabled"] = True
        out["verdict"] = ENABLED
        _emit("cache_enabled", dir=cache_dir, platform=platform)
    else:
        out["verdict"] = SCAN_ONLY
        _emit("cache_quarantined", dir=cache_dir, reason="enable_failed")
    return out
