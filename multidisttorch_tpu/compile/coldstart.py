"""Cold-start benchmark: cold vs precompiled vs cache-warm admission.

The compile subsystem's banked evidence (``bench.py --coldstart``). One
fixed multi-bucket sweep — ``len(COLDSTART_HIDDENS)`` shape buckets
(distinct hidden dims), one trial each, one submesh, so every admission
is serialized and visible — is run to completion in FRESH child
processes, one per mode (a child per mode is what makes "cold" honest:
jax's in-process caches cannot leak executables between modes):

- **cold** — no farm, no persistent cache: every admission pays the
  full inline ``lower→compile`` (the pre-PR baseline, now timed and
  attributed by the registry).
- **precompiled** — ``run_hpo(precompile=True)``: the farm compiles all
  four programs on worker threads at entry; the first admission waits
  cooperatively, the rest take finished executables.
- **seed** (measurement-free) — warms the persistent cache directory
  with the sweep's programs and seals the entries (CRC sidecars).
- **cache-warm** — the full subsystem, as a restarted service would
  run it: the quarantined cache path end-to-end (sidecar scan →
  subprocess canary bit-match gate → sacrificial enable — this IS the
  XLA:CPU policy: the warm child is expendable by construction and
  parity-gated below) PLUS the farm, whose workers now deserialize
  from disk instead of compiling — admission cost drops below the
  compile-from-scratch farm's.

Per-trial **admission latency** is ``first_dispatch − attempt_start``
off the child's telemetry stream (setup + compile — the cold-start cost
a sweep-as-a-service front door charges each trial). Gates:

- ``parity``: every trial's final train/test losses BIT-identical
  (float hex) across cold, precompiled, and cache-warm — an executable
  that arrived by farm thread or disk deserialization must be the same
  program, or the whole subsystem is disqualified.
- ``admission_blocked_on_compile`` (farm mode): no admission compiled
  inline on the host loop — every program arrived by registry hit or
  cooperative wait.
- ``speedup_cold_over_precompiled`` ≥ 2 and cache-warm mean below
  precompiled mean (the acceptance targets; recorded either way).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

# The fixed sweep: 4 shape buckets (hidden_dim), one trial per bucket,
# single submesh. Several epochs of training per trial so the farm's
# background compiles genuinely overlap foreground training (the
# service shape: admission cost amortizes against real work, and the
# worker stays ahead of the admission cadence).
COLDSTART_HIDDENS = (64, 96, 128, 160, 192, 224)
COLDSTART_ROWS = 2048
COLDSTART_BATCH = 64
COLDSTART_EPOCHS = 8
CHILD_TIMEOUT_S = int(os.environ.get("MDT_COLDSTART_CHILD_TIMEOUT_S", "600"))


def coldstart_configs():
    from multidisttorch_tpu.hpo.driver import TrialConfig

    return [
        TrialConfig(
            trial_id=i,
            epochs=COLDSTART_EPOCHS,
            batch_size=COLDSTART_BATCH,
            lr=1e-3,
            seed=7,
            hidden_dim=h,
            latent_dim=16,
        )
        for i, h in enumerate(COLDSTART_HIDDENS)
    ]


def _child_main(mode: str, out_dir: str, tel_dir: str, cache_dir: str) -> int:
    """One mode's sweep in THIS (child) process. Prints the result line
    the parent parses; telemetry lands under ``tel_dir``."""
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.compile import cache as _cache
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import run_hpo

    telemetry.configure(tel_dir)
    cache_rec = None
    if mode == "seed":
        # Cache writer: plain enable (this child is sacrificial by
        # role — it exists to populate the dir), then seal what landed.
        _cache._enable(cache_dir)
    elif mode == "warm":
        cache_rec = _cache.enable_quarantined_cache(
            cache_dir, sacrificial=True
        )
    train = synthetic_mnist(COLDSTART_ROWS)
    test = synthetic_mnist(256)
    t0 = time.perf_counter()
    results = run_hpo(
        coldstart_configs(),
        train,
        test,
        num_groups=1,
        out_dir=out_dir,
        save_images=False,
        verbose=False,
        precompile=(mode in ("farm", "warm")),
    )
    wall = time.perf_counter() - t0
    if mode == "seed":
        sealed = _cache.seal_cache(cache_dir)
    else:
        sealed = None
    out = {
        "mode": mode,
        "wall_s": round(wall, 3),
        "sealed": sealed,
        "cache": (
            {
                "enabled": cache_rec["enabled"],
                "verdict": cache_rec["verdict"],
                "scan": cache_rec.get("scan"),
                "canary_passed": bool(
                    (cache_rec.get("canary") or {}).get("passed")
                ),
            }
            if cache_rec is not None
            else None
        ),
        "trials": [
            {
                "trial_id": r.trial_id,
                "status": r.status,
                "steps": r.steps,
                "train_hex": float(r.final_train_loss).hex(),
                "test_hex": float(r.final_test_loss).hex(),
            }
            for r in results
        ],
    }
    print("COLDSTART|" + json.dumps(out))
    return 0


def _run_child(
    mode: str, work_dir: str, cache_dir: str, timeout_s: int
) -> dict:
    tel_dir = os.path.join(work_dir, f"tel_{mode}")
    out_dir = os.path.join(work_dir, f"out_{mode}")
    os.makedirs(tel_dir, exist_ok=True)
    env = dict(os.environ)
    # Each mode configures its own cache explicitly — an inherited
    # cache env (bench.py's CPU-fallback opt-in, a developer shell)
    # would silently warm the cold leg and fake the whole comparison.
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("MDT_FORCE_COMPILE_CACHE", None)
    if mode in ("farm", "warm"):
        # Pin the farm width for machine-comparable artifacts: two
        # workers overlap each item's init+train compiles, so even
        # trial 0's admission waits on ONE compile wall, not a serial
        # queue (default_workers() would give a 2-core CI box a single
        # worker).
        env.setdefault("MDT_PRECOMPILE_WORKERS", "2")
    if mode == "warm":
        # The cache-warm child is sacrificial BY DECLARATION — the
        # env mark is what licenses deserialized executables on the
        # XLA:CPU quarantined-only policy (compile/cache.py).
        env["MDT_CACHE_SACRIFICIAL"] = "1"
    t0 = time.perf_counter()
    try:
        p = subprocess.run(
            [
                sys.executable,
                "-m",
                "multidisttorch_tpu.compile.coldstart",
                "--child",
                mode,
                "--out",
                out_dir,
                "--tel",
                tel_dir,
                "--cache",
                cache_dir,
            ],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return {
            "mode": mode,
            "ok": False,
            "error": f"child timed out after {timeout_s}s",
            "tel_dir": tel_dir,
        }
    rec = None
    for line in p.stdout.splitlines():
        if line.startswith("COLDSTART|"):
            try:
                rec = json.loads(line[len("COLDSTART|"):])
            except json.JSONDecodeError:
                rec = None
    if p.returncode != 0 or rec is None:
        return {
            "mode": mode,
            "ok": False,
            "error": (
                f"child rc={p.returncode} "
                "(a crash here in the warm mode is the deserialized-"
                "executable corruption class — quarantine held)"
            ),
            "stderr_tail": p.stderr[-600:],
            "tel_dir": tel_dir,
        }
    rec["ok"] = True
    rec["child_wall_s"] = round(time.perf_counter() - t0, 3)
    rec["tel_dir"] = tel_dir
    return rec


def _fold_admissions(tel_dir: str) -> dict:
    """Per-trial admission latencies + compile books off a child's
    telemetry stream (the run-summary fold, post-hoc)."""
    from multidisttorch_tpu.telemetry.events import EVENTS_NAME, read_events
    from multidisttorch_tpu.telemetry.export import SweepFold

    fold = SweepFold()
    path = os.path.join(tel_dir, EVENTS_NAME)
    for ev in read_events(path):
        fold.feed(ev)
    lat = [
        a["admission_s"]
        for a in fold.admissions
        if a.get("admission_s") is not None
    ]
    return {
        "admissions": fold.admissions,
        "latencies_s": [round(v, 4) for v in lat],
        "mean_admission_s": (
            round(sum(lat) / len(lat), 4) if lat else None
        ),
        "max_admission_s": round(max(lat), 4) if lat else None,
        "compile_books": fold.compile_books,
        "compiles": fold.compiles,
        "compile_s_total": fold.compile_s_total,
        "cache_hits": fold.cache_hits,
        "precompile": fold.precompile,
    }


def run_coldstart_bench(
    work_dir: str, *, timeout_s: int = CHILD_TIMEOUT_S
) -> dict:
    """The full protocol: cold → farm → seed → warm children, folded
    into one artifact dict (see module docstring for the gates)."""
    os.makedirs(work_dir, exist_ok=True)
    cache_dir = os.path.join(work_dir, "xla_cache")
    out: dict = {
        "protocol": "coldstart_v1",
        "buckets": len(COLDSTART_HIDDENS),
        "hidden_dims": list(COLDSTART_HIDDENS),
        "epochs": COLDSTART_EPOCHS,
        "batch_size": COLDSTART_BATCH,
        "rows": COLDSTART_ROWS,
        "modes": {},
    }
    for mode in ("cold", "farm", "seed", "warm"):
        rec = _run_child(mode, work_dir, cache_dir, timeout_s)
        if rec.get("ok") and mode != "seed":
            rec["books"] = _fold_admissions(rec["tel_dir"])
        out["modes"][mode] = rec

    cold = out["modes"]["cold"]
    farm = out["modes"]["farm"]
    warm = out["modes"]["warm"]

    def trials_hex(rec) -> Optional[dict]:
        if not rec.get("ok"):
            return None
        return {
            t["trial_id"]: (t["train_hex"], t["test_hex"], t["status"])
            for t in rec["trials"]
        }

    ref = trials_hex(cold)
    parity = ref is not None
    mismatches = []
    for name, rec in (("farm", farm), ("warm", warm)):
        th = trials_hex(rec)
        if th is None or th != ref:
            parity = False
            mismatches.append(name)
    out["parity"] = parity
    out["parity_mismatches"] = mismatches

    def mean_of(rec) -> Optional[float]:
        return (rec.get("books") or {}).get("mean_admission_s")

    cold_mean, farm_mean, warm_mean = (
        mean_of(cold), mean_of(farm), mean_of(warm),
    )
    out["cold_mean_admission_s"] = cold_mean
    out["precompiled_mean_admission_s"] = farm_mean
    out["cache_warm_mean_admission_s"] = warm_mean
    out["speedup_cold_over_precompiled"] = (
        round(cold_mean / farm_mean, 3)
        if cold_mean and farm_mean
        else None
    )
    out["cache_warm_below_precompiled"] = (
        warm_mean < farm_mean
        if warm_mean is not None and farm_mean is not None
        else None
    )
    # "Admission blocked on XLA" = some trial's program was compiled
    # inline on the host loop (outcome inline, or jit fallback — the
    # implicit first-dispatch compile). With the farm on, every
    # program must arrive by registry hit or cooperative wait.
    farm_adm = (farm.get("books") or {}).get("admissions") or []
    out["admission_blocked_on_compile"] = (
        any(a.get("outcome") in ("inline", "jit") for a in farm_adm)
        if farm.get("ok")
        else None
    )
    out["cache_verdict"] = (warm.get("cache") or {}).get("verdict") if \
        warm.get("ok") else None
    out["passed"] = bool(
        parity
        and out["speedup_cold_over_precompiled"] is not None
        and out["speedup_cold_over_precompiled"] >= 2.0
        and out["admission_blocked_on_compile"] is False
        and out["cache_warm_below_precompiled"] is True
    )
    return out


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="coldstart bench child/driver (see bench.py --coldstart)"
    )
    parser.add_argument("--child", default=None)
    parser.add_argument("--out", default=None)
    parser.add_argument("--tel", default=None)
    parser.add_argument("--cache", default=None)
    parser.add_argument("--work", default=None)
    args = parser.parse_args(argv)
    if args.child:
        return _child_main(args.child, args.out, args.tel, args.cache)
    import tempfile

    work = args.work or tempfile.mkdtemp(prefix="coldstart_")
    print(json.dumps(run_coldstart_bench(work), indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
