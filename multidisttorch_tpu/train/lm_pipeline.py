"""Pipeline-parallel TransformerLM: blocks staged over the pipe axis.

Completes the per-family parallelism matrix (the reference has no PP at
all — SURVEY.md §2c): the LM's transformer blocks ride the
shape-heterogeneous GPipe schedule (``parallel/pipeline.py``) as equal-
width stages, with the embedding lookup before the pipeline and the
final-norm + vocab head after it (both are resident on every device —
they're cheap next to the block stack, and keeping them outside lets
the staged bodies stay pure float-array functions, which is the
pipeline's contract). Attention inside a stage must be collective-free:
the dense default or the single-chip flash kernel — NOT a device ring
(a ring inside a ``lax.switch`` branch would need collectives only some
devices execute).

On a ``(data x pipe)`` trial mesh one jitted step trains DP x PP; grads
flow through the packed stage array and the embed/head params alike, so
a single Adam update covers the whole model.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from multidisttorch_tpu.models.transformer import Block, TransformerLM
from multidisttorch_tpu.parallel.mesh import TrialMesh
from multidisttorch_tpu.parallel.pipeline import (
    pipeline_apply_stages,
    stage_params_sharding,
)


def _stage_layers(num_layers: int, num_stages: int) -> list[list[int]]:
    """Contiguous, near-even block chunks; every stage non-empty."""
    if num_layers < num_stages:
        raise ValueError(
            f"{num_layers} blocks cannot fill {num_stages} pipeline stages"
        )
    base, rem = divmod(num_layers, num_stages)
    out, i = [], 0
    for s in range(num_stages):
        n = base + (1 if s < rem else 0)
        out.append(list(range(i, i + n)))
        i += n
    return out


def make_pipelined_lm(
    trial: TrialMesh,
    model: TransformerLM,
    params: Any,
    *,
    num_microbatches: int,
    attention: Optional[Callable] = None,
) -> tuple[Callable[[jax.Array, Any, jax.Array], jax.Array], jax.Array, Any]:
    """Stage ``model``'s blocks over ``trial``'s pipe axis.

    ``params`` is a plain ``TransformerLM`` param tree (from
    ``model.init`` / ``create_lm_state``). Returns ``(apply, packed,
    outer)``:

    - ``apply(packed, outer, tokens) -> (B, T, vocab) logits`` — pure
      and differentiable in both param arguments;
    - ``packed`` — the per-stage block params as one pipe-sharded
      array (place with ``parallel.pipeline.stage_params_sharding``);
    - ``outer`` — the embed / final-norm / head params that stay
      resident everywhere.

    ``attention`` overrides the staged blocks' attention (must be
    collective-free; default = the model's own, which must not be a
    ring — pass the dense default or ``make_flash_attention()``).
    """
    from multidisttorch_tpu.parallel.mesh import PIPE_AXIS

    num_stages = int(dict(trial.mesh.shape).get(PIPE_AXIS, 1))
    if num_stages < 2:
        raise ValueError(
            "trial mesh has no pipe axis of extent >= 2; carve one with "
            "setup_groups(..., pipeline_parallel=S)"
        )
    attn = attention if attention is not None else model.attention
    # Ring factories mark their callables carries_collectives=True
    # (shard_map + ppermute hops), which cannot run inside a lax.switch
    # stage branch that only some devices execute. Checked by VALUE,
    # not hasattr: make_flash_attention() sets it False and is staged
    # fine (a plain pallas_call is collective-free).
    if getattr(attn, "carries_collectives", False):
        raise ValueError(
            "staged attention must be collective-free; a ring callable "
            "cannot run inside a pipeline stage (use the dense default "
            "or make_flash_attention())"
        )
    if attn is None:
        from multidisttorch_tpu.ops.ring_attention import (
            dense_attention_reference,
        )

        attn = lambda q, k, v: dense_attention_reference(
            q, k, v, causal=True
        )

    stages = _stage_layers(model.num_layers, num_stages)
    # Stages compute at the model's own dtype (params stay f32 per the
    # pipeline's packing contract; the inter-stage carry is an f32
    # buffer, so a bf16 model pays one cast per stage boundary — the
    # within-stage math is unchanged). model.remat carries over:
    # per-block checkpointing composes with the staged schedule.
    block_cls = nn.remat(Block) if model.remat else Block
    block_mod = block_cls(
        d_model=model.d_model,
        num_heads=model.num_heads,
        attention=attn,
        dtype=model.dtype,
    )

    def stage_fn(layer_ids):
        def fn(p, x):
            for i in layer_ids:
                x = block_mod.apply({"params": p[f"block_{i}"]}, x)
            return x

        return fn

    stage_fns = [stage_fn(ids) for ids in stages]
    stage_params = [
        {f"block_{i}": params[f"block_{i}"] for i in ids} for ids in stages
    ]
    pp_apply, packed = pipeline_apply_stages(
        trial, stage_fns, stage_params, num_microbatches=num_microbatches
    )

    outer = {
        k: params[k] for k in ("tok_embed", "pos_embed", "ln_out", "head")
    }
    ln = nn.LayerNorm(dtype=model.dtype, param_dtype=jnp.float32)

    def apply(packed_arr, outer_params, tokens):
        _, t = tokens.shape
        if t > model.max_len:
            # Same trace-time contract as TransformerLM.__call__:
            # out-of-range pos-embed gathers clamp silently, not raise.
            raise ValueError(
                f"sequence length {t} exceeds max_len={model.max_len}"
            )
        x = jnp.take(
            outer_params["tok_embed"]["embedding"], tokens, axis=0
        ).astype(model.dtype)
        x = x + jnp.take(
            outer_params["pos_embed"]["embedding"], jnp.arange(t), axis=0
        ).astype(model.dtype)[None, :, :]
        x = pp_apply(packed_arr, x)
        x = ln.apply({"params": outer_params["ln_out"]}, x)
        # head computes in f32, matching TransformerLM's own head Dense
        return (
            x.astype(jnp.float32) @ outer_params["head"]["kernel"]
            + outer_params["head"]["bias"]
        )

    return apply, packed, outer


__all__ = [
    "make_pipelined_lm",
    "stage_params_sharding",
]
