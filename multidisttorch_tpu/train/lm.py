"""Language-model train/eval steps with sequence parallelism.

Next-token objective for :class:`models.transformer.TransformerLM`
under the same per-trial contract as the VAE/classifier steps. With
``sequence_parallel=True`` the token batch's TIME dimension is sharded
over the trial's data axis — the long-context regime where one
sequence exceeds a chip — and the model's ring attention exchanges K/V
blocks around the submesh ring while GSPMD reduces gradients over the
same axis. The full sequence length stays resident; only ``T/N`` of it
lives per chip.

Shift handling keeps shapes static and divisible (ring attention needs
``T % N == 0``): the model sees all ``T`` tokens, targets are the
input rolled left by one, and the final position is masked out of the
loss instead of slicing ``T-1``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh
from multidisttorch_tpu.train.steps import TrainState


def _logits(out):
    """Model outputs are logits, or (logits, aux) from the MoE LM."""
    return out[0] if isinstance(out, tuple) else out


def _filter_logits(logits, top_k, top_p):
    """Top-k / nucleus filtering for sampling, shared by both samplers.

    ``top_k``: keep the k highest logits per row. ``top_p``: keep the
    smallest set of tokens whose probability mass reaches p (the
    highest-probability token always survives). Both may combine.

    RANK-based, not value-threshold: one stable descending argsort
    (ties resolved in index order, so rank 0 is exactly ``argmax``),
    masks computed in sorted space, scattered back to vocab positions
    — exact counts even on tied or uniform logits, and one sort serves
    both filters.
    """
    b, v = logits.shape
    if top_k is not None and not 1 <= top_k <= v:
        raise ValueError(f"top_k={top_k} must be in [1, vocab={v}]")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    idx = jnp.argsort(-logits, axis=-1)  # descending, argmax-stable
    sorted_logits = jnp.take_along_axis(logits, idx, axis=-1)
    keep = jnp.ones((b, v), bool)
    if top_k is not None:
        keep &= jnp.arange(v)[None, :] < top_k
    if top_p is not None:
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # smallest prefix with mass >= p; the top token always stays
        keep &= jnp.concatenate(
            [jnp.ones((b, 1), bool), cum[:, :-1] < top_p], axis=-1
        )
    keep_vocab = (
        jnp.zeros((b, v), bool)
        .at[jnp.arange(b)[:, None], idx]
        .set(keep)
    )
    return jnp.where(keep_vocab, logits, jnp.float32(-jnp.inf))


def _validate_sampling(temperature, top_k, top_p, vocab_size=None) -> None:
    """Build-time validation shared by both sampler factories: bad
    values fail at construction, not on the first jitted call (and
    filters are never silently dropped by a greedy temperature).
    Factories know their model's vocab, so an out-of-range ``top_k``
    is also a construction error, not a first-call trace error."""
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k={top_k} must be >= 1")
    if top_k is not None and vocab_size is not None and top_k > vocab_size:
        raise ValueError(
            f"top_k={top_k} exceeds the model's vocab_size={vocab_size}"
        )
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    if temperature <= 0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p require temperature > 0 (greedy sampling "
            "ignores filters; refusing to drop them silently)"
        )


def _sample_token(logits, rng, temperature, top_k, top_p):
    """One draw shared by both samplers: greedy at temperature 0, else
    (optionally filtered) softmax-temperature sampling. Returns
    ``(token, new_rng)``."""
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1), rng
    rng, sub = jax.random.split(rng)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None or top_p is not None:  # static: no-op filters
        logits = _filter_logits(logits, top_k, top_p)  # cost nothing
    return jax.random.categorical(sub, logits, axis=-1), rng


def lm_loss_mean(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy; the last position is masked (its
    target would wrap around the roll)."""
    targets = jnp.roll(tokens, -1, axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    t = tokens.shape[1]
    w = (jnp.arange(t) < t - 1).astype(jnp.float32)[None, :]
    return jnp.sum(nll * w) / jnp.sum(w) / tokens.shape[0]


def _lm_shardings(trial: TrialMesh, sequence_parallel: bool, shardings):
    """The one copy of the LM input/state sharding contract shared by
    the train, eval, and scan-fused step builders: ``(B, T)`` tokens
    shard T over the data axis under sequence parallelism (batch
    replicated), else B (plain DP). ``(K, B, T)`` stacked chunks are
    the same contract with a leading unsharded scan axis — derived
    here from the tokens spec so the two can never drift."""
    repl = trial.replicated_sharding
    tokens_sh = (
        trial.sharding(None, DATA_AXIS)
        if sequence_parallel
        else trial.batch_sharding
    )
    spec = tuple(tokens_sh.spec) + (None,) * (2 - len(tokens_sh.spec))
    chunks_sh = trial.sharding(None, *spec)
    return repl, tokens_sh, chunks_sh, (repl if shardings is None else shardings)


def lm_chunk_sharding(trial: TrialMesh, *, sequence_parallel: bool = False):
    """Placement helper for ``make_lm_multi_step`` inputs: the
    ``(K, B, T)`` stacked-chunk ``NamedSharding`` (leading scan axis
    unsharded; B or T over the data axis per the tokens contract).
    Callers should ``device_put`` chunks with THIS rather than
    restating the spec — it is derived from the same ``_lm_shardings``
    source as the step builders, so placement can't drift from what
    the jitted program expects (which would trigger a resharding copy
    on every dispatch)."""
    return _lm_shardings(trial, sequence_parallel, None)[2]


def make_lm_train_step(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    *,
    sequence_parallel: bool = False,
    shardings: Any = None,
    aux_loss_weight: float = 1e-2,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """``step(state, tokens) -> (state, {loss})`` — ``tokens`` is
    ``(B, T) int32``; with ``sequence_parallel`` the T dimension is
    sharded over the data axis (batch replicated), otherwise B is
    sharded (plain DP). For activation rematerialization construct the
    model with ``TransformerLM(remat=True)`` — per-BLOCK checkpointing,
    the placement that actually cuts peak HBM (a whole-forward
    ``jax.checkpoint`` here would recompute everything and save
    nothing). A model returning ``(logits, aux)`` (the MoE LM's Switch
    load-balancing term) trains on
    ``lm_loss + aux_loss_weight * aux``."""
    repl, tokens_sh, _, state_sh = _lm_shardings(
        trial, sequence_parallel, shardings
    )
    step_fn = _build_lm_step_fn(model, tx, aux_loss_weight)
    return jax.jit(
        step_fn,
        in_shardings=(state_sh, tokens_sh),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def _build_lm_step_fn(model, tx, aux_loss_weight):
    """The un-jitted LM optimizer step shared by the single-dispatch and
    scan-fused factories (one copy of the loss/update math, so the two
    cannot drift)."""

    def step_fn(state: TrainState, tokens: jax.Array):
        def loss_fn(params):
            out = model.apply({"params": params}, tokens)
            loss = lm_loss_mean(_logits(out), tokens)
            if isinstance(out, tuple):
                loss = loss + aux_loss_weight * out[1]
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=new_params, opt_state=new_opt, step=state.step + 1
            ),
            {"loss": loss.astype(jnp.float32)},
        )

    return step_fn


def make_lm_multi_step(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    *,
    sequence_parallel: bool = False,
    shardings: Any = None,
    aux_loss_weight: float = 1e-2,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """K chained LM optimizer steps in ONE dispatch, via ``lax.scan``.

    The LM analog of :func:`train.steps.make_multi_step`, and for the
    same reason (docs/DISPATCH.md): a single LM step at bench scale is
    ~1 ms of device time on a v5e, the same order as one host enqueue,
    so a step-per-dispatch loop leaves the chip idle half the time.
    ``token_chunks`` is ``(K, B, T) int32`` — sharded over the submesh
    data axis on B (plain DP) or T (``sequence_parallel``) — and
    ``metrics['loss']`` comes back ``(K,)``, the same per-step logging
    contract as the single-step factory. Per-step activations do not
    accumulate across the scan (each iteration differentiates and
    updates inside its own body).
    """
    repl, _, chunks_sh, state_sh = _lm_shardings(
        trial, sequence_parallel, shardings
    )
    step_fn = _build_lm_step_fn(model, tx, aux_loss_weight)

    def multi_fn(state: TrainState, token_chunks: jax.Array):
        def body(s, toks):
            s, metrics = step_fn(s, toks)
            return s, metrics["loss"]

        state, losses = jax.lax.scan(body, state, token_chunks)
        return state, {"loss": losses}

    return jax.jit(
        multi_fn,
        in_shardings=(state_sh, chunks_sh),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def make_lm_eval_step(
    trial: TrialMesh,
    model: Any,
    *,
    sequence_parallel: bool = False,
    shardings: Any = None,
) -> Callable[[TrainState, jax.Array], dict]:
    """``eval(state, tokens) -> {loss, perplexity}`` — same next-token
    objective and token sharding contract as :func:`make_lm_train_step`,
    no gradient."""
    repl, tokens_sh, _, state_sh = _lm_shardings(
        trial, sequence_parallel, shardings
    )

    def eval_fn(state: TrainState, tokens: jax.Array):
        out = model.apply({"params": state.params}, tokens)
        loss = lm_loss_mean(_logits(out), tokens)
        return {
            "loss": loss.astype(jnp.float32),
            "perplexity": jnp.exp(loss).astype(jnp.float32),
        }

    return jax.jit(
        eval_fn, in_shardings=(state_sh, tokens_sh), out_shardings=repl
    )


def create_lm_state(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    example_len: Optional[int] = None,
    param_shardings: Any = None,
) -> TrainState:
    """Initialize and place an LM state on the trial submesh.

    ``example_len`` shapes the init dummy; for ring-attention models the
    sequence length must divide the trial's data-axis extent, so the
    default is ``8 * trial.data_size`` (always divisible; irrelevant to
    the resulting param shapes). ``param_shardings`` shards weights
    (e.g. ``parallel.fsdp.fsdp_param_shardings``) via the shared
    ``train.steps.place_sharded_state`` recipe — same contract as the
    VAE and classifier state creators.
    """
    from multidisttorch_tpu.train.steps import place_sharded_state

    if example_len is None:
        example_len = 8 * trial.data_size
    params = model.init(
        {"params": rng}, jnp.zeros((1, example_len), jnp.int32)
    )["params"]
    if param_shardings is not None:
        return place_sharded_state(trial, params, tx, param_shardings)
    return trial.device_put(
        TrainState(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )
    )


def make_lm_sample(
    trial: TrialMesh,
    model: Any,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    shardings: Any = None,
) -> Callable[[TrainState, jax.Array, int, jax.Array], jax.Array]:
    """Autoregressive sampling — the LM analog of the reference's
    prior-sample dump (vae-hpo.py:163-170: draw from the model, look at
    what it learned).

    ``sample(state, tokens, prompt_len, rng) -> (B, T) int32``: the
    ``(B, T)`` buffer holds the prompt in its first ``prompt_len``
    positions (the rest is ignored); positions ``prompt_len..T-1`` are
    filled autoregressively. Greedy at ``temperature=0``, else
    softmax-temperature sampling. Shapes stay static (one ``(B, T)``
    buffer; ``lax.fori_loop`` + ``dynamic_update_slice``) so one
    compilation serves every prompt length; each step recomputes the
    full prefix — O(T^2) attention per token, the simple exact
    formulation (a KV cache is a bandwidth optimization, not a
    semantics change). Causal attention guarantees the padding beyond
    the current position cannot influence the next token.

    ``prompt_len`` is clamped to >= 1: position 0 is always taken from
    the buffer (a BOS/seed token) — "unconditional" sampling is
    sampling conditioned on a chosen first token, never on buffer
    garbage. The buffer batch-shards over the trial's data axis like
    every other LM step (B must divide it).
    """
    _validate_sampling(
        temperature, top_k, top_p, getattr(model, "vocab_size", None)
    )
    repl = trial.replicated_sharding

    def sample_fn(
        state: TrainState, tokens: jax.Array, prompt_len, rng: jax.Array
    ):
        def body(i, carry):
            buf, rng = carry
            out = model.apply({"params": state.params}, buf)
            nxt, rng = _sample_token(
                _logits(out)[:, i - 1], rng, temperature, top_k, top_p
            )
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None].astype(buf.dtype), i, axis=1
            )
            return buf, rng

        start = jnp.maximum(prompt_len, 1)  # never index position -1
        buf, _ = jax.lax.fori_loop(
            start, tokens.shape[1], body, (tokens, rng)
        )
        return buf

    return jax.jit(
        sample_fn,
        in_shardings=(
            repl if shardings is None else shardings,
            trial.batch_sharding,
            None,
            repl,
        ),
        out_shardings=trial.batch_sharding,
    )
