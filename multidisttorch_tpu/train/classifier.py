"""Classifier train/eval steps — same per-trial submesh contract as the
VAE steps (BASELINE.md config 4: ResNet-18 HPO on the subgroup
scaffolding).

Identical execution model to ``train.steps``: params/opt state
replicated over the trial submesh, (images, labels) batch sharded over
the data axis, XLA-inserted gradient reduction. Reuses
:class:`train.steps.TrainState` so checkpointing and PBT transfer work
for classifiers unchanged.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from multidisttorch_tpu.ops.losses import softmax_cross_entropy_mean
from multidisttorch_tpu.parallel.mesh import TrialMesh
from multidisttorch_tpu.train.steps import TrainState


def create_classifier_state(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    param_shardings: Any = None,
) -> TrainState:
    """Initialize and place a classifier state on the trial submesh.

    ``param_shardings`` (e.g. ``models.resnet.resnet_tp_shardings``)
    shards weights over the submesh's model axis instead of the default
    DDP-style replication — same contract as
    ``train.steps.create_train_state``, including the eager optimizer
    init that lets each Adam moment inherit its weight's sharding.
    """
    from multidisttorch_tpu.train.steps import place_sharded_state

    params = model.init(
        {"params": rng}, jnp.zeros((1, model.input_dim), jnp.float32)
    )["params"]
    if param_shardings is None:
        state = TrainState(
            params=params,
            opt_state=tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        return trial.device_put(state)
    return place_sharded_state(trial, params, tx, param_shardings)


def _build_classifier_step_fn(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    grad_accum: int = 1,
) -> Callable:
    """Un-jitted classifier step body, shared by the single-step and
    scan-fused builders.

    ``grad_accum=A`` accumulates gradients over A equal microbatches
    (the shared ``train.steps.accumulate_gradients`` recipe — one copy
    of the scan/constraint logic); the classifier forward is
    deterministic, so the accumulated gradient equals the full-batch
    gradient exactly (up to summation order)."""
    from multidisttorch_tpu.train.steps import accumulate_gradients

    def microbatch(params, images, labels):
        logits = model.apply({"params": params}, images)
        loss = softmax_cross_entropy_mean(logits, labels)
        correct = jnp.sum(
            (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        )
        return loss, correct

    def step_fn(state: TrainState, images: jax.Array, labels: jax.Array):
        n = images.shape[0]
        if grad_accum == 1:
            (loss, correct), grads = jax.value_and_grad(
                microbatch, has_aux=True
            )(state.params, images, labels)
        else:
            loss, correct, grads = accumulate_gradients(
                trial,
                microbatch,
                state.params,
                (images, labels),
                grad_accum=grad_accum,
            )

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        return new_state, {
            "loss": loss.astype(jnp.float32),
            "accuracy": correct / n,
        }

    return step_fn


def make_classifier_train_step(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    *,
    shardings: Any = None,
    grad_accum: int = 1,
) -> Callable:
    """``step(state, images, labels) -> (state, {loss, accuracy})``.

    ``shardings`` (from ``train.steps.state_shardings`` on a
    tensor-parallel state) pins the state layout across steps, same as
    the VAE step builders. ``grad_accum`` accumulates over microbatches
    (see ``_build_classifier_step_fn``).
    """
    from multidisttorch_tpu.train.steps import _validate_grad_accum

    _validate_grad_accum(grad_accum)
    repl = trial.replicated_sharding
    data = trial.batch_sharding
    state_sh = repl if shardings is None else shardings
    return jax.jit(
        _build_classifier_step_fn(trial, model, tx, grad_accum),
        in_shardings=(state_sh, data, data),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def make_classifier_multi_step(
    trial: TrialMesh,
    model: Any,
    tx: optax.GradientTransformation,
    *,
    shardings: Any = None,
    grad_accum: int = 1,
) -> Callable:
    """K chained classifier train steps in ONE dispatch (``lax.scan``) —
    the labeled-data analog of ``train.steps.make_multi_step``.

    ``multi_step(state, images, labels) -> (state, metrics)`` with
    ``images``/``labels`` stacked as ``(K, batch, ...)`` (the sampler's
    ``epoch_chunks``/``stream_chunks`` shapes, sharded over the data
    axis on dim 1); metrics are per-step arrays of shape ``(K,)``.
    ``shardings`` pins a tensor-parallel state's layout, same as
    :func:`make_classifier_train_step` — without it a TP state would be
    silently resharded to replicated on every fused dispatch.
    ``grad_accum`` composes with fusion, same as the VAE multi-step.
    """
    from multidisttorch_tpu.parallel.mesh import DATA_AXIS
    from multidisttorch_tpu.train.steps import _validate_grad_accum

    _validate_grad_accum(grad_accum)
    repl = trial.replicated_sharding
    chunk = trial.sharding(None, DATA_AXIS)
    state_sh = repl if shardings is None else shardings
    step_fn = _build_classifier_step_fn(trial, model, tx, grad_accum)

    def multi_fn(state: TrainState, images: jax.Array, labels: jax.Array):
        def body(s, xs):
            s, m = step_fn(s, *xs)
            return s, m

        state, metrics = jax.lax.scan(body, state, (images, labels))
        return state, metrics

    return jax.jit(
        multi_fn,
        in_shardings=(state_sh, chunk, chunk),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def make_classifier_eval_step(
    trial: TrialMesh, model: Any, *, shardings: Any = None
) -> Callable:
    repl = trial.replicated_sharding
    data = trial.batch_sharding
    state_sh = repl if shardings is None else shardings

    def eval_fn(state: TrainState, images: jax.Array, labels: jax.Array):
        logits = model.apply({"params": state.params}, images)
        loss = softmax_cross_entropy_mean(logits, labels)
        correct = jnp.sum(
            (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        )
        return {"loss": loss.astype(jnp.float32), "correct": correct}

    return jax.jit(
        eval_fn, in_shardings=(state_sh, data, data), out_shardings=repl
    )
