"""KV-cache autoregressive decoding for the TransformerLM.

``train.lm.make_lm_sample`` is the exact-but-simple sampler: every new
token recomputes the whole prefix (O(T²) attention per token). Decode
on TPU is bandwidth-bound, and the real serving formulation caches
each block's K/V so one step touches O(T·D) cache plus O(D²) weights —
this module is that formulation, TPU-first: one static
``(L, 2, B, T, H, Dh)`` cache buffer carried through ``lax.fori_loop``
(in-place ``dynamic_update_slice`` writes — no per-step rebuild),
masked attention over the cache, a prefill loop for the prompt and a
generation loop that samples — so the rng stream matches
``make_lm_sample`` draw for draw.

The per-position math intentionally re-implements ``models.transformer
.Block``'s forward (a flax module can't thread an explicit cache
through an injected ``attention`` callable without changing its
signature); the decode-vs-model parity tests in
``tests/test_lm_decode.py`` pin the two together — if the Block
changes, those tests fail before any silent drift ships. Scope:
dense-block float32 ``TransformerLM`` only (MoE routing per decoded
token is a different schedule, and bf16 compute would need flax's
exact cast placement — both fall back to ``make_lm_sample``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from multidisttorch_tpu.parallel.mesh import TrialMesh
from multidisttorch_tpu.train.lm import _sample_token, _validate_sampling
from multidisttorch_tpu.train.steps import TrainState

_LN_EPS = 1e-6  # flax nn.LayerNorm default, which the model uses


def _layernorm(p, x):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + _LN_EPS) * p["scale"] + p["bias"]


def _dense(p, x):
    if "q" in p:  # int8 weight-only quantized layer (train/lm_quant.py)
        return (x @ p["q"].astype(jnp.float32)) * p["scale"] + p["bias"]
    return x @ p["kernel"] + p["bias"]


def make_cached_lm_sample(
    trial: TrialMesh,
    model: Any,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    shardings: Any = None,
) -> Callable[[TrainState, jax.Array, int, jax.Array], jax.Array]:
    """KV-cached ``sample(state, tokens, prompt_len, rng) -> (B, T)``.

    Same contract as :func:`train.lm.make_lm_sample` (prompt in the
    buffer's first ``prompt_len`` positions, clamped >= 1; the rest is
    filled autoregressively; greedy at ``temperature=0``; buffer
    batch-sharded; ``shardings`` for weight-sharded states), but each
    position costs one cache-masked attention instead of a full-prefix
    forward.
    """
    _validate_sampling(
        temperature, top_k, top_p, getattr(model, "vocab_size", None)
    )
    if model.dtype != jnp.float32:
        raise ValueError(
            "make_cached_lm_sample implements float32 compute; for a "
            f"{model.dtype} model use make_lm_sample (flax's exact "
            "cast placement is the model's business)"
        )
    if getattr(model, "num_experts", None) is not None:
        raise ValueError(
            "make_cached_lm_sample supports dense-block TransformerLM "
            "only; MoE routing per decoded token is a different "
            "schedule — use make_lm_sample"
        )
    # The decode path always computes exact dense causal attention.
    # The model's injected `attention` (ring / ring-flash / flash) is
    # assumed to be exactly that, computed a different way — true for
    # every callable this repo ships; a future non-equivalent attention
    # (sliding window, local masking) must not use this sampler.
    num_heads = model.num_heads
    num_layers = model.num_layers
    max_len = model.max_len

    def process_position(p, buf, caches, i):
        """Run position ``i`` through the stack, writing its K/V into
        every layer's cache; returns (caches, logits_at_i)."""
        b, t = buf.shape
        tok = jax.lax.dynamic_index_in_dim(buf, i, axis=1)[:, 0]
        x = (
            p["tok_embed"]["embedding"][tok]
            + p["pos_embed"]["embedding"][i]
        )
        d = x.shape[-1]
        dh = d // num_heads
        for layer in range(num_layers):
            bp = p[f"block_{layer}"]
            y = _layernorm(bp["ln_attn"], x)
            q = _dense(bp["q"], y).reshape(b, num_heads, dh)
            k = _dense(bp["k"], y).reshape(b, num_heads, dh)
            v = _dense(bp["v"], y).reshape(b, num_heads, dh)
            # in-place writes into the carried 6-D cache
            caches = jax.lax.dynamic_update_slice(
                caches, k[None, None, :, None], (layer, 0, 0, i, 0, 0)
            )
            caches = jax.lax.dynamic_update_slice(
                caches, v[None, None, :, None], (layer, 1, 0, i, 0, 0)
            )
            k_cache = caches[layer, 0]  # (B, T, H, Dh)
            v_cache = caches[layer, 1]
            s = jnp.einsum("bhd,bthd->bht", q, k_cache) / jnp.sqrt(
                jnp.float32(dh)
            )
            mask = (jnp.arange(t) <= i)[None, None, :]
            s = jnp.where(mask, s, -jnp.inf)
            w = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("bht,bthd->bhd", w, v_cache).reshape(b, d)
            x = x + _dense(bp["proj"], attn)

            y = _layernorm(bp["ln_mlp"], x)
            y = _dense(bp["up"], y)
            y = jax.nn.gelu(y)
            x = x + _dense(bp["down"], y)
        x = _layernorm(p["ln_out"], x)
        return caches, _dense(p["head"], x)  # (B, vocab)

    def sample_fn(
        state: TrainState, tokens: jax.Array, prompt_len, rng: jax.Array
    ):
        p = state.params
        b, t = tokens.shape
        if t > max_len:
            # same trace-time contract as the model's own forward
            raise ValueError(
                f"sequence length {t} exceeds max_len={max_len}"
            )
        d = p["tok_embed"]["embedding"].shape[1]
        dh = d // num_heads
        start = jnp.maximum(prompt_len, 1)

        # Prefill: ONE batched causal forward over the whole buffer
        # fills every layer's K/V slab (static shapes; no rng draws, so
        # the sampling stream still matches make_lm_sample exactly).
        # Cache slots >= start-1 are garbage-derived here, but the
        # generation loop rewrites slot i-1 before any read of it, so
        # only the prompt region's entries are ever consumed as-is.
        # Deliberately full-T (not prompt-only): prompt_len stays
        # traced, so one compilation serves every prompt length and the
        # ring paths' T-divisibility holds; a caller with short static
        # prompts can simply pass a shorter buffer. The attention is
        # the MODEL'S OWN callable (flash/ring keep memory linear on
        # long contexts; only the no-injection default uses the O(T²)
        # dense reference).
        if model.attention is not None:
            prefill_attn = model.attention
        else:
            from multidisttorch_tpu.ops.ring_attention import (
                dense_attention_reference,
            )

            prefill_attn = lambda q, k, v: dense_attention_reference(
                q, k, v, causal=True
            )

        x = (
            p["tok_embed"]["embedding"][tokens]
            + p["pos_embed"]["embedding"][jnp.arange(t)][None]
        )  # (B, T, d)
        slabs = []
        for layer in range(num_layers):
            bp = p[f"block_{layer}"]
            y = _layernorm(bp["ln_attn"], x)
            q = _dense(bp["q"], y).reshape(b, t, num_heads, dh)
            k = _dense(bp["k"], y).reshape(b, t, num_heads, dh)
            v = _dense(bp["v"], y).reshape(b, t, num_heads, dh)
            slabs.append(jnp.stack([k, v]))
            attn = prefill_attn(q, k, v)
            x = x + _dense(bp["proj"], attn.reshape(b, t, d))
            y = _layernorm(bp["ln_mlp"], x)
            x = x + _dense(bp["down"], jax.nn.gelu(_dense(bp["up"], y)))
        caches = jnp.stack(slabs)  # (L, 2, B, T, H, Dh)

        # Generate: position i-1's logits choose the token at i.
        def body(i, carry):
            buf, caches, rng = carry
            caches, logits = process_position(p, buf, caches, i - 1)
            nxt, rng = _sample_token(
                logits, rng, temperature, top_k, top_p
            )
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None].astype(buf.dtype), i, axis=1
            )
            return buf, caches, rng

        buf, _, _ = jax.lax.fori_loop(
            start, t, body, (tokens, caches, rng)
        )
        return buf

    repl = trial.replicated_sharding
    return jax.jit(
        sample_fn,
        in_shardings=(
            repl if shardings is None else shardings,
            trial.batch_sharding,
            None,
            repl,
        ),
        out_shardings=trial.batch_sharding,
    )
