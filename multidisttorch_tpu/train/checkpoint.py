"""Trial-state checkpoint/resume with crash-safe durability.

The reference persists nothing but PNGs (SURVEY.md §5 — no
``torch.save`` anywhere); checkpointing is an explicit upgrade required
by the PBT config (BASELINE.md config 5), which moves trial weights
between submeshes. State is a plain pytree (``train.steps.TrainState``),
serialized with flax's msgpack codec; restore re-places it onto any
target submesh — the same mechanism serves disk checkpoints and
inter-trial weight broadcast.

Durability contract (the fault-tolerance subsystem's foundation,
docs/RESILIENCE.md):

- **Atomic + durable writes**: tmp file, ``fsync``, ``os.replace``,
  directory ``fsync`` — a crash (or power loss) mid-write can never
  tear the visible ``state.msgpack``; either the old file or the new
  one is fully there.
- **CRC32-verified sidecars**: the metadata sidecar records the state
  file's CRC32 + byte count (``_integrity``), so a reader can tell a
  valid checkpoint from a corrupt/rotted one — and tell "state newer
  than sidecar" (a crash landed between the two replaces) from a
  healthy pair.
- **Keep-last-K retention** (``keep_last``): each save also retains an
  independent versioned copy ``{path}.v{step}`` (a real copy, not a
  hard-link — see :func:`_copy_replace`) and prunes beyond K, so a torn
  or corrupt latest still has valid history behind it.
- **:func:`restore_latest_valid`**: scan newest→oldest past torn/
  corrupt candidates and restore the first verifiable one — what
  retry-with-resume (``hpo/driver.py``) uses, where ``restore_state``'s
  strict single-file semantics would abandon recoverable work.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Callable, Optional

import jax
from flax import serialization

from multidisttorch_tpu.parallel.mesh import TrialMesh
from multidisttorch_tpu.train import ckpt_store

_VERSION_RE = re.compile(r"\.v(\d+)$")

# The RAM-snapshot restore's sentinel "path": restore_latest_valid /
# _restore_scan report it as the used candidate so books and logs can
# tell a warm re-place from a disk read.
RAM_SNAPSHOT = "<ram-snapshot>"


def default_format() -> str:
    """The checkpoint format new saves use: ``MDT_CKPT_FORMAT`` env
    (``v1`` = legacy full-msgpack, ``v2`` = sharded-native chunked
    manifests — the default). Restore always sniffs per file, so mixed
    directories (a v1 history under a v2 primary) scan back fine."""
    fmt = os.environ.get("MDT_CKPT_FORMAT", "v2")
    return "v1" if fmt == "v1" else "v2"


# Process-wide checkpoint data-plane counters (plain ints — always on;
# the zero-cost-when-off telemetry contract governs Event OBJECTS, not
# counter increments). The service books and bench read these.
_CKPT_LOCK = threading.Lock()
_CKPT_COUNTERS = {
    "saves": 0,
    "saves_v1": 0,
    "bytes_total": 0,
    "bytes_written": 0,
    "bytes_reused": 0,
    "chunks_written": 0,
    "restores": 0,
    "restores_ram": 0,
}


def ckpt_counters() -> dict:
    with _CKPT_LOCK:
        return dict(_CKPT_COUNTERS)


def reset_ckpt_counters() -> None:
    with _CKPT_LOCK:
        for k in _CKPT_COUNTERS:
            _CKPT_COUNTERS[k] = 0


def _count(**kw) -> None:
    with _CKPT_LOCK:
        for k, v in kw.items():
            _CKPT_COUNTERS[k] += v


class _SnapshotCache:
    """Process-wide RAM cache of the newest host-side checkpoint
    snapshot per checkpoint path (the snapshot-fast drain's warm
    restore source): a preempted trial re-placed in the SAME process
    restores straight from RAM instead of re-reading chunks.

    Entries are only ever written at the device→host fetch that also
    feeds the durable write, so an entry is always at least as new as
    the newest on-disk candidate for its path — within this process's
    continuous ownership of the path. Ownership breaks (a fabric
    replica losing/adopting a shard another process wrote to) must
    ``drop_under`` the affected directory: a stale RAM snapshot would
    otherwise resurrect old weights over the adopter's newer disk
    state. Bounded LRU (``MDT_SNAPSHOT_CACHE``)."""

    def __init__(self, max_entries: int = 8):
        self._lock = threading.Lock()
        self._max = max(1, int(max_entries))
        self._entries: OrderedDict[str, tuple[Any, dict]] = OrderedDict()

    def put(self, path: str, host_state: Any, meta: dict) -> None:
        key = os.path.abspath(path)
        with self._lock:
            self._entries.pop(key, None)
            self._entries[key] = (host_state, dict(meta))
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def get(self, path: str) -> Optional[tuple[Any, dict]]:
        key = os.path.abspath(path)
        with self._lock:
            got = self._entries.get(key)
            if got is not None:
                self._entries.move_to_end(key)
            return got

    def drop(self, path: str) -> None:
        with self._lock:
            self._entries.pop(os.path.abspath(path), None)

    def drop_under(self, prefix: str) -> int:
        """Invalidate every snapshot under a directory (fabric shard
        ownership changes). Returns how many were dropped."""
        pre = os.path.abspath(prefix).rstrip(os.sep) + os.sep
        with self._lock:
            dead = [k for k in self._entries if k.startswith(pre)]
            for k in dead:
                del self._entries[k]
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_SNAPSHOTS = _SnapshotCache(
    int(os.environ.get("MDT_SNAPSHOT_CACHE", "8"))
)


def snapshot_cache() -> _SnapshotCache:
    return _SNAPSHOTS


class CheckpointError(RuntimeError):
    """A checkpoint could not be read/verified (and no fallback said
    otherwise)."""


# One durability-helper family for the whole checkpoint layer, owned
# by the jax-free lower module (writer-unique tmp names — the
# snapshot-fast drain makes same-path writer overlap legal): without
# the dir fsync, a power loss after ``os.replace`` can resurrect the
# old file through the new name.
_fsync_dir = ckpt_store._fsync_dir
_write_atomic = ckpt_store.write_atomic


def _copy_replace(src: str, dst: str) -> None:
    """Atomically make ``dst`` an independent COPY of ``src``. A
    hard-link would be free, but it shares the inode: in-place
    corruption (bit rot, a torn rewrite) of the primary would garble
    its newest retained version with it, silently shrinking the
    scan-back depth from K to K-1. States here are small; pay the copy
    and keep the retention contract exact."""
    tmp = f"{dst}.{os.getpid()}.{threading.get_ident()}.tmp"
    shutil.copy2(src, tmp)
    os.replace(tmp, dst)


def save_state(
    state: Any,
    path: str,
    *,
    metadata: Optional[dict] = None,
    keep_last: int = 1,
    fsync: bool = True,
    format: Optional[str] = None,
    layouts: Any = None,
    chunk_bytes: Optional[int] = None,
    stats_out: Optional[dict] = None,
) -> str:
    """Serialize a state pytree (host-side) to ``path`` (msgpack).

    Writes are atomic AND durable (tmp file + ``fsync`` +
    ``os.replace`` + directory ``fsync``): a crash mid-write — including
    the interpreter exiting while a background checkpoint thread is
    running, or the host losing power — can never leave a torn
    ``state.msgpack`` that breaks a later ``resume``. The state file
    lands before the metadata sidecar, so a reader never sees metadata
    describing a state that isn't there yet; the sidecar carries the
    state's CRC32 (``_integrity``) so a reader can detect the converse
    tear (state replaced, crash before the sidecar followed).

    ``keep_last=K`` (K > 1) additionally retains the K most recent
    checkpoints as independent ``{path}.v{step}`` copies (version id =
    ``metadata['step']`` when present, else a monotonic counter), giving
    :func:`restore_latest_valid` history to scan back through when the
    latest is torn or corrupted. ``fsync=False`` opts out of the
    durability syncs (benchmarks on throwaway dirs).

    ``format`` picks the on-disk layout: ``"v1"`` is the legacy
    full-msgpack blob; ``"v2"`` (the :func:`default_format` when the
    caller passes None... which resolves to v1 here for direct callers'
    byte-stability — the DRIVER paths opt into v2 explicitly) writes a
    chunked manifest over a content-addressed store (``ckpt_store``):
    unchanged chunks are referenced, not rewritten, and ``keep_last``
    retains manifests (tiny) with chunks SHARED across versions under a
    refcounting GC. ``layouts`` optionally records the live state's
    shardings in the manifest; ``stats_out`` receives the save's
    written/reused byte split.
    """
    from multidisttorch_tpu.telemetry.events import get_bus

    fmt = format if format is not None else "v1"
    t0 = time.perf_counter()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _require_fully_addressable(state, "save_state")
    host_state = jax.device_get(state)
    # Deterministic test/bench seam: a bounded persist delay makes the
    # snapshot-vs-persist drain split measurable on states whose real
    # serialize+fsync cost is microseconds (docs/RESILIENCE.md).
    delay = float(os.environ.get("MDT_CKPT_PERSIST_DELAY_S", "0") or 0)
    if delay > 0:
        time.sleep(delay)
    if fmt == "v2":
        stats = _save_state_v2(
            host_state,
            path,
            metadata=metadata,
            keep_last=keep_last,
            fsync=fsync,
            layouts=layouts,
            chunk_bytes=chunk_bytes,
        )
    else:
        blob = serialization.to_bytes(host_state)
        _write_atomic(path, blob, fsync=fsync)
        meta = dict(metadata) if metadata is not None else {}
        meta["_integrity"] = {
            "crc32": zlib.crc32(blob),
            "nbytes": len(blob),
        }
        _write_atomic(
            path + ".json",
            json.dumps(meta, indent=2, default=str).encode(),
            fsync=fsync,
        )
        if keep_last > 1:
            _retain_version(path, meta, keep_last)
        stats = {
            "format": "v1",
            "total_bytes": len(blob),
            "new_bytes": len(blob),
            "reused_bytes": 0,
            "chunks": 0,
            "chunks_written": 0,
            "delta_ratio": 1.0,
        }
        _count(saves_v1=1)
    _count(
        saves=1,
        bytes_total=stats["total_bytes"],
        bytes_written=stats["new_bytes"],
        bytes_reused=stats["reused_bytes"],
        chunks_written=stats["chunks_written"],
    )
    if stats_out is not None:
        stats_out.update(stats)
    meta_src = metadata if metadata is not None else {}
    bus = get_bus()
    if bus is not None:
        # Emitted once the whole save — state, CRC sidecar, retention —
        # has landed, so wall_s covers the full checkpoint cost and the
        # trace never claims an integrity-checked save whose sidecar a
        # crash then withheld. Runs on the background writer thread;
        # the bus is locked.
        bus.emit(
            "ckpt_save",
            step=meta_src.get("step"),
            path=path,
            nbytes=stats["total_bytes"],
            epoch=meta_src.get("completed_epochs"),
            wall_s=round(time.perf_counter() - t0, 6),
            format=stats["format"],
            new_bytes=stats["new_bytes"],
            reused_bytes=stats["reused_bytes"],
        )
    return path


def _save_state_v2(
    host_state: Any,
    path: str,
    *,
    metadata: Optional[dict],
    keep_last: int,
    fsync: bool,
    layouts: Any,
    chunk_bytes: Optional[int],
) -> dict:
    """The v2 save: chunks first, refcounts second, manifest third,
    old-manifest decrement last — a crash at any instant leaves the
    previous candidate fully restorable and at worst leaks chunks for
    the orphan sweep (``tools/ckpt_gc.py``), never corrupts."""
    store = ckpt_store.ChunkStore(
        ckpt_store.chunk_dir_for(path), fsync=fsync
    )
    manifest, stats = ckpt_store.build_manifest(
        host_state,
        store,
        metadata=metadata,
        layouts=layouts,
        chunk_bytes=(
            int(chunk_bytes)
            if chunk_bytes
            else int(
                os.environ.get(
                    "MDT_CKPT_CHUNK_BYTES", ckpt_store.DEFAULT_CHUNK_BYTES
                )
            )
        ),
    )
    new_digests = ckpt_store.manifest_digests(manifest)
    blob = ckpt_store.manifest_bytes(manifest)
    new_step = (metadata or {}).get("step")
    # Increment + manifest replace are ONE critical section (see
    # ChunkStore.locked): a GC's refs rebuild must never land between
    # them — it would drop the counts of a manifest it cannot see yet.
    # The DISPLACED manifest is identified inside the same section
    # (two overlapping writers each decrement exactly the manifest
    # THEY displaced — reading it before the lock would double-count
    # one and skip the other), and a save may only move the primary
    # FORWARD: under the snapshot-fast drain a drained victim's
    # delayed background persist of step N can land after its
    # successor attempt already wrote step N+1 on the same path — the
    # stale replace is skipped (its chunks leak to the sweep), never
    # published over newer work.
    with store.locked():
        displaced = ckpt_store.read_manifest_file(path)
        if displaced is not None and new_step is not None:
            try:
                cur_step = int(
                    (displaced.get("meta") or {}).get("step")
                )
            except (TypeError, ValueError):
                cur_step = None
            if cur_step is not None and cur_step > int(new_step):
                stats["superseded_by_step"] = cur_step
                return stats
        displaced_digests = (
            ckpt_store.manifest_digests(displaced) if displaced else set()
        )
        store._incr_unlocked(new_digests)
        _write_atomic(path, blob, fsync=fsync)
        # Sidecar inside the same section: two overlapped writers
        # must publish {manifest, sidecar} as a pair, or the loser's
        # late sidecar describes the winner's manifest as torn.
        meta = dict(metadata) if metadata is not None else {}
        meta["_integrity"] = {
            "crc32": zlib.crc32(blob),
            "nbytes": len(blob),
        }
        meta["_format"] = "v2"
        _write_atomic(
            path + ".json",
            json.dumps(meta, indent=2, default=str).encode(),
            fsync=fsync,
        )
    if keep_last > 1:
        _retain_version(path, meta, keep_last, store=store)
    store.decr(displaced_digests)
    return stats


def _versions(path: str) -> list[tuple[int, str]]:
    """Existing ``{path}.v{N}`` siblings, newest first."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if not name.startswith(base + ".v") or name.endswith(
            (".json", ".tmp")
        ):
            continue
        m = _VERSION_RE.search(name)
        if m:
            out.append((int(m.group(1)), os.path.join(d, name)))
    out.sort(reverse=True)
    return out


def _retain_version(
    path: str, meta: dict, keep_last: int, *, store=None
) -> None:
    """Retain ``{path}.v{step}`` and prune beyond K. v1 copies the full
    state blob (independent inode — the scan-back depth contract). v2
    copies only the MANIFEST (tiny; the chunks are shared across
    retained versions) and keeps the refcount ledger exact: +1 before
    the version copy lands, −1 after a pruned version is gone — so
    eviction can never drop a chunk a retained manifest still
    references, and a crash in between only leaks a count."""
    step = meta.get("step")
    if step is None:
        existing = _versions(path)
        step = (existing[0][0] + 1) if existing else 1
    ver = f"{path}.v{int(step):010d}"
    if store is not None:
        with store.locked():
            # Same critical-section rule as the primary replace: the
            # displaced same-step version is identified, the new
            # copy's counts land with the copy, and the {manifest,
            # sidecar} pair copies together — an overlapped writer
            # cannot interleave a mismatched pair into the retained
            # version or double-decrement the displaced one.
            displaced = ckpt_store.read_manifest_file(ver)
            m = ckpt_store.read_manifest_file(path)
            if m is not None:
                store._incr_unlocked(ckpt_store.manifest_digests(m))
            _copy_replace(path, ver)
            _copy_replace(path + ".json", ver + ".json")
        if displaced is not None:
            # A re-save at the same step displaced an older same-name
            # version: its references drop now that the copy replaced
            # it.
            store.decr(ckpt_store.manifest_digests(displaced))
    else:
        _copy_replace(path, ver)
        _copy_replace(path + ".json", ver + ".json")
    for _, old in _versions(path)[keep_last:]:
        old_m = (
            ckpt_store.read_manifest_file(old) if store is not None else None
        )
        removed_manifest = False
        for p in (old, old + ".json"):
            try:
                os.remove(p)
                removed_manifest = removed_manifest or p == old
            except OSError:
                pass
        if store is not None and old_m is not None and removed_manifest:
            # Decrement only as the writer that actually removed the
            # file: two overlapped retentions pruning the same version
            # must not double-decrement shared chunks toward zero.
            store.decr(ckpt_store.manifest_digests(old_m))


def checkpoint_candidates(path: str) -> list[str]:
    """Restore candidates, newest first: the primary path, then retained
    versions in descending version order."""
    return [path] + [p for _, p in _versions(path)]


def verify_checkpoint(path: str) -> tuple[bool, Optional[dict], str]:
    """``(ok, metadata, reason)`` for one candidate file.

    A candidate is valid when its sidecar parses and the state bytes
    match the sidecar's CRC32/length — and, for a v2 manifest, when
    every referenced chunk is present, sized, and CRC-clean
    (**chunk-complete verification**: a torn manifest OR a missing/
    rotted chunk disqualifies the candidate, so scan-back and the
    cross-host restore agreement degrade to the previous step exactly
    as they do for a torn v1 state file). Legacy checkpoints (no
    ``_integrity`` — written before this layer existed) fall back to a
    structural decode; a missing sidecar is accepted the same way
    (``restore_state`` never required one).
    """
    if not os.path.exists(path):
        return False, None, "missing"
    meta: Optional[dict] = None
    meta_path = path + ".json"
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            return False, None, f"sidecar unreadable: {e}"
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        return False, meta, f"state unreadable: {e}"
    integ = (meta or {}).get("_integrity")
    if integ is not None:
        if len(blob) != int(integ.get("nbytes", -1)):
            return False, meta, (
                f"size mismatch ({len(blob)} vs recorded "
                f"{integ.get('nbytes')}) — torn write"
            )
        if zlib.crc32(blob) != int(integ.get("crc32", -1)):
            return False, meta, "crc32 mismatch — corrupt or torn state"
        return _verify_chunks_if_v2(path, blob, meta)
    if ckpt_store.is_manifest_blob(blob):
        # Sidecar-less v2 manifest: structural parse + chunk-complete
        # verification carry the whole verdict.
        return _verify_chunks_if_v2(path, blob, meta)
    try:  # legacy (pre-CRC) checkpoint: structural check only
        serialization.msgpack_restore(blob)
    except Exception as e:  # noqa: BLE001 — any decode failure disqualifies
        return False, meta, f"msgpack undecodable: {e}"
    return True, meta, "ok"


def _verify_chunks_if_v2(path: str, blob: bytes, meta: Optional[dict]):
    """The v2 half of :func:`verify_checkpoint`: non-manifest blobs
    pass through (the sidecar CRC already vouched for them)."""
    if not ckpt_store.is_manifest_blob(blob):
        return True, meta, "ok"
    try:
        manifest = ckpt_store.load_manifest(blob)
    except Exception as e:  # noqa: BLE001 — undecodable manifest = torn
        return False, meta, f"manifest undecodable: {e}"
    store = ckpt_store.ChunkStore(ckpt_store.chunk_dir_for(path))
    ok, reason = ckpt_store.verify_manifest_chunks(manifest, store)
    if not ok:
        return False, meta, f"chunk-incomplete: {reason}"
    return True, meta, "ok"


def valid_candidates_by_step(
    path: str,
    *,
    accept_meta: Optional[Callable[[dict], bool]] = None,
) -> dict[int, tuple[str, dict]]:
    """Locally-verifiable restore candidates keyed by their recorded
    optimizer step: ``{step: (candidate_path, metadata)}``, newest
    candidate winning a step collision.

    The read side of the cross-host restore agreement
    (``hpo/driver.py``): each owner process of a spanning submesh calls
    this to learn which steps IT can verify (CRC + ``accept_meta``
    gate), agrees on the min of the newest steps across owners
    (``collectives.group_min_scalar``), then restores its candidate at
    the agreed step. Candidates without a recorded ``step`` (pre-CRC
    legacy sidecars) cannot participate in a step agreement and are
    skipped. Rejections emit the same ``ckpt_scan_reject`` telemetry as
    :func:`restore_latest_valid`.
    """
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    out: dict[int, tuple[str, dict]] = {}
    for cand in checkpoint_candidates(path):
        ok, meta, reason = verify_checkpoint(cand)
        if not ok:
            if bus is not None and reason != "missing":
                bus.emit("ckpt_scan_reject", path=cand, reason=reason)
            continue
        meta = meta or {}
        if accept_meta is not None and not accept_meta(meta):
            if bus is not None:
                bus.emit(
                    "ckpt_scan_reject", path=cand, reason="meta rejected"
                )
            continue
        if "step" not in meta:
            continue  # legacy sidecar: no step to agree on
        step = int(meta["step"])
        if step not in out:  # candidates iterate newest-first
            out[step] = (cand, meta)
    return out


def agreed_restore_step(
    path: str,
    *,
    name: str,
    participants,
    accept_meta: Optional[Callable[[dict], bool]] = None,
    timeout_s: Optional[float] = None,
    what: str = "cross-host restore agreement",
    **tags,
) -> Optional[tuple[int, str, dict]]:
    """The **cross-host restore agreement** (docs/RESILIENCE.md
    "Elastic multi-host"): every participant process verifies its
    restore candidates locally, the group agrees on the MIN of the
    newest locally-valid steps, confirms every participant holds the
    agreed candidate, and returns ``(step, candidate_path, metadata)``
    — or ``None`` for "all resume from scratch".

    Shared-filesystem views can disagree (NFS close-to-open races, a
    write torn under one reader): without the agreement, owners of a
    process-spanning submesh would restore different weights and
    silently desynchronize SPMD. Any disagreement degrades to scratch
    on EVERY participant, never an error — recovery must degrade, not
    wedge.

    The agreement rides the coordination-service sideband
    (``cluster.agree_min_int``), NOT an on-mesh collective: it must
    work during recovery, when the device world may be the broken
    thing, and on backends without cross-process XLA computations.
    ``name`` scopes the agreement's keys — callers make it unique per
    (trial, attempt). A missing participant becomes a
    ``WedgedCollective`` within ``timeout_s``. Extra ``tags`` ride the
    emitted ``restore_agreement`` telemetry event.
    """
    from multidisttorch_tpu.parallel.cluster import agree_min_int
    from multidisttorch_tpu.telemetry.events import get_bus

    cands = valid_candidates_by_step(path, accept_meta=accept_meta)
    local_best = max(cands) if cands else 0
    agreed = agree_min_int(
        f"mdt:restore:{name}:best",
        local_best,
        participants,
        timeout_s=timeout_s,
        what=f"{what} (best-step round)",
    )
    # Second round: min-over-bests guarantees agreed <= every local
    # best, but not that every participant's valid SET contains it
    # (retention skew). All hold the exact step, or all go scratch —
    # and every participant reaches both rounds whatever its local
    # verdict (uniform cadence).
    have = 1 if (agreed > 0 and agreed in cands) else 0
    all_have = agree_min_int(
        f"mdt:restore:{name}:have",
        have,
        participants,
        timeout_s=timeout_s,
        what=f"{what} (availability round)",
    )
    bus = get_bus()
    if bus is not None:
        bus.emit(
            "restore_agreement",
            local_best_step=local_best,
            agreed_step=agreed,
            all_have=bool(all_have),
            **tags,
        )
    if agreed <= 0 or not all_have:
        return None
    cand, meta = cands[agreed]
    return agreed, cand, meta


def restore_latest_valid(
    template: Any,
    path: str,
    trial: Optional[TrialMesh] = None,
    *,
    shardings: Any = None,
    accept_meta: Optional[Callable[[dict], bool]] = None,
) -> Optional[tuple[Any, dict, str]]:
    """Restore the newest checkpoint that verifies, scanning back past
    torn/corrupt candidates (the latest file, then ``keep_last``
    history).

    ``accept_meta`` optionally gates candidates on their sidecar (e.g.
    "config must match the retrying trial's"); rejected candidates are
    skipped like corrupt ones, not fatal. Returns ``(state, metadata,
    used_path)`` — or ``None`` when nothing valid remains, which a
    supervisor treats as "retry from scratch", never an error: recovery
    must degrade, not wedge.
    """
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    for cand in checkpoint_candidates(path):
        ok, meta, reason = verify_checkpoint(cand)
        if not ok:
            if bus is not None:
                # Scan-back transparency: every rejected candidate is a
                # tagged event, so a chaos trace shows exactly which
                # torn/corrupt files recovery had to skip.
                bus.emit("ckpt_scan_reject", path=cand, reason=reason)
            continue
        meta = meta or {}
        if accept_meta is not None and not accept_meta(meta):
            if bus is not None:
                bus.emit(
                    "ckpt_scan_reject", path=cand, reason="meta rejected"
                )
            continue
        try:
            restored = restore_state(
                template, cand, trial, shardings=shardings
            )
        except Exception as e:  # noqa: BLE001 — scan on (CRC can't catch all)
            if bus is not None:
                bus.emit(
                    "ckpt_scan_reject",
                    path=cand,
                    reason=f"restore failed: {type(e).__name__}",
                )
            continue
        if bus is not None:
            # restore_state above already emitted the plain
            # "ckpt_restore"; this one tags the scan-back outcome.
            bus.emit(
                "ckpt_scan_restore",
                step=meta.get("step"),
                path=cand,
                epoch=meta.get("completed_epochs"),
            )
        return restored, meta, cand
    if bus is not None:
        bus.emit("ckpt_scan_none", path=path)
    return None


def _require_fully_addressable(tree: Any, op: str) -> None:
    """Serialization reads whole arrays on this host. A process-spanning
    *replicated* state is fine (every shard is a full copy); a
    weight-SHARDED state on a process-spanning submesh is not — this
    process doesn't hold the other processes' shards, and a collective
    gather can't happen here because the driver writer-gates checkpoint
    I/O to ONE process. Fail with the contract instead of jax's opaque
    span error: callers with such states gather to replicated on all
    owners first, then let the writer save."""
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if getattr(leaf.sharding, "is_fully_replicated", False):
                continue  # every process holds a complete copy
            raise ValueError(
                f"{op}: state leaf (shape {leaf.shape}) is sharded across "
                "processes and not fully addressable here. Gather it to "
                "replicated on every owner process first (one process "
                "cannot serialize shards it does not hold)."
            )


def restore_state(
    template: Any,
    path: str,
    trial: Optional[TrialMesh] = None,
    *,
    shardings: Any = None,
) -> Any:
    """Restore into the structure of ``template``; optionally place onto
    ``trial``'s submesh (checkpoint-restart or PBT exploit onto a
    different device group).

    Strict single-file semantics: a torn/corrupt ``path`` raises. The
    scan-back sibling for supervised recovery is
    :func:`restore_latest_valid`.

    Placement defaults to replicated — correct for the plain-DP trials
    the driver runs. A weight-sharded state (TP/FSDP/EP) must pass its
    ``shardings`` pytree (``train.steps.state_shardings`` of the live
    state) or the restore silently lands fully replicated, costing the
    sharding's whole memory benefit until the first step reshards it.
    (Cross-PROCESS-sharded templates additionally need a gather before
    save — see :func:`save_state`'s addressability contract; restore
    placement itself is multi-process safe via ``TrialMesh.device_put``.)
    """
    if shardings is not None and trial is None:
        raise ValueError(
            "restore_state: shardings= requires trial= (the submesh to "
            "place onto); without it the shardings would be silently "
            "ignored"
        )
    _require_fully_addressable(template, "restore_state")
    with open(path, "rb") as f:
        blob = f.read()
    if ckpt_store.is_manifest_blob(blob):
        # v2: reassemble from the chunk store with the parallel read
        # pool, then device_put straight onto the target sharding — no
        # intermediate replicated copy.
        manifest = ckpt_store.load_manifest(blob)
        store = ckpt_store.ChunkStore(ckpt_store.chunk_dir_for(path))
        state_dict = ckpt_store.restore_arrays(manifest, store)
        restored = serialization.from_state_dict(
            jax.device_get(template), state_dict
        )
        fmt = "v2"
    else:
        restored = serialization.from_bytes(
            jax.device_get(template), blob
        )
        fmt = "v1"
    if trial is not None:
        restored = trial.device_put(restored, shardings)
    _count(restores=1)
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    if bus is not None:
        bus.emit(
            "ckpt_restore",
            group_id=getattr(trial, "group_id", None),
            path=path,
            format=fmt,
        )
    return restored
