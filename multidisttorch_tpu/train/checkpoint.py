"""Trial-state checkpoint/resume.

The reference persists nothing but PNGs (SURVEY.md §5 — no
``torch.save`` anywhere); checkpointing is an explicit upgrade required
by the PBT config (BASELINE.md config 5), which moves trial weights
between submeshes. State is a plain pytree (``train.steps.TrainState``),
serialized with flax's msgpack codec; restore re-places it onto any
target submesh — the same mechanism serves disk checkpoints and
inter-trial weight broadcast.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
from flax import serialization

from multidisttorch_tpu.parallel.mesh import TrialMesh


def save_state(state: Any, path: str, *, metadata: Optional[dict] = None) -> str:
    """Serialize a state pytree (host-side) to ``path`` (msgpack).

    Writes are atomic (tmp file + ``os.replace``): a crash mid-write —
    including the interpreter exiting while a background checkpoint
    thread is running — can never leave a torn ``state.msgpack`` that
    breaks a later ``resume``. The state file lands before the metadata
    sidecar, so a reader never sees metadata describing a state that
    isn't there yet.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    host_state = jax.device_get(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(host_state))
    os.replace(tmp, path)
    if metadata is not None:
        meta_tmp = path + ".json.tmp"
        with open(meta_tmp, "w") as f:
            json.dump(metadata, f, indent=2, default=str)
        os.replace(meta_tmp, path + ".json")
    return path


def restore_state(template: Any, path: str, trial: Optional[TrialMesh] = None) -> Any:
    """Restore into the structure of ``template``; optionally place
    replicated onto ``trial``'s submesh (checkpoint-restart or PBT
    exploit onto a different device group)."""
    with open(path, "rb") as f:
        restored = serialization.from_bytes(jax.device_get(template), f.read())
    if trial is not None:
        restored = trial.device_put(restored)
    return restored
