"""Trial-state checkpoint/resume.

The reference persists nothing but PNGs (SURVEY.md §5 — no
``torch.save`` anywhere); checkpointing is an explicit upgrade required
by the PBT config (BASELINE.md config 5), which moves trial weights
between submeshes. State is a plain pytree (``train.steps.TrainState``),
serialized with flax's msgpack codec; restore re-places it onto any
target submesh — the same mechanism serves disk checkpoints and
inter-trial weight broadcast.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
from flax import serialization

from multidisttorch_tpu.parallel.mesh import TrialMesh


def save_state(state: Any, path: str, *, metadata: Optional[dict] = None) -> str:
    """Serialize a state pytree (host-side) to ``path`` (msgpack).

    Writes are atomic (tmp file + ``os.replace``): a crash mid-write —
    including the interpreter exiting while a background checkpoint
    thread is running — can never leave a torn ``state.msgpack`` that
    breaks a later ``resume``. The state file lands before the metadata
    sidecar, so a reader never sees metadata describing a state that
    isn't there yet.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _require_fully_addressable(state, "save_state")
    host_state = jax.device_get(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(host_state))
    os.replace(tmp, path)
    if metadata is not None:
        meta_tmp = path + ".json.tmp"
        with open(meta_tmp, "w") as f:
            json.dump(metadata, f, indent=2, default=str)
        os.replace(meta_tmp, path + ".json")
    return path


def _require_fully_addressable(tree: Any, op: str) -> None:
    """Serialization reads whole arrays on this host. A process-spanning
    *replicated* state is fine (every shard is a full copy); a
    weight-SHARDED state on a process-spanning submesh is not — this
    process doesn't hold the other processes' shards, and a collective
    gather can't happen here because the driver writer-gates checkpoint
    I/O to ONE process. Fail with the contract instead of jax's opaque
    span error: callers with such states gather to replicated on all
    owners first, then let the writer save."""
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if getattr(leaf.sharding, "is_fully_replicated", False):
                continue  # every process holds a complete copy
            raise ValueError(
                f"{op}: state leaf (shape {leaf.shape}) is sharded across "
                "processes and not fully addressable here. Gather it to "
                "replicated on every owner process first (one process "
                "cannot serialize shards it does not hold)."
            )


def restore_state(
    template: Any,
    path: str,
    trial: Optional[TrialMesh] = None,
    *,
    shardings: Any = None,
) -> Any:
    """Restore into the structure of ``template``; optionally place onto
    ``trial``'s submesh (checkpoint-restart or PBT exploit onto a
    different device group).

    Placement defaults to replicated — correct for the plain-DP trials
    the driver runs. A weight-sharded state (TP/FSDP/EP) must pass its
    ``shardings`` pytree (``train.steps.state_shardings`` of the live
    state) or the restore silently lands fully replicated, costing the
    sharding's whole memory benefit until the first step reshards it.
    (Cross-PROCESS-sharded templates additionally need a gather before
    save — see :func:`save_state`'s addressability contract; restore
    placement itself is multi-process safe via ``TrialMesh.device_put``.)
    """
    if shardings is not None and trial is None:
        raise ValueError(
            "restore_state: shardings= requires trial= (the submesh to "
            "place onto); without it the shardings would be silently "
            "ignored"
        )
    _require_fully_addressable(template, "restore_state")
    with open(path, "rb") as f:
        restored = serialization.from_bytes(jax.device_get(template), f.read())
    if trial is not None:
        restored = trial.device_put(restored, shardings)
    return restored
