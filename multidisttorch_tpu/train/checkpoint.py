"""Trial-state checkpoint/resume with crash-safe durability.

The reference persists nothing but PNGs (SURVEY.md §5 — no
``torch.save`` anywhere); checkpointing is an explicit upgrade required
by the PBT config (BASELINE.md config 5), which moves trial weights
between submeshes. State is a plain pytree (``train.steps.TrainState``),
serialized with flax's msgpack codec; restore re-places it onto any
target submesh — the same mechanism serves disk checkpoints and
inter-trial weight broadcast.

Durability contract (the fault-tolerance subsystem's foundation,
docs/RESILIENCE.md):

- **Atomic + durable writes**: tmp file, ``fsync``, ``os.replace``,
  directory ``fsync`` — a crash (or power loss) mid-write can never
  tear the visible ``state.msgpack``; either the old file or the new
  one is fully there.
- **CRC32-verified sidecars**: the metadata sidecar records the state
  file's CRC32 + byte count (``_integrity``), so a reader can tell a
  valid checkpoint from a corrupt/rotted one — and tell "state newer
  than sidecar" (a crash landed between the two replaces) from a
  healthy pair.
- **Keep-last-K retention** (``keep_last``): each save also retains an
  independent versioned copy ``{path}.v{step}`` (a real copy, not a
  hard-link — see :func:`_copy_replace`) and prunes beyond K, so a torn
  or corrupt latest still has valid history behind it.
- **:func:`restore_latest_valid`**: scan newest→oldest past torn/
  corrupt candidates and restore the first verifiable one — what
  retry-with-resume (``hpo/driver.py``) uses, where ``restore_state``'s
  strict single-file semantics would abandon recoverable work.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import zlib
from typing import Any, Callable, Optional

import jax
from flax import serialization

from multidisttorch_tpu.parallel.mesh import TrialMesh

_VERSION_RE = re.compile(r"\.v(\d+)$")


class CheckpointError(RuntimeError):
    """A checkpoint could not be read/verified (and no fallback said
    otherwise)."""


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (the rename itself) — without
    this, a power loss after ``os.replace`` can resurrect the old file.
    Best-effort: some filesystems refuse O_RDONLY dir fsync."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_atomic(path: str, blob: bytes, *, fsync: bool) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path)


def _copy_replace(src: str, dst: str) -> None:
    """Atomically make ``dst`` an independent COPY of ``src``. A
    hard-link would be free, but it shares the inode: in-place
    corruption (bit rot, a torn rewrite) of the primary would garble
    its newest retained version with it, silently shrinking the
    scan-back depth from K to K-1. States here are small; pay the copy
    and keep the retention contract exact."""
    tmp = dst + ".tmp"
    if os.path.exists(tmp):
        os.remove(tmp)
    shutil.copy2(src, tmp)
    os.replace(tmp, dst)


def save_state(
    state: Any,
    path: str,
    *,
    metadata: Optional[dict] = None,
    keep_last: int = 1,
    fsync: bool = True,
) -> str:
    """Serialize a state pytree (host-side) to ``path`` (msgpack).

    Writes are atomic AND durable (tmp file + ``fsync`` +
    ``os.replace`` + directory ``fsync``): a crash mid-write — including
    the interpreter exiting while a background checkpoint thread is
    running, or the host losing power — can never leave a torn
    ``state.msgpack`` that breaks a later ``resume``. The state file
    lands before the metadata sidecar, so a reader never sees metadata
    describing a state that isn't there yet; the sidecar carries the
    state's CRC32 (``_integrity``) so a reader can detect the converse
    tear (state replaced, crash before the sidecar followed).

    ``keep_last=K`` (K > 1) additionally retains the K most recent
    checkpoints as independent ``{path}.v{step}`` copies (version id =
    ``metadata['step']`` when present, else a monotonic counter), giving
    :func:`restore_latest_valid` history to scan back through when the
    latest is torn or corrupted. ``fsync=False`` opts out of the
    durability syncs (benchmarks on throwaway dirs).
    """
    import time as _time

    from multidisttorch_tpu.telemetry.events import get_bus

    t0 = _time.perf_counter()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _require_fully_addressable(state, "save_state")
    host_state = jax.device_get(state)
    blob = serialization.to_bytes(host_state)
    _write_atomic(path, blob, fsync=fsync)

    meta = dict(metadata) if metadata is not None else {}
    meta["_integrity"] = {"crc32": zlib.crc32(blob), "nbytes": len(blob)}
    _write_atomic(
        path + ".json",
        json.dumps(meta, indent=2, default=str).encode(),
        fsync=fsync,
    )

    if keep_last > 1:
        _retain_version(path, meta, keep_last)
    bus = get_bus()
    if bus is not None:
        # Emitted once the whole save — state, CRC sidecar, retention —
        # has landed, so wall_s covers the full checkpoint cost and the
        # trace never claims an integrity-checked save whose sidecar a
        # crash then withheld. Runs on the background writer thread;
        # the bus is locked.
        bus.emit(
            "ckpt_save",
            step=meta.get("step"),
            path=path,
            nbytes=len(blob),
            epoch=meta.get("completed_epochs"),
            wall_s=round(_time.perf_counter() - t0, 6),
        )
    return path


def _versions(path: str) -> list[tuple[int, str]]:
    """Existing ``{path}.v{N}`` siblings, newest first."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if not name.startswith(base + ".v") or name.endswith(
            (".json", ".tmp")
        ):
            continue
        m = _VERSION_RE.search(name)
        if m:
            out.append((int(m.group(1)), os.path.join(d, name)))
    out.sort(reverse=True)
    return out


def _retain_version(path: str, meta: dict, keep_last: int) -> None:
    step = meta.get("step")
    if step is None:
        existing = _versions(path)
        step = (existing[0][0] + 1) if existing else 1
    ver = f"{path}.v{int(step):010d}"
    _copy_replace(path, ver)
    _copy_replace(path + ".json", ver + ".json")
    for _, old in _versions(path)[keep_last:]:
        for p in (old, old + ".json"):
            try:
                os.remove(p)
            except OSError:
                pass


def checkpoint_candidates(path: str) -> list[str]:
    """Restore candidates, newest first: the primary path, then retained
    versions in descending version order."""
    return [path] + [p for _, p in _versions(path)]


def verify_checkpoint(path: str) -> tuple[bool, Optional[dict], str]:
    """``(ok, metadata, reason)`` for one candidate file.

    A candidate is valid when its sidecar parses and the state bytes
    match the sidecar's CRC32/length. Legacy checkpoints (no
    ``_integrity`` — written before this layer existed) fall back to a
    structural msgpack decode; a missing sidecar is accepted the same
    way (``restore_state`` never required one).
    """
    if not os.path.exists(path):
        return False, None, "missing"
    meta: Optional[dict] = None
    meta_path = path + ".json"
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            return False, None, f"sidecar unreadable: {e}"
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        return False, meta, f"state unreadable: {e}"
    integ = (meta or {}).get("_integrity")
    if integ is not None:
        if len(blob) != int(integ.get("nbytes", -1)):
            return False, meta, (
                f"size mismatch ({len(blob)} vs recorded "
                f"{integ.get('nbytes')}) — torn write"
            )
        if zlib.crc32(blob) != int(integ.get("crc32", -1)):
            return False, meta, "crc32 mismatch — corrupt or torn state"
        return True, meta, "ok"
    try:  # legacy (pre-CRC) checkpoint: structural check only
        serialization.msgpack_restore(blob)
    except Exception as e:  # noqa: BLE001 — any decode failure disqualifies
        return False, meta, f"msgpack undecodable: {e}"
    return True, meta, "ok"


def valid_candidates_by_step(
    path: str,
    *,
    accept_meta: Optional[Callable[[dict], bool]] = None,
) -> dict[int, tuple[str, dict]]:
    """Locally-verifiable restore candidates keyed by their recorded
    optimizer step: ``{step: (candidate_path, metadata)}``, newest
    candidate winning a step collision.

    The read side of the cross-host restore agreement
    (``hpo/driver.py``): each owner process of a spanning submesh calls
    this to learn which steps IT can verify (CRC + ``accept_meta``
    gate), agrees on the min of the newest steps across owners
    (``collectives.group_min_scalar``), then restores its candidate at
    the agreed step. Candidates without a recorded ``step`` (pre-CRC
    legacy sidecars) cannot participate in a step agreement and are
    skipped. Rejections emit the same ``ckpt_scan_reject`` telemetry as
    :func:`restore_latest_valid`.
    """
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    out: dict[int, tuple[str, dict]] = {}
    for cand in checkpoint_candidates(path):
        ok, meta, reason = verify_checkpoint(cand)
        if not ok:
            if bus is not None and reason != "missing":
                bus.emit("ckpt_scan_reject", path=cand, reason=reason)
            continue
        meta = meta or {}
        if accept_meta is not None and not accept_meta(meta):
            if bus is not None:
                bus.emit(
                    "ckpt_scan_reject", path=cand, reason="meta rejected"
                )
            continue
        if "step" not in meta:
            continue  # legacy sidecar: no step to agree on
        step = int(meta["step"])
        if step not in out:  # candidates iterate newest-first
            out[step] = (cand, meta)
    return out


def agreed_restore_step(
    path: str,
    *,
    name: str,
    participants,
    accept_meta: Optional[Callable[[dict], bool]] = None,
    timeout_s: Optional[float] = None,
    what: str = "cross-host restore agreement",
    **tags,
) -> Optional[tuple[int, str, dict]]:
    """The **cross-host restore agreement** (docs/RESILIENCE.md
    "Elastic multi-host"): every participant process verifies its
    restore candidates locally, the group agrees on the MIN of the
    newest locally-valid steps, confirms every participant holds the
    agreed candidate, and returns ``(step, candidate_path, metadata)``
    — or ``None`` for "all resume from scratch".

    Shared-filesystem views can disagree (NFS close-to-open races, a
    write torn under one reader): without the agreement, owners of a
    process-spanning submesh would restore different weights and
    silently desynchronize SPMD. Any disagreement degrades to scratch
    on EVERY participant, never an error — recovery must degrade, not
    wedge.

    The agreement rides the coordination-service sideband
    (``cluster.agree_min_int``), NOT an on-mesh collective: it must
    work during recovery, when the device world may be the broken
    thing, and on backends without cross-process XLA computations.
    ``name`` scopes the agreement's keys — callers make it unique per
    (trial, attempt). A missing participant becomes a
    ``WedgedCollective`` within ``timeout_s``. Extra ``tags`` ride the
    emitted ``restore_agreement`` telemetry event.
    """
    from multidisttorch_tpu.parallel.cluster import agree_min_int
    from multidisttorch_tpu.telemetry.events import get_bus

    cands = valid_candidates_by_step(path, accept_meta=accept_meta)
    local_best = max(cands) if cands else 0
    agreed = agree_min_int(
        f"mdt:restore:{name}:best",
        local_best,
        participants,
        timeout_s=timeout_s,
        what=f"{what} (best-step round)",
    )
    # Second round: min-over-bests guarantees agreed <= every local
    # best, but not that every participant's valid SET contains it
    # (retention skew). All hold the exact step, or all go scratch —
    # and every participant reaches both rounds whatever its local
    # verdict (uniform cadence).
    have = 1 if (agreed > 0 and agreed in cands) else 0
    all_have = agree_min_int(
        f"mdt:restore:{name}:have",
        have,
        participants,
        timeout_s=timeout_s,
        what=f"{what} (availability round)",
    )
    bus = get_bus()
    if bus is not None:
        bus.emit(
            "restore_agreement",
            local_best_step=local_best,
            agreed_step=agreed,
            all_have=bool(all_have),
            **tags,
        )
    if agreed <= 0 or not all_have:
        return None
    cand, meta = cands[agreed]
    return agreed, cand, meta


def restore_latest_valid(
    template: Any,
    path: str,
    trial: Optional[TrialMesh] = None,
    *,
    shardings: Any = None,
    accept_meta: Optional[Callable[[dict], bool]] = None,
) -> Optional[tuple[Any, dict, str]]:
    """Restore the newest checkpoint that verifies, scanning back past
    torn/corrupt candidates (the latest file, then ``keep_last``
    history).

    ``accept_meta`` optionally gates candidates on their sidecar (e.g.
    "config must match the retrying trial's"); rejected candidates are
    skipped like corrupt ones, not fatal. Returns ``(state, metadata,
    used_path)`` — or ``None`` when nothing valid remains, which a
    supervisor treats as "retry from scratch", never an error: recovery
    must degrade, not wedge.
    """
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    for cand in checkpoint_candidates(path):
        ok, meta, reason = verify_checkpoint(cand)
        if not ok:
            if bus is not None:
                # Scan-back transparency: every rejected candidate is a
                # tagged event, so a chaos trace shows exactly which
                # torn/corrupt files recovery had to skip.
                bus.emit("ckpt_scan_reject", path=cand, reason=reason)
            continue
        meta = meta or {}
        if accept_meta is not None and not accept_meta(meta):
            if bus is not None:
                bus.emit(
                    "ckpt_scan_reject", path=cand, reason="meta rejected"
                )
            continue
        try:
            restored = restore_state(
                template, cand, trial, shardings=shardings
            )
        except Exception as e:  # noqa: BLE001 — scan on (CRC can't catch all)
            if bus is not None:
                bus.emit(
                    "ckpt_scan_reject",
                    path=cand,
                    reason=f"restore failed: {type(e).__name__}",
                )
            continue
        if bus is not None:
            # restore_state above already emitted the plain
            # "ckpt_restore"; this one tags the scan-back outcome.
            bus.emit(
                "ckpt_scan_restore",
                step=meta.get("step"),
                path=cand,
                epoch=meta.get("completed_epochs"),
            )
        return restored, meta, cand
    if bus is not None:
        bus.emit("ckpt_scan_none", path=path)
    return None


def _require_fully_addressable(tree: Any, op: str) -> None:
    """Serialization reads whole arrays on this host. A process-spanning
    *replicated* state is fine (every shard is a full copy); a
    weight-SHARDED state on a process-spanning submesh is not — this
    process doesn't hold the other processes' shards, and a collective
    gather can't happen here because the driver writer-gates checkpoint
    I/O to ONE process. Fail with the contract instead of jax's opaque
    span error: callers with such states gather to replicated on all
    owners first, then let the writer save."""
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if getattr(leaf.sharding, "is_fully_replicated", False):
                continue  # every process holds a complete copy
            raise ValueError(
                f"{op}: state leaf (shape {leaf.shape}) is sharded across "
                "processes and not fully addressable here. Gather it to "
                "replicated on every owner process first (one process "
                "cannot serialize shards it does not hold)."
            )


def restore_state(
    template: Any,
    path: str,
    trial: Optional[TrialMesh] = None,
    *,
    shardings: Any = None,
) -> Any:
    """Restore into the structure of ``template``; optionally place onto
    ``trial``'s submesh (checkpoint-restart or PBT exploit onto a
    different device group).

    Strict single-file semantics: a torn/corrupt ``path`` raises. The
    scan-back sibling for supervised recovery is
    :func:`restore_latest_valid`.

    Placement defaults to replicated — correct for the plain-DP trials
    the driver runs. A weight-sharded state (TP/FSDP/EP) must pass its
    ``shardings`` pytree (``train.steps.state_shardings`` of the live
    state) or the restore silently lands fully replicated, costing the
    sharding's whole memory benefit until the first step reshards it.
    (Cross-PROCESS-sharded templates additionally need a gather before
    save — see :func:`save_state`'s addressability contract; restore
    placement itself is multi-process safe via ``TrialMesh.device_put``.)
    """
    if shardings is not None and trial is None:
        raise ValueError(
            "restore_state: shardings= requires trial= (the submesh to "
            "place onto); without it the shardings would be silently "
            "ignored"
        )
    _require_fully_addressable(template, "restore_state")
    with open(path, "rb") as f:
        restored = serialization.from_bytes(jax.device_get(template), f.read())
    if trial is not None:
        restored = trial.device_put(restored, shardings)
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    if bus is not None:
        bus.emit(
            "ckpt_restore",
            group_id=getattr(trial, "group_id", None),
            path=path,
        )
    return restored
