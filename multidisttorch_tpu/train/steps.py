"""Per-trial jit-compiled train/eval/sample steps.

This is the TPU-native replacement for the reference's DDP training
machinery (``/root/reference/vae-hpo.py:61-92,122-131``): where the
reference wraps the model in ``DistributedDataParallel(model,
process_group=group)`` and relies on backward-hook all-reduces scoped to
the subgroup, here the entire step is one jit-compiled program placed on
the trial's submesh — parameters and optimizer state replicated
(``TrialMesh.replicated_sharding``), the batch sharded over the
submesh's ``data`` axis (``TrialMesh.batch_sharding``) — and XLA inserts
the gradient reduction over ICI itself. One compilation per trial; every
subsequent step is a single async dispatch.

Gradient semantics: the loss is the per-sample mean, so gradients are
scale-invariant to batch/group size. The reference's effective gradient
(DDP average of per-rank *summed* losses, ``vae-hpo.py:49-58,130``) is
``local_batch_size``× larger; under Adam (the reference's optimizer,
``vae-hpo.py:131``) the difference is absorbed by the second-moment
normalization. Logged losses are *sums* so the reference's per-sample
logging arithmetic (``vae-hpo.py:83,89,118``) carries over unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import struct

from multidisttorch_tpu.utils.compat import shard_map as compat_shard_map
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.ops.losses import elbo_loss_sum
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh


@struct.dataclass
class TrainState:
    """Replicated per-trial training state (the analog of the reference's
    DDP-wrapped model + Adam optimizer, ``vae-hpo.py:129-131``).

    A plain pytree: serializable for checkpoint/resume and PBT
    weight-exchange across submeshes.
    """

    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar


def build_train_state(
    model: VAE, tx: optax.GradientTransformation, rng: jax.Array
) -> TrainState:
    """Construct an un-placed :class:`TrainState` on the default device.

    The single source of the state pytree's structure: placement
    (:func:`create_train_state`) and the multi-host broadcast template
    (``hpo/pbt.py``) both derive from it, so the tree every process
    expects in a cross-process transfer can never drift from the tree
    members actually train.
    """
    params = model.init(
        {"params": rng, "reparam": rng},
        jnp.zeros((1, model.input_dim), jnp.float32),
    )["params"]
    return TrainState(
        params=params,
        opt_state=tx.init(params),
        step=jnp.zeros((), jnp.int32),
    )


def place_sharded_state(
    trial: TrialMesh,
    params: Any,
    tx: optax.GradientTransformation,
    param_shardings: Any,
) -> TrainState:
    """Place an initialized param tree as a weight-sharded TrainState.

    The one copy of the tensor-parallel placement recipe (shared by the
    VAE and classifier state creators): params placed per
    ``param_shardings``; the optimizer state initialized *eagerly* so
    computation-follows-data gives each Adam moment its weight's
    sharding — no hand-written moment shardings. (Do NOT jit the init:
    jit constant-folds the zeros and drops the sharding.) Scalar opt
    leaves with no input dependence (Adam's count) come back
    single-device — those are pinned replicated on the submesh.
    """
    from jax.sharding import NamedSharding

    params = jax.device_put(params, param_shardings)
    opt_state = jax.tree.map(
        lambda x: (
            x
            if isinstance(getattr(x, "sharding", None), NamedSharding)
            else trial.device_put(x)
        ),
        tx.init(params),
    )
    return TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.device_put(
            jnp.zeros((), jnp.int32), trial.replicated_sharding
        ),
    )


def create_train_state(
    trial: TrialMesh,
    model: VAE,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    param_shardings: Any = None,
) -> TrainState:
    """Initialize params on host, place them on the trial submesh.

    The analog of ``VAE().to(device)`` + DDP's initial parameter
    broadcast (``vae-hpo.py:129-130``) — except there is no broadcast:
    placement with a sharding materializes the right shard/copy on every
    member device. Default is DDP-style full replication;
    ``param_shardings`` (a pytree of ``NamedSharding`` matching the
    param tree, e.g. ``models.vae.vae_tp_shardings``) instead shards
    weights over the submesh's model axis via
    :func:`place_sharded_state`.
    """
    if param_shardings is None:
        return trial.device_put(build_train_state(model, tx, rng))

    params = model.init(
        {"params": rng, "reparam": rng},
        jnp.zeros((1, model.input_dim), jnp.float32),
    )["params"]
    return place_sharded_state(trial, params, tx, param_shardings)


def state_shardings(state: TrainState) -> TrainState:
    """The concrete sharding of every leaf of a placed ``TrainState`` —
    pass to :func:`make_train_step` to pin a tensor-parallel state's
    layout across steps (no layout drift, no resharding)."""
    return jax.tree.map(lambda x: x.sharding, state)


def accumulate_gradients(
    trial: TrialMesh,
    fn: Callable,
    params: Any,
    batch_arrays: tuple,
    per_micro_args: tuple = (),
    *,
    grad_accum: int,
):
    """The ONE copy of the microbatch gradient-accumulation recipe.

    ``fn(params, *micro_batch_arrays, *micro_extra_args) -> (loss, aux)``
    is evaluated on ``grad_accum`` equal splits of each batch-major
    array (dim 0), with gradients, f32 losses, and aux values summed in
    a ``lax.scan`` carry; returns ``(loss_mean, aux_sum, grads_mean)``.
    ``per_micro_args`` are already microbatch-major ``(A, ...)`` (e.g.
    per-microbatch RNG keys). The reshape keeps batch rows sharded over
    the data axis WITHIN each microbatch — without the constraint GSPMD
    may shard the microbatch index instead, which parallelizes the scan
    away and gives up the activation-memory saving.
    """
    n = batch_arrays[0].shape[0]
    if n % grad_accum:
        raise ValueError(
            f"batch size {n} not divisible by grad_accum={grad_accum}"
        )
    mb = n // grad_accum

    def prep(a):
        m = a.reshape((grad_accum, mb) + a.shape[1:])
        return jax.lax.with_sharding_constraint(
            m, trial.sharding(None, DATA_AXIS, *([None] * (a.ndim - 1)))
        )

    micro = tuple(prep(a) for a in batch_arrays)

    def body(carry, xs):
        loss_acc, aux_acc, grad_acc = carry
        (l, aux), g = jax.value_and_grad(fn, has_aux=True)(params, *xs)
        return (
            loss_acc + l.astype(jnp.float32),
            jax.tree.map(jnp.add, aux_acc, aux),
            jax.tree.map(jnp.add, grad_acc, g),
        ), None

    # Abstract eval for the aux zero-carry (shapes/dtypes only, no FLOPs).
    aux_shape = jax.eval_shape(
        lambda p, *xs: fn(p, *xs)[1],
        params,
        *(m[0] for m in micro),
        *(x[0] for x in per_micro_args),
    )
    zeros = (
        jnp.zeros((), jnp.float32),
        jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), aux_shape),
        jax.tree.map(jnp.zeros_like, params),
    )
    (loss_sum, aux_sum, grad_sum), _ = jax.lax.scan(
        body, zeros, micro + per_micro_args
    )
    return (
        loss_sum / grad_accum,
        aux_sum,
        jax.tree.map(lambda g: g / grad_accum, grad_sum),
    )


def _validate_grad_accum(grad_accum: int) -> None:
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")


def _build_step_fn(
    trial: TrialMesh,
    model: VAE,
    tx: optax.GradientTransformation,
    beta: float,
    use_fused_loss: bool,
    remat: bool = False,
    grad_accum: int = 1,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, dict]]:
    """The un-jitted train-step body shared by :func:`make_train_step`
    (one step per dispatch) and :func:`make_multi_step` (scan-fused).

    ``remat=True`` wraps the forward in ``jax.checkpoint``: activations
    are recomputed during the backward pass instead of stored — the
    standard HBM-for-FLOPs trade when a model (or a long scan of fused
    steps) outgrows device memory. Numerically identical training.

    ``grad_accum=A`` splits the batch into A equal microbatches and
    accumulates their gradients in a ``lax.scan`` before the single
    optimizer update — activation memory drops to one microbatch's
    worth, so the effective batch can exceed HBM. The per-sample-mean
    loss makes the accumulated gradient the mean of microbatch
    gradients, i.e. the same estimator as the full batch (each
    microbatch draws its own reparameterization noise, so values match
    the full-batch program in expectation, not bitwise).
    """
    loss_impl = elbo_loss_sum
    if use_fused_loss:
        from jax.sharding import PartitionSpec as _P

        from multidisttorch_tpu.ops.pallas_elbo import fused_elbo_loss_sum
        from multidisttorch_tpu.parallel.mesh import DATA_AXIS as _AXIS

        if trial.size == 1:
            loss_impl = fused_elbo_loss_sum
        else:
            # A bare Pallas custom call is opaque to the partitioner, so
            # on a multi-device submesh XLA would all-gather all four
            # operands onto every chip. Run the kernel per-shard under
            # shard_map and psum the partial sums instead — each chip
            # reduces only its own batch rows.
            def loss_impl(logits, x, mu, logvar, beta):
                return compat_shard_map(
                    lambda lo, xx, m, lv: jax.lax.psum(
                        fused_elbo_loss_sum(lo, xx, m, lv, beta), _AXIS
                    ),
                    mesh=trial.mesh,
                    in_specs=(_P(_AXIS), _P(_AXIS), _P(_AXIS), _P(_AXIS)),
                    out_specs=_P(),
                    # pallas_call's out_shape carries no VMA annotation,
                    # so the varying-axis checker can't type it; the
                    # trailing psum makes the result replicated anyway.
                    check_vma=False,
                )(logits, x, mu, logvar)

    def forward(params, batch, rng):
        return model.apply({"params": params}, batch, rngs={"reparam": rng})

    if remat:
        forward = jax.checkpoint(forward)

    def microbatch_loss(params, mb_batch, mb_rng):
        m = mb_batch.shape[0]
        recon_logits, mu, logvar = forward(params, mb_batch, mb_rng)
        total = loss_impl(
            recon_logits, mb_batch.reshape(m, -1), mu, logvar, beta
        )
        return total / m

    def step_fn(state: TrainState, batch: jax.Array, rng: jax.Array):
        n = batch.shape[0]

        if grad_accum == 1:
            loss, grads = jax.value_and_grad(microbatch_loss)(
                state.params, batch, rng
            )
        else:
            loss, _, grads = accumulate_gradients(
                trial,
                lambda p, mb, r: (microbatch_loss(p, mb, r), ()),
                state.params,
                (batch,),
                (jax.random.split(rng, grad_accum),),
                grad_accum=grad_accum,
            )

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        metrics = {"loss_sum": (loss * n).astype(jnp.float32)}
        return new_state, metrics

    return step_fn


def make_train_step(
    trial: TrialMesh,
    model: VAE,
    tx: optax.GradientTransformation,
    *,
    beta: float = 1.0,
    use_fused_loss: bool = False,
    shardings: Any = None,
    remat: bool = False,
    grad_accum: int = 1,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, dict]]:
    """Build the compiled train step for one trial submesh.

    Returns ``step(state, batch, rng) -> (state, metrics)`` where
    ``batch`` is the trial-global batch (sharded over the submesh data
    axis on entry), and ``metrics['loss_sum']`` is the summed negative
    ELBO over the batch (reference logging contract, ``vae-hpo.py:73``).
    ``use_fused_loss`` swaps in the single-pass Pallas ELBO kernel
    (``ops/pallas_elbo.py``, forward + custom-VJP backward); default off
    because XLA's own fusion is already competitive and composes with
    the surrounding matmuls.

    ``shardings`` (from :func:`state_shardings` on a tensor-parallel
    state) pins the state layout in and out of the step, so a 2-D
    (data × model) trial runs Megatron-style: batch split over ``data``,
    weights split over ``model``, and GSPMD inserts the activation
    psums + gradient reductions over the right ICI axes.
    """
    repl = trial.replicated_sharding
    data = trial.batch_sharding
    _validate_grad_accum(grad_accum)
    step_fn = _build_step_fn(
        trial, model, tx, beta, use_fused_loss, remat, grad_accum
    )
    state_sh = repl if shardings is None else shardings
    return jax.jit(
        step_fn,
        in_shardings=(state_sh, data, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def make_multi_step(
    trial: TrialMesh,
    model: VAE,
    tx: optax.GradientTransformation,
    *,
    beta: float = 1.0,
    use_fused_loss: bool = False,
    shardings: Any = None,
    remat: bool = False,
    grad_accum: int = 1,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, dict]]:
    """K chained train steps in ONE dispatch, via ``lax.scan``.

    At the reference's workload size (a 784-400-20 MLP VAE at batch 128,
    ``/root/reference/vae-hpo.py:19-45,183``) a single train step is a
    few microseconds of MXU time, so a per-step Python dispatch — the
    reference's loop shape (``vae-hpo.py:67-74``) and
    :func:`make_train_step`'s — is host-bound. The TPU-first fix is to
    keep the loop on device: scan the step body over a stacked batch so
    the chip runs K optimizer updates per host round-trip.

    Returns ``multi_step(state, batches, rng) -> (state, metrics)`` where
    ``batches`` has shape ``(K, batch, ...)`` — sharded over the submesh
    data axis on dim 1 — and ``metrics['loss_sum']`` has shape ``(K,)``
    (one summed negative ELBO per inner step, same logging contract as
    :func:`make_train_step`). ``rng`` is split into K per-step keys
    inside the compiled program.
    """
    _validate_grad_accum(grad_accum)
    step_fn = _build_step_fn(
        trial, model, tx, beta, use_fused_loss, remat, grad_accum
    )
    repl = trial.replicated_sharding
    batches_sh = trial.sharding(None, DATA_AXIS)
    state_sh = repl if shardings is None else shardings

    def multi_fn(state: TrainState, batches: jax.Array, rng: jax.Array):
        rngs = jax.random.split(rng, batches.shape[0])

        def body(s, xs):
            b, r = xs
            s, metrics = step_fn(s, b, r)
            return s, metrics["loss_sum"]

        state, losses = jax.lax.scan(body, state, (batches, rngs))
        return state, {"loss_sum": losses}

    return jax.jit(
        multi_fn,
        in_shardings=(state_sh, batches_sh, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


# --- trial stacking: K same-shape trials through ONE compiled program ---
#
# At the flagship's size a whole train step is microseconds of MXU time,
# so a sweep of small trials is dispatch-bound no matter how its
# submeshes are carved (docs/DISPATCH.md; VERDICT pins flagship MFU at
# 0.13-0.25 with dispatch as prime suspect). Scan-fusion amortizes
# dispatch *in time* (more steps per call); stacking amortizes it *in
# trials*: bucket K configs that share every array shape (architecture,
# batch size) and differ only in scalar hypers (lr, beta, seed), stack
# their states along a leading trial axis, and vmap the step body over
# that axis — XLA fuses K trials' matmuls into batched ops inside one
# program, so one host dispatch advances K trials (the DrJAX
# mapped-workload construction, arXiv:2403.07128). Composes with
# lax.scan chunking: one dispatch = fused_steps x K optimizer updates.
#
# Per-trial hypers ride in as batched arrays (TrialHypers); the
# optimizer is rebuilt per-lane inside the vmap from the traced lr as
# chain(scale_by_adam, scale(-lr)) — the literal definition of
# optax.adam(lr), so state trees AND update math are bit-identical to
# the unstacked driver path (regression-tested in tests/test_stacking).
# `active` masks a lane's parameter updates (x1.0 live, x0.0 retired):
# a finished trial's lane keeps flowing through the same compiled
# program with frozen params until the driver refills the lane with the
# next queued config (`write_lane`) — retirement and refill never
# recompile.


@struct.dataclass
class TrialHypers:
    """Per-lane scalar hyperparameters of a stacked trial bucket, each
    shape ``(K,)``: the vmapped axis of everything that may differ
    between bucket members without changing the compiled program."""

    lr: jnp.ndarray
    beta: jnp.ndarray
    # 1.0 = lane training; 0.0 = lane retired (updates masked to zero,
    # params frozen at their final values until the lane is refilled).
    active: jnp.ndarray

    @staticmethod
    def stack(lrs, betas, active=None) -> "TrialHypers":
        lrs = jnp.asarray(lrs, jnp.float32)
        return TrialHypers(
            lr=lrs,
            beta=jnp.asarray(betas, jnp.float32),
            active=(
                jnp.ones_like(lrs)
                if active is None
                else jnp.asarray(active, jnp.float32)
            ),
        )


def build_lane_state(model: VAE, seed: int) -> TrainState:
    """One lane's fresh :class:`TrainState` (un-placed, no leading axis).

    Adam's init is learning-rate-independent (zero moments + count), so
    a single builder serves every lane regardless of its lr — the same
    tree :func:`build_train_state` produces for the unstacked driver
    path, which is what keeps stacked/unstacked checkpoints
    interchangeable."""
    return build_train_state(model, optax.adam(1.0), jax.random.key(seed))


def build_stacked_train_state(model: VAE, seeds: Sequence[int]) -> TrainState:
    """Stack K per-seed lane states along a new leading trial axis."""
    lanes = [build_lane_state(model, s) for s in seeds]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *lanes)


def create_stacked_train_state(
    trial: TrialMesh, model: VAE, seeds: Sequence[int]
) -> TrainState:
    """Build and place a stacked state: every leaf gains a leading
    ``K = len(seeds)`` axis, replicated over the submesh (the trial axis
    is the vmap axis, never a mesh axis — lanes are data-independent by
    construction, so there is nothing to communicate between them)."""
    return trial.device_put(build_stacked_train_state(model, seeds))


def _lane_fold_rngs(base_rngs: jax.Array, lane_steps: jnp.ndarray) -> jax.Array:
    """Per-lane step keys: ``fold_in(base_k, step_k)`` — the SAME stream
    as the unstacked per-step driver path (driver.py folds its trial key
    with the global optimizer-step count), which is what makes
    stacked-vs-unstacked bit-for-bit parity possible."""
    return jax.vmap(jax.random.fold_in)(base_rngs, lane_steps)


def _stacked_lane_body(
    trial: TrialMesh, model: VAE, remat: bool, grad_accum: int
):
    """The per-lane step body vmapped by both stacked step builders:
    ``(state, batch, rng, lr, beta, active) -> (state, loss_sum)`` with
    lr/beta as traced scalars (the batched-hypers contract) and the
    optimizer rebuilt from lr as optax.adam's own definition."""

    def forward(params, batch, rng):
        return model.apply({"params": params}, batch, rngs={"reparam": rng})

    if remat:
        forward = jax.checkpoint(forward)

    def microbatch_loss(params, mb_batch, mb_rng, beta):
        m = mb_batch.shape[0]
        recon_logits, mu, logvar = forward(params, mb_batch, mb_rng)
        total = elbo_loss_sum(
            recon_logits, mb_batch.reshape(m, -1), mu, logvar, beta
        )
        return total / m

    def lane_body(state, batch, rng, lr, beta, active):
        n = batch.shape[0]
        if grad_accum == 1:
            loss, grads = jax.value_and_grad(microbatch_loss)(
                state.params, batch, rng, beta
            )
        else:
            loss, _, grads = accumulate_gradients(
                trial,
                lambda p, mb, r: (microbatch_loss(p, mb, r, beta), ()),
                state.params,
                (batch,),
                (jax.random.split(rng, grad_accum),),
                grad_accum=grad_accum,
            )
        # optax.adam(lr) IS chain(scale_by_adam, scale(-lr)); building it
        # from the traced per-lane lr keeps state structure and update
        # arithmetic bit-identical to the unstacked path.
        tx = optax.chain(optax.scale_by_adam(), optax.scale(-lr))
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        # Retirement mask as a SELECT, not a multiply: `active * update`
        # changes XLA's FMA contraction around the parameter add and
        # costs live lanes one ulp vs the unstacked program (measured);
        # where() picks whole computed values, so live lanes stay
        # bit-identical and retired lanes stay frozen exactly.
        new_state = jax.tree.map(
            lambda new, old: jnp.where(active > 0.5, new, old),
            new_state,
            state,
        )
        return new_state, (loss * n).astype(jnp.float32)

    return lane_body


def make_stacked_train_step(
    trial: TrialMesh,
    model: VAE,
    *,
    remat: bool = False,
    grad_accum: int = 1,
):
    """One vmapped optimizer step for K stacked trials in ONE dispatch.

    Returns ``step(state, hypers, batch, base_rngs, lane_steps) ->
    (state, metrics)`` where every ``state`` leaf and ``batch``
    (``(K, B, ...)``, dim 1 sharded over the submesh data axis) carry a
    leading trial axis, ``hypers`` is a :class:`TrialHypers` of ``(K,)``
    arrays, ``base_rngs`` is a ``(K,)`` key array (one per-trial stream,
    ``key(seed+1)`` in the driver), and ``lane_steps`` ``(K,)`` int32 is
    each lane's optimizer-step count — folded into its key exactly like
    the unstacked per-step path, so a stacked trial's RNG stream (and
    therefore its weights) match the unstacked trial bit-for-bit.
    ``metrics['loss_sum']`` is ``(K,)``, one summed negative ELBO per
    trial (the reference logging contract, per lane).

    The fused Pallas ELBO is deliberately NOT plumbed here: its kernel
    takes beta as a compile-time constant, and per-lane traced betas
    would force one kernel instance per lane — the XLA loss fuses fine
    under vmap and benches within noise of the kernel (BENCH r4).
    """
    _validate_grad_accum(grad_accum)
    lane_body = _stacked_lane_body(trial, model, remat, grad_accum)
    vstep = jax.vmap(lane_body, in_axes=(0, 0, 0, 0, 0, 0))
    repl = trial.replicated_sharding
    batch_sh = trial.sharding(None, DATA_AXIS)

    def step_fn(
        state: TrainState,
        hypers: TrialHypers,
        batch: jax.Array,
        base_rngs: jax.Array,
        lane_steps: jnp.ndarray,
    ):
        rngs = _lane_fold_rngs(base_rngs, lane_steps)
        state, loss_sums = vstep(
            state, batch, rngs, hypers.lr, hypers.beta, hypers.active
        )
        return state, {"loss_sum": loss_sums}

    return jax.jit(
        step_fn,
        in_shardings=(repl, repl, batch_sh, repl, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )


def make_stacked_multi_step(
    trial: TrialMesh,
    model: VAE,
    *,
    remat: bool = False,
    grad_accum: int = 1,
):
    """``S`` scan-chained vmapped steps: one dispatch = S x K optimizer
    updates (scan amortizes dispatch in time, the stacked axis amortizes
    it in trials — the two compose multiplicatively).

    Returns ``multi(state, hypers, batches, base_rngs, lane_steps) ->
    (state, metrics)`` with ``batches`` of shape ``(S, K, B, ...)``
    (dim 2 sharded over the submesh data axis) and
    ``metrics['loss_sum']`` of shape ``(S, K)``. Inner step ``s`` folds
    ``lane_steps + s`` into each lane's base key — the identical stream
    to :func:`make_stacked_train_step` called S times, so chunked and
    per-step stacked training produce bit-identical weights (unlike
    :func:`make_multi_step`, whose split-based stream is its own).
    """
    _validate_grad_accum(grad_accum)
    lane_body = _stacked_lane_body(trial, model, remat, grad_accum)
    vstep = jax.vmap(lane_body, in_axes=(0, 0, 0, 0, 0, 0))
    repl = trial.replicated_sharding
    batches_sh = trial.sharding(None, None, DATA_AXIS)

    def multi_fn(
        state: TrainState,
        hypers: TrialHypers,
        batches: jax.Array,
        base_rngs: jax.Array,
        lane_steps: jnp.ndarray,
    ):
        def body(s, xs):
            b, i = xs
            rngs = _lane_fold_rngs(base_rngs, lane_steps + i)
            s, loss_sums = vstep(
                s, b, rngs, hypers.lr, hypers.beta, hypers.active
            )
            return s, loss_sums

        state, losses = jax.lax.scan(
            body, state, (batches, jnp.arange(batches.shape[0], dtype=jnp.int32))
        )
        return state, {"loss_sum": losses}

    return jax.jit(
        multi_fn,
        in_shardings=(repl, repl, batches_sh, repl, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )


def _stacked_eval_lane(model: VAE):
    """The per-lane masked posterior-mean eval body shared by
    :func:`make_stacked_eval_step` and the fused PBT generation program
    (:func:`make_pbt_generation_step`) — one copy, so a lane's eval loss
    is bit-identical whether it is scored standalone or inside the
    fused generation dispatch."""
    from multidisttorch_tpu.ops.losses import elbo_loss_weighted_sum

    def lane_eval(params, beta, batch, weights):
        n = batch.shape[0]
        flat = batch.reshape(n, -1)
        mu, logvar = model.apply({"params": params}, batch, method="encode")
        recon_logits = model.apply({"params": params}, mu, method="decode")
        return elbo_loss_weighted_sum(
            recon_logits, flat, mu, logvar, weights, beta
        ).astype(jnp.float32)

    return lane_eval


def _scan_eval_sums(veval, params, betas, eval_batches, eval_weights):
    """Scan-accumulate the per-lane eval loss sums over ``(E, B, ...)``
    stacked eval batches from a zero f32 carry — the ONE copy of the
    eval reduction structure shared by :func:`make_stacked_eval_scan`
    and the fused PBT generation program. Sharing the structure is a
    bit-parity requirement, not a style choice: XLA fuses a scanned
    reduction differently from a per-batch one (last-ulp reassociation,
    measured on XLA:CPU at the flagship model size), so the per-submesh
    reference path and the fused path must BOTH reduce through this
    scan for their scores to stay bit-identical."""
    k_lanes = betas.shape[0]

    def ebody(acc, xs):
        b, w = xs
        return acc + veval(params, betas, b, w), None

    sums, _ = jax.lax.scan(
        ebody,
        jnp.zeros((k_lanes,), jnp.float32),
        (eval_batches, eval_weights),
    )
    return sums


def make_stacked_eval_scan(trial: TrialMesh, model: VAE):
    """Whole-eval-set masked eval for K stacked trials in ONE dispatch:
    ``eval_scan(state, hypers, eval_batches, eval_weights) ->
    {'loss_sum': (K,)}`` with ``eval_batches`` ``(E, B, ...)`` and
    ``eval_weights`` ``(E, B)`` (dim 1 data-sharded, shared across
    lanes) — the per-batch :func:`make_stacked_eval_step` folded over
    the eval set on device. This is the PBT reference path's scorer:
    structurally identical to the eval phase inside the fused
    generation program (see :func:`_scan_eval_sums`)."""
    repl = trial.replicated_sharding
    eval_sh = trial.sharding(None, DATA_AXIS)
    veval = jax.vmap(_stacked_eval_lane(model), in_axes=(0, 0, None, None))

    def eval_fn(
        state: TrainState, hypers: TrialHypers, eval_batches, eval_weights
    ):
        return {
            "loss_sum": _scan_eval_sums(
                veval, state.params, hypers.beta, eval_batches,
                eval_weights,
            )
        }

    return jax.jit(
        eval_fn,
        in_shardings=(repl, repl, eval_sh, eval_sh),
        out_shardings=repl,
    )


def make_stacked_eval_step(trial: TrialMesh, model: VAE):
    """Masked posterior-mean eval for K stacked trials in one dispatch:
    ``eval(state, hypers, batch, weights) -> {'loss_sum': (K,)}`` — the
    batch and its pad-mask weights are shared across lanes (every trial
    scores the same test rows, reference contract), only the state and
    beta are per-lane."""
    repl = trial.replicated_sharding
    data = trial.batch_sharding

    veval = jax.vmap(_stacked_eval_lane(model), in_axes=(0, 0, None, None))

    def eval_fn(state: TrainState, hypers: TrialHypers, batch, weights):
        return {"loss_sum": veval(state.params, hypers.beta, batch, weights)}

    return jax.jit(
        eval_fn,
        in_shardings=(repl, repl, data, data),
        out_shardings=repl,
    )


def make_lane_ops(trial: TrialMesh):
    """Compiled lane surgery for mask-and-refill: ``(read, write)``.

    ``read(state, k) -> TrainState`` slices lane ``k`` out of a stacked
    state (checkpoint/result capture at retirement); ``write(state,
    lane_state, k) -> state`` overwrites lane ``k`` with a freshly
    initialized lane (refill). ``k`` is a TRACED int32, so every lane
    index reuses one compiled program each way — a bucket churns through
    its whole queue with zero recompiles (asserted via ``_cache_size``
    in tests)."""
    repl = trial.replicated_sharding

    def read(state: TrainState, k) -> TrainState:
        return jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, k, 0, keepdims=False),
            state,
        )

    def write(state: TrainState, lane: TrainState, k) -> TrainState:
        return jax.tree.map(
            lambda a, b: jax.lax.dynamic_update_index_in_dim(
                a, b.astype(a.dtype), k, 0
            ),
            state,
            lane,
        )

    read_j = jax.jit(read, in_shardings=(repl, None), out_shardings=repl)
    write_j = jax.jit(
        write,
        in_shardings=(repl, repl, None),
        out_shardings=repl,
        donate_argnums=(0,),
    )
    return read_j, write_j


# --- fused PBT: exploit/explore as collectives over the lane axis ---
#
# The stacked lane axis (above) already runs K trials as one vmapped
# program; population-based training adds one more per-generation op —
# the exploit/explore exchange — and the pre-stacking PBT ran it
# host-side: fetch every member's score, rank on the host, device_get/
# device_put each exploited member's whole state across submeshes. Over
# the lane axis the exchange is just lane-collectives (the DrJAX
# population-as-mapped-axis construction, arXiv:2403.07128): a stable
# argsort ranks lanes, a gather copies winners' params+opt-state into
# losers' lanes, and a where perturbs the batched per-lane lr — so a
# whole generation (train scan + eval scan + exchange) compiles into
# ONE program and dispatches once, with no host round-trip per
# exploited member. The explore perturbation is a PURE function of
# (explore_key, generation, target lane) — the seeding contract that
# lets the host-side reference path (hpo/pbt.py, fused=False) draw the
# identical factors and stay bit-identical to the in-program exchange
# (docs/PBT.md).

# Domain-separation tag folded into key(seed) for the explore stream:
# keeps perturbation draws disjoint from the param-init (key(seed+k))
# and per-step data (key(seed+k+1)) streams, which share the seed space.
PBT_EXPLORE_TAG = 0x9E3779B9


def pbt_explore_key(seed: int) -> jax.Array:
    """The population's explore stream root: every perturbation in a
    PBT run (fused or host-side reference) derives from this one key,
    so the two paths draw identical factors."""
    return jax.random.fold_in(jax.random.key(seed), PBT_EXPLORE_TAG)


def pbt_perturb_factor(
    explore_key: jax.Array, gen, lane, perturb_factors: tuple
) -> jnp.ndarray:
    """The explore draw for (generation, target lane): a pure function
    — ``fold_in(fold_in(explore_key, gen), lane)`` indexing the factor
    table — identical eager (host reference path) and traced (inside
    the fused generation program), which is the whole seeding contract.
    ``gen``/``lane`` may be Python ints or traced int32 scalars."""
    k = jax.random.fold_in(jax.random.fold_in(explore_key, gen), lane)
    idx = jax.random.randint(k, (), 0, len(perturb_factors))
    return jnp.asarray(perturb_factors, jnp.float32)[idx]


def pbt_exchange(
    state: TrainState,
    hypers: TrialHypers,
    eval_sums: jnp.ndarray,
    gen,
    explore_key: jax.Array,
    *,
    n_exploit: int,
    perturb_factors: tuple,
    lr_min: float,
    lr_max: float,
):
    """The in-program exploit/explore over the lane axis.

    ``eval_sums`` is the per-lane summed eval loss ``(K,)`` (f32; the
    monotone rank statistic — dividing by the shared row count changes
    no ordering). Ranking sanitizes NaN to ``+inf`` with a STABLE
    argsort, so a diverged lane ranks strictly last (never a source)
    and ties break by lane index — the same total order the host
    reference path computes with ``np.argsort(kind='stable')``.

    With ``n_exploit`` top/bottom slots (a static int, clamped by the
    caller to ``K // 2`` so the slices can never overlap), bottom slot
    ``i`` exploits top slot ``i`` iff its sanitized loss is strictly
    worse: the whole per-lane TrainState (params, optimizer moments,
    step) is GATHERED from the source lane, and the target lane's lr
    becomes ``clip(lr[src] * factor, lr_min, lr_max)`` with the factor
    drawn by :func:`pbt_perturb_factor`. Non-exploiting lanes pass
    through untouched (gather from self). ``n_exploit == 0`` (the K=1
    degenerate population) is the identity exchange.

    Returns ``(state, hypers, stats)`` where ``stats`` carries
    ``order`` (lanes best→worst), ``exploited`` (K,) bool, ``src``
    (K,) int32 (self where not exploited), and ``new_lr`` (K,) f32 —
    the host's books for telemetry and history, one fetch per
    generation.
    """
    k_lanes = hypers.lr.shape[0]
    sanitized = jnp.where(jnp.isnan(eval_sums), jnp.inf, eval_sums)
    order = jnp.argsort(sanitized, stable=True).astype(jnp.int32)
    lanes = jnp.arange(k_lanes, dtype=jnp.int32)
    if n_exploit == 0:
        stats = {
            "order": order,
            "exploited": jnp.zeros((k_lanes,), bool),
            "src": lanes,
            "new_lr": hypers.lr,
        }
        return state, hypers, stats
    top = order[:n_exploit]
    bottom = order[k_lanes - n_exploit:]
    cond = sanitized[bottom] > sanitized[top]
    src = lanes.at[bottom].set(jnp.where(cond, top, bottom))
    exploited = jnp.zeros((k_lanes,), bool).at[bottom].set(cond)
    factors = jax.vmap(
        lambda lane: pbt_perturb_factor(
            explore_key, gen, lane, perturb_factors
        )
    )(lanes)
    new_lr = jnp.where(
        exploited,
        jnp.clip(jnp.take(hypers.lr, src) * factors, lr_min, lr_max),
        hypers.lr,
    )
    new_state = jax.tree.map(lambda a: jnp.take(a, src, axis=0), state)
    new_hypers = TrialHypers(
        lr=new_lr, beta=hypers.beta, active=hypers.active
    )
    stats = {
        "order": order,
        "exploited": exploited,
        "src": src,
        "new_lr": new_lr,
    }
    return new_state, new_hypers, stats


def make_pbt_generation_step(
    trial: TrialMesh,
    model: VAE,
    *,
    n_exploit: int,
    perturb_factors: tuple,
    lr_min: float,
    lr_max: float,
):
    """ONE whole PBT generation as ONE compiled dispatch: an S-step
    train scan over K stacked lanes (the exact
    :func:`make_stacked_multi_step` body and RNG stream), an eval scan
    over E shared pad-and-mask batches (the exact
    :func:`make_stacked_eval_step` lane body), and the in-program
    :func:`pbt_exchange` — where the pre-stacking PBT paid K train
    dispatches + K·E eval dispatches + a host round-trip per exploited
    member per generation.

    Returns ``gen_step(state, hypers, batches, eval_batches,
    eval_weights, base_rngs, lane_steps, gen, explore_key) ->
    (state, hypers, stats)`` with ``batches`` of shape ``(S, K, B, ...)``
    (dim 2 data-sharded), ``eval_batches``/``eval_weights`` of shape
    ``(E, B, ...)``/``(E, B)`` shared across lanes, and ``gen`` a traced
    int32 scalar — so one executable serves every generation (the
    ``pbt_gen`` program kind, registered and AOT-compiled through
    ``compile/programs.py``). ``stats`` carries per-step train losses
    ``(S, K)``, per-lane eval loss sums ``(K,)``, and the exchange
    books (:func:`pbt_exchange`).
    """
    lane_body = _stacked_lane_body(trial, model, remat=False, grad_accum=1)
    vstep = jax.vmap(lane_body, in_axes=(0, 0, 0, 0, 0, 0))
    veval = jax.vmap(_stacked_eval_lane(model), in_axes=(0, 0, None, None))
    repl = trial.replicated_sharding
    batches_sh = trial.sharding(None, None, DATA_AXIS)
    eval_sh = trial.sharding(None, DATA_AXIS)

    def gen_fn(
        state: TrainState,
        hypers: TrialHypers,
        batches: jax.Array,
        eval_batches: jax.Array,
        eval_weights: jax.Array,
        base_rngs: jax.Array,
        lane_steps: jnp.ndarray,
        gen: jnp.ndarray,
        explore_key: jax.Array,
    ):
        def body(s, xs):
            b, i = xs
            rngs = _lane_fold_rngs(base_rngs, lane_steps + i)
            s, loss_sums = vstep(
                s, b, rngs, hypers.lr, hypers.beta, hypers.active
            )
            return s, loss_sums

        state, train_losses = jax.lax.scan(
            body,
            state,
            (batches, jnp.arange(batches.shape[0], dtype=jnp.int32)),
        )

        # Eval lane-SEQUENTIALLY at width 1 (lax.map over the lane
        # axis), not as one width-K vmap: XLA's batched eval reduction
        # at width K rounds the loss sum differently from the width-1
        # program the per-submesh reference members run (last-ulp,
        # measured at the flagship size on a sharded submesh), and the
        # fused-vs-reference bit-parity contract pins the reference's
        # arithmetic. Eval is a small fraction of a generation's FLOPs
        # (E forward passes vs S forward+backward+update), so the
        # sequential map costs little; the train scan stays width-K.
        def eval_one(args):
            p1, b1 = args
            return _scan_eval_sums(
                veval, p1, b1, eval_batches, eval_weights
            )[0]

        eval_sums = jax.lax.map(
            eval_one,
            (
                jax.tree.map(lambda x: x[:, None], state.params),
                hypers.beta[:, None],
            ),
        )

        state, hypers_out, stats = pbt_exchange(
            state,
            hypers,
            eval_sums,
            gen,
            explore_key,
            n_exploit=n_exploit,
            perturb_factors=perturb_factors,
            lr_min=lr_min,
            lr_max=lr_max,
        )
        stats["train_loss_sum"] = train_losses
        stats["eval_loss_sum"] = eval_sums
        return state, hypers_out, stats

    return jax.jit(
        gen_fn,
        in_shardings=(
            repl, repl, batches_sh, eval_sh, eval_sh, repl, repl, repl,
            repl,
        ),
        out_shardings=(repl, repl, repl),
        donate_argnums=(0, 1),
    )


def wrap_step_with_hooks(
    step_fn: Callable,
    *,
    before: Optional[Callable] = None,
    transform_batch: Optional[Callable] = None,
    batch_argnum: int = 1,
) -> Callable:
    """Host-side hook seam around a compiled step — the fault-injection
    thread-through point (``faults/inject.py`` via ``hpo/driver.py``),
    usable for any pre-dispatch instrumentation.

    ``before(batch)`` runs before the dispatch (it may raise — an
    injected crash/preemption — or stall — an injected straggler);
    ``transform_batch(batch) -> batch`` may replace the batch operand
    (NaN poisoning for divergence drills). Both see the positional
    argument at ``batch_argnum``. The compiled program itself is
    untouched: hooks never change shapes, so nothing recompiles, and a
    ``None``-hook wrap is exactly the bare step.
    """
    if before is None and transform_batch is None:
        return step_fn

    def hooked(*args, **kwargs):
        args = list(args)
        batch = args[batch_argnum]
        if before is not None:
            before(batch)
        if transform_batch is not None:
            args[batch_argnum] = transform_batch(batch)
        return step_fn(*args, **kwargs)

    # Keep the compiled function reachable through the wrapper: the
    # device cost books (telemetry/device.py) need ``.lower()`` on the
    # underlying jit fn to run XLA's cost analysis on the program that
    # actually dispatches.
    hooked.__wrapped__ = step_fn
    return hooked


def make_eval_step(
    trial: TrialMesh,
    model: VAE,
    *,
    beta: float = 1.0,
    with_recon: bool = True,
    masked: bool = False,
    sampled: bool = False,
    shardings: Any = None,
) -> Callable[..., dict]:
    """Compiled eval step: summed ELBO (+ reconstructions) for one batch.

    The analog of the reference's ``test`` inner loop
    (``vae-hpo.py:101-105``) minus the host-side PNG I/O; with
    ``with_recon=True`` reconstruction probabilities are returned so the
    caller can image them (``vae-hpo.py:106-116``). Loss-only callers
    (e.g. PBT scoring) pass ``with_recon=False`` to skip materializing
    the (N, input_dim) output.

    ``masked=True`` returns ``eval_fn(state, batch, weights)`` whose
    ``loss_sum`` is the weight-vector masked sum — the static-shape way
    to evaluate a test set that doesn't divide the batch size: the final
    partial batch arrives zero-padded with 0.0 weights
    (``data.sampler.EvalDataIterator``) and contributes exactly its real
    rows, so reported test losses cover every row, like the reference's.

    ``sampled=True`` appends an ``rng`` argument and evaluates the
    reference's exact semantics — the full sampled forward, z drawn from
    the posterior (``vae-hpo.py:101-105`` calls ``model(data)``, which
    reparameterizes, ``vae-hpo.py:42-45``) — for apples-to-apples test
    losses against the reference. Default stays the posterior mean:
    deterministic, and a strictly tighter bound.
    """
    from multidisttorch_tpu.ops.losses import elbo_loss_weighted_sum

    repl = trial.replicated_sharding
    data = trial.batch_sharding
    # ``shardings`` (a TrainState of NamedShardings) pins a
    # weight-sharded state's layout on entry, same as the train steps —
    # without it a TP/EP state would be gathered to replicated per call.
    state_sh = repl if shardings is None else shardings

    def eval_core(state: TrainState, batch: jax.Array, weights, rng=None):
        n = batch.shape[0]
        flat = batch.reshape(n, -1)
        if sampled:
            recon_logits, mu, logvar = model.apply(
                {"params": state.params}, batch, rngs={"reparam": rng}
            )
        else:
            mu, logvar = model.apply(
                {"params": state.params}, batch, method="encode"
            )
            recon_logits = model.apply(
                {"params": state.params}, mu, method="decode"
            )
        if weights is None:
            loss = elbo_loss_sum(recon_logits, flat, mu, logvar, beta)
        else:
            loss = elbo_loss_weighted_sum(
                recon_logits, flat, mu, logvar, weights, beta
            )
        out = {"loss_sum": loss.astype(jnp.float32)}
        if with_recon:
            out["recon"] = jax.nn.sigmoid(recon_logits.astype(jnp.float32))
        return out

    if masked and sampled:
        return jax.jit(
            eval_core,
            in_shardings=(state_sh, data, data, repl),
            out_shardings=repl,
        )
    if masked:
        def eval_masked(state: TrainState, batch: jax.Array, weights):
            return eval_core(state, batch, weights)

        return jax.jit(
            eval_masked,
            in_shardings=(state_sh, data, data),
            out_shardings=repl,
        )
    if sampled:
        def eval_sampled_fn(state: TrainState, batch: jax.Array, rng):
            return eval_core(state, batch, None, rng)

        return jax.jit(
            eval_sampled_fn,
            in_shardings=(state_sh, data, repl),
            out_shardings=repl,
        )

    def eval_fn(state: TrainState, batch: jax.Array):
        return eval_core(state, batch, None)

    return jax.jit(eval_fn, in_shardings=(state_sh, data), out_shardings=repl)


def make_sample_step(
    trial: TrialMesh,
    model: VAE,
    num_samples: int = 64,
    *,
    shardings: Any = None,
) -> Callable[[TrainState, jax.Array], jax.Array]:
    """Compiled prior-sampling step: ``randn(n, latent) → decode``.

    Mirrors the reference's per-epoch sample dump
    (``vae-hpo.py:163-170``), returning pixel probabilities for imaging.
    ``shardings`` pins a weight-sharded state's layout on entry.
    """
    repl = trial.replicated_sharding
    state_sh = repl if shardings is None else shardings

    def sample_fn(state: TrainState, rng: jax.Array):
        z = jax.random.normal(rng, (num_samples, model.latent_dim))
        probs = model.apply(
            {"params": state.params}, z, method="decode_probs"
        )
        return probs.astype(jnp.float32)

    return jax.jit(
        sample_fn, in_shardings=(state_sh, repl), out_shardings=repl
    )
