from multidisttorch_tpu.train.steps import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_multi_step,
    make_sample_step,
    make_train_step,
    state_shardings,
)
