from multidisttorch_tpu.train.lm import (
    create_lm_state,
    lm_chunk_sharding,
    lm_loss_mean,
    make_lm_eval_step,
    make_lm_multi_step,
    make_lm_sample,
    make_lm_train_step,
)
from multidisttorch_tpu.train.lm_decode import make_cached_lm_sample
from multidisttorch_tpu.train.lm_pipeline import make_pipelined_lm
from multidisttorch_tpu.train.lm_quant import (
    dequantize_lm_params,
    quantize_lm_params,
)
from multidisttorch_tpu.train.steps import (
    TrainState,
    TrialHypers,
    build_lane_state,
    build_stacked_train_state,
    create_stacked_train_state,
    create_train_state,
    make_eval_step,
    make_lane_ops,
    make_multi_step,
    make_sample_step,
    make_stacked_eval_step,
    make_stacked_multi_step,
    make_stacked_train_step,
    make_train_step,
    state_shardings,
)
