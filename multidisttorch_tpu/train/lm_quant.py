"""Post-training int8 weight quantization for LM decoding.

KV-cached decode (``train/lm_decode.py``) is bandwidth-bound: each
generated token streams every weight matrix through the chip once. A
per-output-channel symmetric int8 quantization cuts that traffic 4x
against f32 — the classic serving trade — and the dequantize-scale
fuses into the matmul under XLA, so the compute path barely changes.

``quantize_lm_params`` rewrites every 2-D dense kernel in a
TransformerLM param tree as ``{"q": int8, "scale": f32 (out,)}``
(bias untouched; embeddings, norms, and everything 1-D stay f32 — the
embedding is a gather, not a matmul, and norm params are tiny).
``train.lm_decode._dense`` understands both forms, so the quantized
tree drops straight into ``make_cached_lm_sample`` — with the sampler's
DEFAULT replicated placement (the quantized tree's structure differs
from the f32 one, so ``shardings=`` pytrees built from the f32 state
do not apply; weight-sharded serving would need shardings built for
the quantized structure). Accuracy is a measured property, not a
promise: ``tests/test_lm_quant.py`` bounds the logit drift and checks
greedy-decode agreement on a trained model.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp


def _quantize_kernel(w: jnp.ndarray) -> dict:
    """Symmetric per-output-channel int8: w ≈ q * scale."""
    amax = jnp.max(jnp.abs(w), axis=0)  # (out,)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


def quantize_lm_params(params: Any) -> Any:
    """Quantize every 2-D ``kernel`` leaf of an LM param tree to int8.

    Returns a tree of the same structure where each dense layer's
    ``{"kernel": (in, out) f32, "bias": ...}`` becomes
    ``{"q": int8, "scale": f32, "bias": ...}``. Embeddings
    (``embedding`` leaves), LayerNorm scales/biases, and biases are
    untouched.
    """

    def rewrite(tree):
        if isinstance(tree, dict):
            if "kernel" in tree and getattr(tree["kernel"], "ndim", 0) == 2:
                out = {k: v for k, v in tree.items() if k != "kernel"}
                out.update(_quantize_kernel(tree["kernel"]))
                return out
            return {k: rewrite(v) for k, v in tree.items()}
        return tree

    # pure on-device transform: no host round-trip, placement preserved
    # for the untouched leaves
    return rewrite(params)


def dequantize_lm_params(qparams: Any) -> Any:
    """Reconstruct an f32 param tree (for comparison/inspection)."""

    def rewrite(tree):
        if isinstance(tree, dict):
            if "q" in tree and "scale" in tree:
                out = {k: v for k, v in tree.items()
                       if k not in ("q", "scale")}
                out["kernel"] = (
                    tree["q"].astype(jnp.float32) * tree["scale"]
                )
                return out
            return {k: rewrite(v) for k, v in tree.items()}
        return tree

    return rewrite(qparams)
