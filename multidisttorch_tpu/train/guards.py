"""Divergence detection for training loops.

A non-finite loss is a *result*, not an infrastructure failure: the
trial's hyperparameters drove the optimization off a cliff, and re-running
the same config reproduces the same NaN (training here is deterministic
in (config, seed)). Retrying it wastes the submesh; recording a garbage
metric silently poisons the sweep's comparison. The honest shape is a
structured :class:`DivergenceError` naming the step, raised at the
loop's existing host-sync point — never an extra device round-trip.

The HPO driver classifies this error terminally (``status="diverged"``,
no retry — ``hpo/supervision.py``); the non-HPO loops (classifier, LM)
get the same contract through :func:`check_finite` / :func:`guard_finite`.
"""

from __future__ import annotations

import math
from typing import Callable, Optional


class DivergenceError(RuntimeError):
    """Training produced a non-finite loss.

    Carries enough structure for a supervisor to act on it without
    parsing the message: the step at which the non-finite value was
    *observed* (detection happens at the loop's existing sync cadence,
    so the true divergence step is <= this one), and the offending value.
    """

    def __init__(
        self,
        what: str,
        value: float,
        *,
        step: Optional[int] = None,
        trial_id: Optional[int] = None,
    ):
        self.what = what
        self.value = value
        self.step = step
        self.trial_id = trial_id
        where = f" at step {step}" if step is not None else ""
        who = f"trial {trial_id}: " if trial_id is not None else ""
        super().__init__(
            f"{who}{what} is non-finite ({value}){where} — training "
            "diverged; this is a terminal result of the configuration, "
            "not a retryable infrastructure fault"
        )


def check_finite(
    value,
    what: str = "loss",
    *,
    step: Optional[int] = None,
    trial_id: Optional[int] = None,
) -> float:
    """Raise :class:`DivergenceError` if ``value`` is NaN/inf; else
    return it as a float. ``value`` may be a python float or a scalar
    array — callers pass something they were already fetching (an epoch
    average, a logged loss), so the check adds no host syncs."""
    v = float(value)
    if not math.isfinite(v):
        raise DivergenceError(what, v, step=step, trial_id=trial_id)
    return v


def guard_finite(
    step_fn: Callable,
    *,
    key: str = "loss",
    every: int = 1,
    what: str = "train loss",
) -> Callable:
    """Wrap a compiled ``step(state, *args) -> (state, metrics)`` so a
    non-finite ``metrics[key]`` surfaces as a :class:`DivergenceError`
    naming the optimizer step instead of flowing on as a silent garbage
    metric.

    The check fetches the metric to host, which synchronizes the
    dispatch pipeline — that is the price of *any* host-side decision on
    a device value. ``every=N`` checks one step in N (detection lag <= N
    steps, sync cost 1/N); loops that already fetch the loss each step
    (the classifier/LM example loops) lose nothing at ``every=1``.

    For scan-fused steps whose ``metrics[key]`` is a per-inner-step
    array, the first non-finite entry names the exact inner step.
    """
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    calls = 0

    def guarded(state, *args, **kw):
        nonlocal calls
        new_state, metrics = step_fn(state, *args, **kw)
        calls += 1
        if calls % every == 0:
            import numpy as np

            vals = np.asarray(metrics[key], dtype=np.float64).reshape(-1)
            step_after = int(new_state.step)  # steps applied so far
            bad = np.flatnonzero(~np.isfinite(vals))
            if bad.size:
                # For a (K,) fused metric, step numbering is contiguous
                # ending at step_after; entry j corresponds to step
                # step_after - K + 1 + j.
                j = int(bad[0])
                step_no = step_after - len(vals) + 1 + j
                raise DivergenceError(
                    what, float(vals[j]), step=step_no
                )
        return new_state, metrics

    return guarded
