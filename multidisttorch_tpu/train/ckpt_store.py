"""Checkpoint format v2: a content-addressed chunk store + manifests.

The v1 checkpoint (``train/checkpoint.py``) rewrites the FULL model as
one msgpack blob per save and retains keep-last-K history as full
COPIES — at the scale the pjit/TPUv4 LM paper targets that gather+
rewrite is the dominant term in drain latency and restart tax. v2
splits the data plane from the metadata plane (docs/RESILIENCE.md
"Checkpoint format v2"):

- **Chunks**: every state leaf is serialized as raw bytes and split
  into fixed-size chunks, each landed in a content-addressed store
  under ``{ckpt_dir}/chunks/`` keyed by its sha256 — the
  ``DatasetStore`` landing discipline (tmp + fsync + rename, CRC32
  sidecar sealed BEFORE the payload rename, unique per-writer tmp
  names), so a torn write is an invisible ``.tmp`` and a rotted chunk
  is a CRC mismatch, never a garbled restore.
- **Manifest**: a small fsync'd JSON file at the checkpoint path
  itself (where v1 put the msgpack blob) listing each leaf's dtype/
  shape/chunk digests plus the caller's metadata and the state's
  ``NamedSharding`` layout. The v1 sidecar machinery (``path + .json``
  with ``_integrity`` over the manifest bytes, ``.v{step}`` retained
  versions, scan-back, the cross-host restore agreement) applies
  UNCHANGED — a v2 checkpoint is just a v1 checkpoint whose primary
  file happens to be tiny.
- **Incremental saves**: a chunk whose digest already exists in the
  store is referenced, not rewritten — optimizer-stable leaves and
  frozen params stop costing full-model bytes every cadence. The save
  stats record written-vs-reused bytes (the bench's delta ratio).
- **Refcounted GC**: ``refs.json`` counts how many manifest FILES
  reference each chunk; retention version copies increment, pruned
  versions decrement, zero unlinks. Every mutation is ordered so a
  crash can only LEAK a count (reconciled by the orphan sweep —
  ``tools/ckpt_gc.py``), never free a chunk a live manifest still
  references.

Crash model: chunks land before the manifest referencing them; refs
increment before the manifest replace and decrement after the old
manifest is gone. A kill at any instant leaves the previous manifest
fully restorable and at worst some unreferenced chunks/counts for the
sweep.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import zlib
from typing import Any, Iterable, Optional

import numpy as np

MANIFEST_FORMAT = "mdt-ckpt-v2"
CHUNKS_DIRNAME = "chunks"
REFS_NAME = "refs.json"
DEFAULT_CHUNK_BYTES = 1 << 20  # 1 MiB
_SNIFF_BYTES = 64


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (the rename itself). One copy
    for the whole checkpoint layer — ``train/checkpoint.py`` imports
    this and :func:`write_atomic` rather than carrying twins that
    could drift. Best-effort: some filesystems refuse O_RDONLY dir
    fsync."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_atomic(path: str, blob: bytes, *, fsync: bool = True) -> None:
    """Atomic (+ durable with ``fsync``) publish with a WRITER-UNIQUE
    tmp name: overlapped writers on one path (a drained victim's
    background persist vs its successor's save; two threads landing
    one chunk digest) must not interleave into a shared tmp that the
    rename then publishes torn."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path)


def chunk_dir_for(ckpt_path: str) -> str:
    """The chunk store serving a checkpoint path: ``chunks/`` next to
    the manifest, shared by every retained version (and, for pipelined
    trials, by every stage manifest in the trial dir)."""
    return os.path.join(os.path.dirname(ckpt_path) or ".", CHUNKS_DIRNAME)


def is_manifest_blob(blob: bytes) -> bool:
    """Sniff a checkpoint file: v2 manifests are JSON whose first key
    is the format marker; v1 blobs are msgpack (first byte is a map/
    bin marker, never ``{``)."""
    head = blob[:_SNIFF_BYTES]
    return head.lstrip()[:1] == b"{" and MANIFEST_FORMAT.encode() in head


def is_manifest_file(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            return is_manifest_blob(f.read(_SNIFF_BYTES * 2))
    except OSError:
        return False


class ChunkStore:
    """Content-addressed chunks under ``root`` with CRC32 sidecars and
    a refcount ledger.

    Concurrency model: every mutation of the {refcounts, chunk
    liveness} pair — incr/decr (including the zero-count unlinks),
    put's has-check + commit rename, and the sweep's whole
    mark/rebuild/unlink pass — runs under ONE exclusive ``refs.lock``
    ``flock`` (the ledger's locking discipline), so a GC running
    against a LIVE directory serializes against in-flight saves
    instead of clobbering a concurrent increment (which could drive a
    still-referenced chunk to zero — corruption, not a leak). The
    in-process ``threading.Lock`` additionally serializes threads of
    one process sharing a store instance; large payload writes happen
    OUTSIDE both locks (only the rename commit is held)."""

    def __init__(self, root: str, *, fsync: bool = True):
        self.root = root
        self.fsync = bool(fsync)
        self._lock = threading.Lock()

    def _locked(self):
        """Exclusive cross-process + in-process critical section over
        the refcount/liveness state."""
        import contextlib
        import fcntl

        @contextlib.contextmanager
        def cm():
            with self._lock:
                os.makedirs(self.root, exist_ok=True)
                fd = os.open(
                    os.path.join(self.root, "refs.lock"),
                    os.O_CREAT | os.O_RDWR,
                )
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                    yield
                finally:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_UN)
                    finally:
                        os.close(fd)

        return cm()

    # -- paths --------------------------------------------------------

    def chunk_path(self, digest: str) -> str:
        return os.path.join(self.root, digest[:2], digest + ".chunk")

    def crc_path(self, digest: str) -> str:
        return os.path.join(self.root, digest[:2], digest + ".crc")

    def refs_path(self) -> str:
        return os.path.join(self.root, REFS_NAME)

    # -- landing (the DatasetStore discipline) ------------------------

    def has(self, digest: str) -> bool:
        return os.path.exists(self.chunk_path(digest)) and os.path.exists(
            self.crc_path(digest)
        )

    def _write_atomic(self, path: str, blob: bytes) -> None:
        write_atomic(path, blob, fsync=self.fsync)

    def put(self, blob: bytes) -> tuple[str, int]:
        """Land one chunk; returns ``(digest, bytes_written)`` where
        written is 0 on a dedup hit (the incremental-save currency).
        The CRC sidecar is sealed BEFORE the payload rename — the
        commit point — so a crash can orphan a sidecar but never
        strand a CRC-less payload nothing would verify. The dedup
        has-check and the commit run under the store lock: a dedup hit
        must not race a concurrent decr/sweep unlinking that digest
        (the save's incr, also locked, follows before any manifest
        references it)."""
        digest = hashlib.sha256(blob).hexdigest()
        with self._locked():
            if self.has(digest):
                # Refresh the grace clock: this chunk may be a leaked
                # orphan (count 0) being re-referenced — a live GC
                # must see it young until the referencing manifest
                # lands, or the sweep unlinks it mid-save.
                try:
                    os.utime(self.chunk_path(digest))
                except OSError:
                    pass
                return digest, 0
            os.makedirs(
                os.path.dirname(self.chunk_path(digest)), exist_ok=True
            )
            self._write_atomic(
                self.crc_path(digest),
                f"{zlib.crc32(blob):08x} {len(blob)}\n".encode(),
            )
            self._write_atomic(self.chunk_path(digest), blob)
        return digest, len(blob)

    def verify(self, digest: str, nbytes: Optional[int] = None):
        """``(ok, reason)`` for one chunk: present, sidecar parses,
        size and CRC32 match (and the recorded size matches the
        manifest's expectation when given)."""
        cp, sp = self.chunk_path(digest), self.crc_path(digest)
        if not os.path.exists(cp):
            return False, f"chunk {digest[:12]} missing"
        try:
            with open(sp) as f:
                crc_hex, rec_n = f.read().split()
        except (OSError, ValueError) as e:
            return False, f"chunk {digest[:12]} sidecar unreadable: {e}"
        try:
            with open(cp, "rb") as f:
                blob = f.read()
        except OSError as e:
            return False, f"chunk {digest[:12]} unreadable: {e}"
        if len(blob) != int(rec_n) or (
            nbytes is not None and len(blob) != int(nbytes)
        ):
            return False, (
                f"chunk {digest[:12]} size mismatch ({len(blob)} vs "
                f"recorded {rec_n}) — torn write"
            )
        if zlib.crc32(blob) != int(crc_hex, 16):
            return False, f"chunk {digest[:12]} crc32 mismatch — corrupt"
        return True, "ok"

    def read(self, digest: str, *, verify: bool = True) -> bytes:
        with open(self.chunk_path(digest), "rb") as f:
            blob = f.read()
        if verify:
            try:
                with open(self.crc_path(digest)) as f:
                    crc_hex, rec_n = f.read().split()
            except (OSError, ValueError) as e:
                raise IOError(
                    f"chunk {digest[:12]} sidecar unreadable: {e}"
                ) from e
            if len(blob) != int(rec_n) or zlib.crc32(blob) != int(
                crc_hex, 16
            ):
                raise IOError(
                    f"chunk {digest[:12]} failed CRC verification"
                )
        return blob

    # -- refcounts ----------------------------------------------------

    def _load_refs(self) -> dict[str, int]:
        try:
            with open(self.refs_path()) as f:
                return {str(k): int(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            return {}

    def _store_refs(self, refs: dict[str, int]) -> None:
        os.makedirs(self.root, exist_ok=True)
        self._write_atomic(
            self.refs_path(),
            json.dumps({k: v for k, v in refs.items() if v > 0}).encode(),
        )

    def refcounts(self) -> dict[str, int]:
        with self._locked():
            return self._load_refs()

    def locked(self):
        """Public critical section for compound mutations: the save
        path holds this across {incr + manifest replace} so a
        concurrent sweep's refs rebuild can never land between the
        increment and the manifest becoming visible (the rebuild would
        drop the counts, and a LATER save's decr could then drive a
        still-referenced shared chunk to zero)."""
        return self._locked()

    def _incr_unlocked(self, digests: Iterable[str]) -> None:
        refs = self._load_refs()
        for d in set(digests):
            refs[d] = refs.get(d, 0) + 1
        self._store_refs(refs)

    def incr(self, digests: Iterable[str]) -> None:
        """Count one more manifest FILE referencing each digest (set
        semantics per manifest — callers pass the manifest's distinct
        digest set). Ordered BEFORE the manifest lands, so a crash
        leaks a count the sweep reconciles, never undercounts."""
        ds = set(digests)
        if not ds:
            return
        with self._locked():
            self._incr_unlocked(ds)

    def decr(self, digests: Iterable[str]) -> int:
        """Drop one manifest's references; unlink chunks whose count
        reaches zero. Returns bytes freed. Ordered AFTER the manifest
        file is gone — a crash in between leaks, never corrupts. The
        unlinks happen INSIDE the critical section: between a count
        hitting zero and the file vanishing, a concurrent put must not
        dedup-hit the doomed chunk."""
        ds = set(digests)
        if not ds:
            return 0
        freed = 0
        with self._locked():
            refs = self._load_refs()
            dead = []
            for d in ds:
                n = refs.get(d, 0) - 1
                if n > 0:
                    refs[d] = n
                else:
                    refs.pop(d, None)
                    dead.append(d)
            self._store_refs(refs)
            for d in dead:
                freed += self._unlink_chunk(d)
        return freed

    def _unlink_chunk(self, digest: str) -> int:
        freed = 0
        for p in (self.chunk_path(digest), self.crc_path(digest)):
            try:
                freed += os.path.getsize(p)
                os.remove(p)
            except OSError:
                pass
        return freed

    # -- enumeration / sweep ------------------------------------------

    def all_chunks(self) -> dict[str, float]:
        """``{digest: mtime}`` of every payload chunk on disk."""
        out: dict[str, float] = {}
        try:
            prefixes = os.listdir(self.root)
        except OSError:
            return out
        for pre in prefixes:
            d = os.path.join(self.root, pre)
            if not os.path.isdir(d):
                continue
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if not name.endswith(".chunk"):
                    continue
                try:
                    out[name[: -len(".chunk")]] = os.path.getmtime(
                        os.path.join(d, name)
                    )
                except OSError:
                    pass
        return out

    def sweep(
        self,
        live,
        *,
        grace_s: float = 0.0,
        now: Optional[float] = None,
    ) -> dict:
        """Mark-and-sweep reconciliation: rebuild ``refs.json`` from
        the LIVE manifest digest sets (leaked counts from crashed saves
        drop out) and unlink chunks no live manifest references, aged
        past ``grace_s`` (protects a save whose chunks landed but whose
        manifest hasn't — those are younger than any sane grace).

        ``live`` is a list of per-manifest digest sets, a single set,
        or a ZERO-ARG CALLABLE resolved INSIDE the critical section —
        the live-directory safety hinge: the manifest list must be
        read under the same lock that rebuilds the refs, or a save
        landing between the read and the rebuild loses its increments
        (and a later decr could unlink a chunk its new manifest still
        references — corruption, not a leak). ``sweep_ckpt_dir``
        always passes the callable form."""
        now = time.time() if now is None else now
        removed = 0
        freed = 0
        kept_young = 0
        with self._locked():
            if callable(live):
                live = live()
            on_disk = self.all_chunks()
            refs = self._load_refs()
            live_counts: dict[str, int] = {}
            for dset in live if isinstance(live, list) else [live]:
                for d in set(dset):
                    live_counts[d] = live_counts.get(d, 0) + 1
            leaked_refs = {
                d: n
                for d, n in refs.items()
                if live_counts.get(d, 0) != n
            }
            self._store_refs(live_counts)
            for digest, mtime in on_disk.items():
                if digest in live_counts:
                    continue
                if now - mtime < grace_s:
                    kept_young += 1
                    continue
                freed += self._unlink_chunk(digest)
                removed += 1
        return {
            "chunks_on_disk": len(on_disk),
            "live_chunks": len(live_counts),
            "orphans_removed": removed,
            "orphan_bytes_freed": freed,
            "kept_in_grace": kept_young,
            "leaked_refs_reconciled": len(leaked_refs),
        }


# --------------------------------------------------------------------
# pytree <-> flat leaves
# --------------------------------------------------------------------


_EMPTY = object()  # marker leaf for empty dicts (optax EmptyState)


def _flatten_state_dict(sd: Any, prefix: str = "") -> list[tuple[str, Any]]:
    if isinstance(sd, dict):
        if not sd:
            # Structure-preserving: optax's EmptyState serializes to
            # {}; dropping it would desync flax's list restoration.
            return [(prefix[:-1] if prefix else "", _EMPTY)]
        out: list[tuple[str, Any]] = []
        for k in sorted(sd, key=str):
            out.extend(
                _flatten_state_dict(sd[k], f"{prefix}{k}/")
            )
        return out
    return [(prefix[:-1] if prefix else "", sd)]


def _unflatten_state_dict(flat: dict[str, Any]) -> Any:
    root: dict = {}
    for key, val in flat.items():
        parts = key.split("/") if key else [""]
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = val
    return root


# --------------------------------------------------------------------
# manifests
# --------------------------------------------------------------------


def build_manifest(
    host_state: Any,
    store: ChunkStore,
    *,
    metadata: Optional[dict] = None,
    layouts: Any = None,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> tuple[dict, dict]:
    """Chunk every leaf of ``host_state`` into ``store`` and return
    ``(manifest, stats)``. Chunks already present (bit-identical to a
    previous save's) are referenced, not rewritten — the incremental-
    save mechanism; ``stats`` records the written/reused split.

    ``layouts`` optionally carries the live state's shardings pytree
    (same structure as the state); each leaf's ``NamedSharding`` is
    recorded as a spec string in the manifest, so the on-disk format
    names the layout the runtime trained under (restore placement
    itself stays caller-driven — the live target's shardings win).
    """
    from flax import serialization

    chunk_bytes = max(1, int(chunk_bytes))
    flat = _flatten_state_dict(serialization.to_state_dict(host_state))
    layout_by_key: dict[str, str] = {}
    if layouts is not None:
        try:
            for key, sh in _flatten_state_dict(
                serialization.to_state_dict(layouts)
            ):
                if sh is not None and sh is not _EMPTY:
                    layout_by_key[key] = str(
                        getattr(sh, "spec", sh)
                    )
        except Exception:  # noqa: BLE001 — layout record is advisory
            layout_by_key = {}
    leaves = []
    new_bytes = 0
    reused_bytes = 0
    chunks_written = 0
    chunks_total = 0
    for key, val in flat:
        if val is _EMPTY:
            leaves.append({"key": key, "kind": "empty"})
            continue
        arr = np.asarray(val)
        blob = np.ascontiguousarray(arr).tobytes()
        entry: dict[str, Any] = {
            "key": key,
            "dtype": str(arr.dtype),
            "shape": [int(s) for s in arr.shape],
            "nbytes": len(blob),
            "chunks": [],
        }
        if key in layout_by_key:
            entry["sharding"] = layout_by_key[key]
        for off in range(0, len(blob), chunk_bytes) or [0]:
            piece = blob[off : off + chunk_bytes]
            if not piece and len(blob) > 0:
                continue
            digest, written = store.put(piece)
            chunks_total += 1
            if written:
                chunks_written += 1
                new_bytes += written
            else:
                reused_bytes += len(piece)
            entry["chunks"].append({"d": digest, "n": len(piece)})
        leaves.append(entry)
    manifest = {
        "format": MANIFEST_FORMAT,
        "chunk_bytes": chunk_bytes,
        "meta": dict(metadata) if metadata is not None else {},
        "leaves": leaves,
    }
    total = new_bytes + reused_bytes
    stats = {
        "format": "v2",
        "total_bytes": total,
        "new_bytes": new_bytes,
        "reused_bytes": reused_bytes,
        "chunks": chunks_total,
        "chunks_written": chunks_written,
        "delta_ratio": round(new_bytes / total, 6) if total else 0.0,
    }
    return manifest, stats


def manifest_bytes(manifest: dict) -> bytes:
    # The format marker is the FIRST key (insertion order) — the sniff
    # contract of is_manifest_blob.
    return json.dumps(manifest).encode()


def load_manifest(blob: bytes) -> dict:
    m = json.loads(blob.decode())
    if m.get("format") != MANIFEST_FORMAT:
        raise ValueError(
            f"not a {MANIFEST_FORMAT} manifest (format="
            f"{m.get('format')!r})"
        )
    return m


def manifest_digests(manifest: dict) -> set:
    return {
        c["d"]
        for leaf in manifest.get("leaves", [])
        for c in leaf.get("chunks", [])
    }


def read_manifest_file(path: str) -> Optional[dict]:
    """Parse ``path`` as a manifest, or None (absent / not v2 /
    undecodable)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if not is_manifest_blob(blob):
        return None
    try:
        return load_manifest(blob)
    except (ValueError, UnicodeDecodeError):
        return None


def verify_manifest_chunks(manifest: dict, store: ChunkStore):
    """Chunk-complete verification: every referenced chunk present,
    sized, and CRC-clean — the v2 extension of the sidecar CRC gate, so
    a missing or rotted chunk disqualifies the candidate exactly like a
    torn v1 state file (scan-back degrades to the previous step)."""
    for leaf in manifest.get("leaves", []):
        for c in leaf.get("chunks", []):
            ok, reason = store.verify(c["d"], nbytes=c["n"])
            if not ok:
                return False, f"leaf {leaf['key']}: {reason}"
    return True, "ok"


def restore_arrays(
    manifest: dict,
    store: ChunkStore,
    *,
    read_threads: Optional[int] = None,
    verify: bool = True,
) -> Any:
    """Reassemble the manifest's state_dict with a parallel per-chunk
    read pool (``MDT_CKPT_READ_THREADS``, default up to 8) — restore
    bandwidth scales with the store's chunk fan-out instead of one
    sequential blob read."""
    from concurrent.futures import ThreadPoolExecutor

    jobs: list[tuple[str, dict]] = []
    for leaf in manifest.get("leaves", []):
        for c in leaf.get("chunks", []):
            jobs.append((c["d"], c))
    if read_threads is None:
        read_threads = int(os.environ.get("MDT_CKPT_READ_THREADS", "8"))
    n_workers = max(1, min(int(read_threads), len(jobs) or 1))
    blobs: dict[int, bytes] = {}
    if n_workers > 1 and len(jobs) > 1:
        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            for i, blob in enumerate(
                pool.map(
                    lambda j: store.read(j[0], verify=verify), jobs
                )
            ):
                blobs[i] = blob
    else:
        for i, (digest, _) in enumerate(jobs):
            blobs[i] = store.read(digest, verify=verify)
    flat: dict[str, Any] = {}
    cursor = 0
    for leaf in manifest.get("leaves", []):
        if leaf.get("kind") == "empty":
            flat[leaf["key"]] = {}
            continue
        parts = []
        for c in leaf["chunks"]:
            parts.append(blobs[cursor])
            cursor += 1
        blob = b"".join(parts)
        arr = np.frombuffer(blob, dtype=np.dtype(leaf["dtype"]))
        flat[leaf["key"]] = arr.reshape(leaf["shape"]).copy()
    return _unflatten_state_dict(flat)


# --------------------------------------------------------------------
# GC over a checkpoint directory
# --------------------------------------------------------------------


def live_manifest_files(ckpt_dir: str) -> list[str]:
    """Every file in ``ckpt_dir`` that sniffs as a v2 manifest — the
    primary checkpoint(s), retained ``.v{step}`` versions, and (for
    pipelined trials) every stage's family share one chunk store."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return out
    for name in names:
        if name == CHUNKS_DIRNAME or name.endswith((".json", ".tmp")):
            continue
        p = os.path.join(ckpt_dir, name)
        if os.path.isfile(p) and is_manifest_file(p):
            out.append(p)
    return sorted(out)


def sweep_ckpt_dir(
    ckpt_dir: str, *, grace_s: float = 300.0, now: Optional[float] = None
) -> Optional[dict]:
    """Reconcile + orphan-sweep one checkpoint directory's chunk store
    against its live manifests. Returns the sweep report, or None when
    the directory has no chunk store. Safe on a LIVE directory: chunks
    younger than ``grace_s`` are kept (an in-flight save's chunks land
    before its manifest), and refs are rebuilt from the manifests that
    exist — a crashed save's leaked counts drop out."""
    store_dir = os.path.join(ckpt_dir, CHUNKS_DIRNAME)
    if not os.path.isdir(store_dir):
        return None
    store = ChunkStore(store_dir)
    counts = {"manifests": 0, "unreadable": 0}

    def live_under_lock() -> list:
        # Resolved inside the store's critical section (see
        # ChunkStore.sweep): a save racing this GC either fully lands
        # before the manifest read — and is marked live — or fully
        # after the rebuild, when its (locked) increments apply to the
        # reconciled refs.
        live_sets = []
        manifests = live_manifest_files(ckpt_dir)
        counts["manifests"] = len(manifests)
        for p in manifests:
            m = read_manifest_file(p)
            if m is None:
                counts["unreadable"] += 1
                continue
            live_sets.append(manifest_digests(m))
        return live_sets

    report = store.sweep(live_under_lock, grace_s=grace_s, now=now)
    report["dir"] = ckpt_dir
    report["manifests"] = counts["manifests"]
    report["manifests_unreadable"] = counts["unreadable"]
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    if bus is not None:
        bus.emit(
            "ckpt_gc",
            dir=ckpt_dir,
            orphans_removed=report["orphans_removed"],
            bytes_freed=report["orphan_bytes_freed"],
            leaked_refs_reconciled=report["leaked_refs_reconciled"],
        )
    return report
