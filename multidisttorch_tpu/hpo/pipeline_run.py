"""The pipelined trial runner: one MPMD trial across S stage submeshes.

The cross-submesh sibling of ``hpo/driver.py``'s ``_TrialRun``: the same
cooperative-generator contract (each ``next()`` dispatches one
optimizer step's GPipe schedule async and returns; host syncs only at
epoch boundaries), the same supervision surface the sweep service
drives (``.run()`` / ``.result`` / ``._join_ckpt()`` / ``._step_no``),
but the trial's devices are a *vector* of submeshes — one per pipeline
stage — and the compiled work is the per-stage program set of
``parallel.pipeline.MpmdPipeline`` (docs/PARALLEL.md).

Checkpoint/restore composes per stage: each stage's TrainState lands in
its own ``stage{c}.msgpack`` under the trial dir (one background writer
thread for all stages, the driver's atomic+CRC machinery per file), and
a supervised retry restores all stages at the NEWEST optimizer step
every stage can locally verify — one stage's torn checkpoint pulls the
whole pipeline back to the last step everyone holds, the per-stage
analog of the elastic restore agreement.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict
from typing import Iterator, Optional

import jax
import optax

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import (
    EvalDataIterator,
    TrialDataIterator,
)
from multidisttorch_tpu.hpo.driver import (
    TrialConfig,
    TrialResult,
    stack_bucket_key,
)
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.pipeline import (
    MpmdPipeline,
    analytic_bubble_fraction,
    make_vae_stage_eval_fns,
    make_vae_stage_fns,
    split_stage_params,
)
from multidisttorch_tpu.telemetry import device as tele_device
from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.telemetry.metrics import get_registry
from multidisttorch_tpu.train.checkpoint import (
    default_format,
    restore_state,
    save_state,
    snapshot_cache,
    valid_candidates_by_step,
)
from multidisttorch_tpu.train.guards import check_finite
from multidisttorch_tpu.train.steps import build_train_state
from multidisttorch_tpu.utils.logging import log0

PIPELINE_BOOKS_NAME = "pipeline_books.json"


def _emit(kind: str, **kw) -> None:
    bus = get_bus()
    if bus is not None:
        bus.emit(kind, **kw)


class _PipelineTrialRun:
    """One MPMD pipelined trial's lifecycle as a cooperative generator.

    ``stage_meshes`` is the placement's submesh vector (stage s trains
    on ``stage_meshes[s]``); ``cfg.pipeline_stages`` must match its
    length and ``cfg.grad_accum`` is the microbatch count M (the GPipe
    schedule IS gradient accumulation across stages — the single-mesh
    ``grad_accum=M`` step is the parity reference). Default VAE family
    only (2 stages: encoder+reparam | decoder+loss), single controller.
    """

    def __init__(
        self,
        stage_meshes,
        cfg: TrialConfig,
        train_data: Dataset,
        test_data: Optional[Dataset],
        out_dir: str,
        *,
        save_checkpoint: bool = True,
        verbose: bool = False,
        resume=False,  # False | "scan"
        ckpt_keep_last: int = 1,
        ckpt_format: Optional[str] = None,
        ram_restore: bool = False,
        attempt: int = 1,
    ):
        S = len(stage_meshes)
        if cfg.pipeline_stages != S:
            raise ValueError(
                f"cfg.pipeline_stages={cfg.pipeline_stages} but "
                f"{S} stage submeshes were placed"
            )
        if S != 2:
            raise ValueError(
                f"the VAE family splits into 2 MPMD stages; got {S} "
                "(deeper chains need a deeper model — see docs/PARALLEL.md)"
            )
        # Knobs the pipelined runner does not carry: reject loudly
        # rather than silently train/evaluate something else (the
        # service mirrors this at admission — rejected_invalid).
        if cfg.eval_sampled:
            raise ValueError(
                f"trial {cfg.trial_id}: eval_sampled is not supported "
                "on the pipelined path (stage eval is posterior-mean "
                "only) — run this config unpipelined"
            )
        if cfg.fused_steps != 1 or cfg.remat:
            raise ValueError(
                f"trial {cfg.trial_id}: fused_steps/remat are not "
                "wired through the MPMD stage programs — run this "
                "config unpipelined"
            )
        M = max(1, int(cfg.grad_accum))
        if cfg.batch_size % M:
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by "
                f"grad_accum={M} microbatches"
            )
        mb = cfg.batch_size // M
        for sm in stage_meshes:
            if mb % sm.data_size:
                raise ValueError(
                    f"microbatch of {mb} rows does not shard over stage "
                    f"submesh of {sm.data_size} devices"
                )
        self.stage_meshes = list(stage_meshes)
        # The service's single-run bookkeeping reads `.trial` for
        # group identity: stage 0's submesh anchors the trial.
        self.trial = stage_meshes[0]
        self.cfg = cfg
        self.M = M
        self.out_dir = os.path.join(out_dir, f"trial-{cfg.trial_id}")
        self._save_checkpoint = save_checkpoint
        self._verbose = verbose
        self._ckpt_keep_last = ckpt_keep_last
        self._ckpt_format = (
            ckpt_format if ckpt_format is not None else default_format()
        )
        # Same-process warm re-place only (the classic driver's rule):
        # disk drills must observe disk.
        self._ram_restore = bool(ram_restore)
        self._attempt = attempt
        self._host_syncs = 0
        self._step_no = 0
        self._mreg = get_registry()
        self._mkey = f"pipe-t{cfg.trial_id}"
        self._cost_done = False

        self.result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=self.trial.group_id,
            config=cfg,
            out_dir=self.out_dir,
            dataset=train_data.name,
            dataset_synthetic=train_data.synthetic,
        )

        model = VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
        self.model = model
        stage_fns, last_fn, stage_keys = make_vae_stage_fns(
            model, beta=cfg.beta
        )
        full = build_train_state(
            model, optax.adam(cfg.lr), jax.random.key(cfg.seed)
        )
        stage_params = split_stage_params(full.params, stage_keys)

        from multidisttorch_tpu.compile.programs import pipeline_stage_keys

        self.pipe = MpmdPipeline(
            self.stage_meshes,
            stage_fns,
            last_fn,
            stage_params,
            lr=cfg.lr,
            microbatches=M,
            zero_update=cfg.zero_update,
            registry_keys=pipeline_stage_keys(
                self.stage_meshes,
                cfg,
                stack_bucket_key(cfg),
                microbatches=M,
            ),
            eval_fns=make_vae_stage_eval_fns(model, cfg.beta),
        )
        self.result.optimizer_state_bytes = self.pipe.optimizer_state_bytes()[
            "per_device_bytes"
        ]

        self.train_iter = TrialDataIterator(
            train_data, self.trial, cfg.batch_size, seed=cfg.seed
        )
        self.test_iter = (
            EvalDataIterator(test_data, self.trial, cfg.batch_size)
            if test_data is not None and len(test_data) > 0
            else None
        )
        self._key = jax.random.key(cfg.seed + 1)

        # Per-stage checkpoint paths + one background writer thread.
        self._ckpt_paths = [
            os.path.join(self.out_dir, f"stage{s}.msgpack")
            for s in range(S)
        ]
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_error: Optional[BaseException] = None
        self._start_epoch = 1
        if resume == "scan":
            got = self._restore_scan()
            if got is not None:
                done = got
                self._start_epoch = done + 1
                log0(
                    f"Pipelined trial {cfg.trial_id} retry resumes from "
                    f"epoch {done} (all {S} stages verified)",
                    trial=self.trial,
                )
        self.result.resumed_from_step = (
            (self._start_epoch - 1) * self.train_iter.num_batches
        )

    # -- checkpoint/restore -------------------------------------------

    def _accept_meta(self, meta: dict) -> bool:
        """Config-match gate per candidate (epochs may extend): the
        driver's ONE resume rule — fields absent from an older
        sidecar compare against their TrialConfig defaults, so a
        checkpoint trained before a field existed can never silently
        resume under a non-default value of it."""
        from multidisttorch_tpu.hpo.driver import config_mismatch_vs_meta

        return not config_mismatch_vs_meta(self.cfg, meta)

    def _restore_scan(self) -> Optional[int]:
        """Per-stage agreed restore: the newest optimizer step EVERY
        stage can locally verify (CRC + config match); one stage's torn
        file pulls the whole pipeline back together. Returns completed
        epochs, or None for scratch."""
        # Warm re-place: every stage's RAM snapshot present at one
        # agreed step (they are written together) restores without
        # touching disk — the pipelined analog of the classic driver's
        # snapshot-cache fast path.
        snaps = (
            [snapshot_cache().get(p) for p in self._ckpt_paths]
            if self._ram_restore
            else [None]
        )
        if all(s is not None for s in snaps):
            metas = [m for _, m in snaps]
            steps = {int(m.get("step", -1)) for m in metas}
            usable = (
                len(steps) == 1
                and self._accept_meta(metas[0])
                and int(metas[0].get("completed_epochs", 0)) >= 1
            )
            if usable:
                try:
                    states = [
                        self.stage_meshes[s].device_put(
                            host, self.pipe.state_shardings[s]
                        )
                        for s, (host, _) in enumerate(snaps)
                    ]
                except Exception:  # noqa: BLE001 — fall back to disk
                    states = None
                if states is not None:
                    from multidisttorch_tpu.train.checkpoint import _count

                    self.pipe.states = states
                    self.result.checkpoint = self._ckpt_paths[0]
                    self._adopt_history(metas[0])
                    _count(restores=1, restores_ram=1)
                    _emit(
                        "ckpt_restore",
                        trial_id=self.cfg.trial_id,
                        group_id=self.trial.group_id,
                        path="<ram-snapshot>",
                        format="ram",
                        step=metas[0].get("step"),
                    )
                    return int(metas[0].get("completed_epochs", 0))
            else:
                # Stale/rejected snapshots squat in the bounded LRU and
                # re-reject on every retry — drop them (the classic
                # driver's rule).
                for p in self._ckpt_paths:
                    snapshot_cache().drop(p)
        common: Optional[set] = None
        cands = []
        for path in self._ckpt_paths:
            by_step = valid_candidates_by_step(
                path, accept_meta=self._accept_meta
            )
            cands.append(by_step)
            steps = set(by_step)
            common = steps if common is None else (common & steps)
        if not common:
            return None
        step = max(common)
        states = []
        try:
            for s, by_step in enumerate(cands):
                path, meta = by_step[step]
                states.append(
                    restore_state(
                        self.pipe.states[s],
                        path,
                        self.stage_meshes[s],
                        shardings=self.pipe.state_shardings[s],
                    )
                )
        except Exception:  # noqa: BLE001 — degrade to scratch, never wedge
            return None
        meta = cands[0][step][1]
        done = int(meta.get("completed_epochs", 0))
        if done < 1:
            return None
        self.pipe.states = states
        self.result.checkpoint = self._ckpt_paths[0]
        self._adopt_history(meta)
        return done

    def _adopt_history(self, meta: dict) -> None:
        """Carry the restored checkpoint's per-epoch history into the
        result (the classic driver's `_adopt_history` contract): a
        resumed trial's settled summary must cover its WHOLE training,
        and a resumed_complete trial must still report its losses."""
        hist = list(meta.get("history", []))
        if not hist:
            return
        self.result.history = hist
        last = hist[-1]
        if last.get("avg_train_loss") is not None:
            self.result.final_train_loss = float(last["avg_train_loss"])
        if last.get("test_loss") is not None:
            self.result.final_test_loss = float(last["test_loss"])

    def _write_ckpt(self, host_states, meta: dict) -> None:
        try:
            for s, (path, host_state) in enumerate(
                zip(self._ckpt_paths, host_states)
            ):
                save_state(
                    host_state,
                    path,
                    metadata=meta,
                    keep_last=self._ckpt_keep_last,
                    # Per-stage manifests: every stage's family shares
                    # the trial dir's ONE chunk store, and each records
                    # its stage's NamedSharding layout (a zero_update
                    # stage's sharded moments stay sharded on disk).
                    format=self._ckpt_format,
                    layouts=self.pipe.state_shardings[s],
                )
            self.result.checkpoint = self._ckpt_paths[0]
        except BaseException as e:  # re-raised at the next join
            self._ckpt_error = e

    def _join_ckpt(self) -> None:
        if self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._ckpt_thread = None
        if self._ckpt_error is not None:
            e, self._ckpt_error = self._ckpt_error, None
            raise RuntimeError(
                f"pipelined trial {self.cfg.trial_id}: stage checkpoint "
                "write failed"
            ) from e

    def _ckpt_idle(self) -> bool:
        """No stage persist in flight (the snapshot-fast drain's
        non-blocking poll)."""
        t = self._ckpt_thread
        return t is None or not t.is_alive()

    # -- books --------------------------------------------------------

    def _record_cost(self) -> None:
        """One-shot device cost books over every stage program (MFU on
        backends with a peak table; null-with-reason on CPU)."""
        if self._cost_done or self._mreg is None:
            return
        self._cost_done = True
        parts = self.pipe.cost_parts()
        if not parts:
            return
        devices = [
            d for sm in self.stage_meshes for d in sm.devices
        ]
        tele_device.record_pipeline_cost(
            self._mkey,
            parts,
            devices=devices,
            trial_id=self.cfg.trial_id,
            group_id=self.trial.group_id,
        )

    def write_books(self) -> Optional[str]:
        """Land the trial's pipeline books (schedule measurement,
        optimizer memory, placement vector) as JSON in the trial dir —
        the ``bench.py --pipeline`` artifact's source."""
        books = {
            "trial_id": self.cfg.trial_id,
            "schedule": self.pipe.schedule_books(),
            "optimizer_state": self.pipe.optimizer_state_bytes(),
            "stage_groups": [
                {
                    "group_id": sm.group_id,
                    "devices": [d.id for d in sm.devices],
                }
                for sm in self.stage_meshes
            ],
        }
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, PIPELINE_BOOKS_NAME)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(books, f, indent=2)
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    def _log(self, *args, level: int = logging.INFO):
        if self._verbose:
            log0(*args, trial=self.trial, level=level)

    # -- the lifecycle ------------------------------------------------

    def run(self) -> Iterator[None]:
        cfg = self.cfg
        t0 = time.time()
        if self._start_epoch > cfg.epochs:
            self.result.status = "resumed_complete"
            self.result.steps = int(
                jax.device_get(self.pipe.states[0].step)
            )
            self._log(
                f"Pipelined trial {cfg.trial_id} already complete; resumed."
            )
            return
        n_per_epoch = self.train_iter.samples_per_epoch
        self._step_no = int(jax.device_get(self.pipe.states[0].step))
        _emit(
            "pipeline_start",
            trial_id=cfg.trial_id,
            group_id=self.trial.group_id,
            stages=self.pipe.S,
            microbatches=self.M,
            stage_groups=[sm.group_id for sm in self.stage_meshes],
            analytic_bubble=analytic_bubble_fraction(self.pipe.S, self.M),
            zero_update=cfg.zero_update,
        )
        ob = self.pipe.optimizer_state_bytes()
        _emit(
            "optimizer_state",
            trial_id=cfg.trial_id,
            group_id=self.trial.group_id,
            per_device_bytes=ob["per_device_bytes"],
            total_bytes=ob["total_bytes"],
            zero_update=cfg.zero_update,
            pipelined=True,
        )
        for epoch in range(self._start_epoch, cfg.epochs + 1):
            if self._mreg is not None:
                self._mreg.step_series(self._mkey).open_interval()
            epoch_sum_dev = None
            books0 = dict(self.pipe.books)
            for batch in self.train_iter.epoch(epoch):
                rng = jax.random.fold_in(self._key, self._step_no)
                metrics = self.pipe.step(batch, rng)
                self._step_no += 1
                s = metrics["loss_sum"]
                epoch_sum_dev = (
                    s if epoch_sum_dev is None else epoch_sum_dev + s
                )
                if self._mreg is not None:
                    self._mreg.step_mark(self._mkey, s)
                yield

            # One fetch per epoch (the O(1)-syncs discipline).
            self._host_syncs += 1
            avg = float(epoch_sum_dev) / n_per_epoch
            if self._mreg is not None:
                self._record_cost()
                devices = [
                    d for sm in self.stage_meshes for d in sm.devices
                ]
                tele_device.sample_memory(
                    self._mkey, devices, where="epoch",
                    trial_id=cfg.trial_id, group_id=self.trial.group_id,
                )
            check_finite(
                avg,
                "epoch average train loss",
                step=self._step_no,
                trial_id=cfg.trial_id,
            )
            self._log(
                "====> [pipeline] Epoch: {} Average loss: {:.4f}".format(
                    epoch, avg
                )
            )
            epoch_record = {"epoch": epoch, "avg_train_loss": avg}

            if self.test_iter is not None:
                test_sum_dev = None
                for tbatch, tweights in self.test_iter.batches():
                    out = self.pipe.eval_batch(tbatch, tweights)
                    test_sum_dev = (
                        out if test_sum_dev is None else test_sum_dev + out
                    )
                    yield
                self._host_syncs += 1
                test_avg = float(test_sum_dev) / self.test_iter.num_rows
                self._log(
                    "====> [pipeline] Test set loss: {:.4f}".format(test_avg)
                )
                epoch_record["test_loss"] = test_avg
                self.result.final_test_loss = test_avg

            self.result.history.append(epoch_record)
            self.result.final_train_loss = avg
            _emit(
                "epoch",
                trial_id=cfg.trial_id,
                group_id=self.trial.group_id,
                step=self._step_no,
                **epoch_record,
            )
            d = dict(self.pipe.books)
            _emit(
                "pipeline_epoch",
                trial_id=cfg.trial_id,
                group_id=self.trial.group_id,
                step=self._step_no,
                epoch=epoch,
                ticks=d["ticks"] - books0["ticks"],
                busy=d["busy"] - books0["busy"],
                transfers=d["transfers"] - books0["transfers"],
                transfer_bytes=(
                    d["transfer_bytes"] - books0["transfer_bytes"]
                ),
                measured_bubble=self.pipe.measured_bubble(),
                analytic_bubble=analytic_bubble_fraction(
                    self.pipe.S, self.M
                ),
            )

            if self._save_checkpoint:
                # Snapshot every stage (replicated leaves or gathered
                # shards are all addressable single-controller), start
                # the device→host copies async, then hand the
                # serialize+write to the background thread.
                _snap_t0 = time.perf_counter()
                snaps = [
                    jax.device_get(st) for st in self.pipe.states
                ]
                meta = {
                    **asdict(cfg),
                    "completed_epochs": epoch,
                    "step": int(snaps[0].step),
                    "history": list(self.result.history),
                    "pipeline_stage": True,
                }
                # Snapshot boundary per stage (the drain contract): a
                # same-process re-place restores every stage from RAM.
                # Same opt-in gate as the read side — no host-copy
                # retention outside the service path.
                if self._ram_restore:
                    for path, host_state in zip(self._ckpt_paths, snaps):
                        snapshot_cache().put(path, host_state, meta)
                _emit(
                    "ckpt_snapshot",
                    trial_id=cfg.trial_id,
                    group_id=self.trial.group_id,
                    step=int(snaps[0].step),
                    epoch=epoch,
                    stages=len(snaps),
                    wall_s=round(time.perf_counter() - _snap_t0, 6),
                )
                self._join_ckpt()
                self._ckpt_thread = threading.Thread(
                    target=self._write_ckpt,
                    args=(snaps, meta),
                    daemon=False,
                )
                self._ckpt_thread.start()
                yield

        for st in self.pipe.states:
            jax.block_until_ready(st.params)
        self._join_ckpt()
        self.result.wall_s = time.time() - t0
        self.result.steps = self._step_no
        self.result.host_syncs = self._host_syncs
        self.write_books()
        self._log(f"Pipelined trial done. time: {self.result.wall_s:f}")


def run_pipeline_trial(
    cfg: TrialConfig,
    train_data: Dataset,
    test_data: Optional[Dataset] = None,
    *,
    stage_meshes,
    out_dir: str = "results",
    save_checkpoint: bool = True,
    verbose: bool = False,
    resume=False,
) -> TrialResult:
    """Run one MPMD pipelined trial to completion (tests, benches, and
    one-off driving outside the service loop)."""
    run = _PipelineTrialRun(
        stage_meshes,
        cfg,
        train_data,
        test_data,
        out_dir,
        save_checkpoint=save_checkpoint,
        verbose=verbose,
        resume=resume,
    )
    for _ in run.run():
        pass
    return run.result
