from multidisttorch_tpu.hpo.driver import TrialConfig, TrialResult, run_hpo
from multidisttorch_tpu.hpo.ledger import SweepLedger, config_hash
from multidisttorch_tpu.hpo.pbt import PBTConfig, PBTResult, run_pbt
from multidisttorch_tpu.hpo.supervision import RetryPolicy, classify_failure
