from multidisttorch_tpu.hpo.driver import TrialConfig, TrialResult, run_hpo
from multidisttorch_tpu.hpo.pbt import PBTConfig, PBTResult, run_pbt
