from multidisttorch_tpu.hpo.driver import TrialConfig, TrialResult, run_hpo
