"""Trial supervision policy: failure classification and retry budgets.

The sweep's unit of failure is ONE trial attempt. What happens next is
a pure function of the failure's *class*, not its text:

- **divergence** (:class:`~multidisttorch_tpu.train.guards.
  DivergenceError`): the configuration itself produced a non-finite
  loss. Deterministic training replays the same NaN on every retry, so
  this is a terminal trial *result* (``status="diverged"``) — the sweep
  records it and moves on.
- **preemption / lost peer** (:class:`~multidisttorch_tpu.faults.
  inject.HostPreemption`, or a ``TimeoutError`` from a deadline-bounded
  cross-process agreement): the host is going away, or a peer already
  did. Per-trial retry is meaningless — and for an expired agreement
  actively harmful: the abandoned collective leaves this process's
  distributed state unusable (``cluster.call_with_timeout``'s
  contract), so retrying on the same submesh would hang again and can
  desync later collectives. The driver re-raises so the process can
  die; the sweep ledger makes the restarted driver resume where it
  stopped.
- **infra** (everything else): the environment failed around a healthy
  trial — worker exception, data-loader fault, checkpoint I/O. Retry
  with capped exponential backoff, resuming from the trial's last
  *valid* checkpoint (``train.checkpoint.restore_latest_valid``), until
  the :class:`RetryPolicy` budget is spent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from multidisttorch_tpu.train.guards import DivergenceError

INFRA = "infra"
DIVERGENCE = "divergence"
PREEMPTION = "preemption"
FATAL = "fatal"

# Attempt-end statuses that SETTLE a trial: its executed steps become
# USEFUL work in the goodput accounting, and a restarted sweep must not
# re-run it (hpo/ledger.py's skip contract). The single definition the
# ledger, the telemetry fold (telemetry/export.py), and the chaos
# harness all share — supervision owns the status taxonomy.
SETTLED_STATUSES = ("completed", "diverged")


class UnretryableError(ValueError):
    """A deliberate hard stop that retrying would only paper over.

    The strict-resume integrity guards raise this (as a ValueError
    subclass, preserving their long-standing catchable type): a
    config-mismatched or state/sidecar-skewed checkpoint needs a HUMAN
    decision — a supervised retry would scan-resume past the rejected
    checkpoint, retrain from scratch, and os.replace() the very weights
    the guard refused to clobber. Classified FATAL: never retried,
    never consumes budget; surfaces through the normal failure path
    (raise, or ``status="failed"`` under ``resilient=True``).
    """


def classify_failure(
    exc: BaseException, *, trial_id: Optional[int] = None
) -> str:
    """Map an attempt's exception to its supervision class.
    ``trial_id``, when the caller knows it, rides on the emitted event
    so downstream consumers (the incident plane's divergence-storm
    counter — telemetry/incident.py) can attribute classifications to
    distinct trials."""
    cls = _classify(exc)
    # Telemetry seam: every classification decision is an event, so a
    # chaos trace shows not just that a fault fired but what the
    # supervisor decided to DO about it (docs/OBSERVABILITY.md).
    from multidisttorch_tpu.telemetry.events import get_bus

    bus = get_bus()
    if bus is not None:
        bus.emit(
            "failure_classified",
            trial_id=trial_id,
            failure_class=cls,
            exc_type=type(exc).__name__,
            error=str(exc)[:300],
        )
    return cls


def _classify(exc: BaseException) -> str:
    from multidisttorch_tpu.faults.inject import HostPreemption

    if isinstance(exc, DivergenceError):
        return DIVERGENCE
    if isinstance(exc, UnretryableError):
        return FATAL
    # AgreementTimeout (and ONLY that TimeoutError subtype — on 3.10+
    # socket.timeout IS TimeoutError, and a transient I/O timeout in a
    # trial must stay retryable) is a lost peer: the expired deadline
    # abandoned a blocked collective on a watchdog thread, so this
    # process's distributed state can no longer be trusted — same
    # response as preemption (die, restart against the ledger), NOT an
    # infra retry on the same wounded submesh.
    from multidisttorch_tpu.parallel.cluster import AgreementTimeout

    if isinstance(exc, (HostPreemption, AgreementTimeout)):
        return PREEMPTION
    return INFRA


def exit_code_for(exc: BaseException) -> int:
    """The exit-code contract for a supervised worker process dying on
    ``exc`` (docs/RESILIENCE.md "Elastic multi-host"): preemption-class
    failures — host preemption, a wedged collective, a graceful drain —
    exit with ``cluster.PREEMPTION_EXIT_CODE`` so the elastic
    supervisor re-admits the host into the next world; anything else
    exits 1 (the host itself is suspect)."""
    from multidisttorch_tpu.parallel.cluster import PREEMPTION_EXIT_CODE

    return PREEMPTION_EXIT_CODE if _classify(exc) == PREEMPTION else 1


@dataclass(frozen=True)
class RetryPolicy:
    """Retry budget for infra-class failures.

    ``max_retries`` is the number of *re*-attempts (0 disables retry;
    a trial runs at most ``max_retries + 1`` times). Backoff before
    retry k (1-based) is ``min(backoff_base_s * backoff_factor**(k-1),
    backoff_max_s)`` — capped exponential. The default base of 0.05 s
    keeps CI fast while still exercising the deadline machinery; a
    production sweep facing real preempt/restart storms raises it.

    ``jitter=True`` switches to **decorrelated jitter** (the AWS
    backoff shape): retry k sleeps ``uniform(base, 3 * previous_sleep)``
    capped at ``backoff_max_s``. Without it, N lanes felled by the SAME
    injected (or real) fault — a dead data host, a shared-FS blip —
    wake in lockstep and re-hammer the resource that just failed them.
    The jitter stream is a pure function of ``(jitter_seed, key,
    retry_number)`` — no hidden RNG state — so a seeded chaos run
    replays bit-identical backoff schedules (``key`` is the caller's
    decorrelation identity, the trial id in the HPO driver: same trial
    same delays, different trials different delays).
    """

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    jitter: bool = False
    jitter_seed: int = 0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}"
            )
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff times must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1, got {self.backoff_factor}"
            )

    def backoff_s(self, retry_number: int, *, key: int = 0) -> float:
        """Backoff before the ``retry_number``-th retry (1-based).
        ``key`` decorrelates concurrent failure domains under
        ``jitter=True`` (ignored otherwise — the deterministic capped
        exponential is bit-stable for existing callers)."""
        if retry_number < 1:
            raise ValueError(f"retry_number is 1-based, got {retry_number}")
        if not self.jitter:
            return min(
                self.backoff_base_s
                * self.backoff_factor ** (retry_number - 1),
                self.backoff_max_s,
            )
        import numpy as np

        # Decorrelated chain, recomputed deterministically from the
        # start: sleep_k ~ uniform(base, 3 * sleep_{k-1}), each draw
        # from its own (seed, key, k)-derived stream so the value for
        # retry k never depends on how many times this method ran.
        sleep = self.backoff_base_s
        for k in range(1, retry_number + 1):
            rng = np.random.default_rng(
                np.random.SeedSequence(
                    [self.jitter_seed & 0xFFFFFFFF, key & 0xFFFFFFFF, k]
                )
            )
            hi = max(self.backoff_base_s, 3.0 * sleep)
            sleep = min(
                self.backoff_max_s, rng.uniform(self.backoff_base_s, hi)
            )
        return sleep

    def should_retry(self, infra_failures: int, failure_class: str) -> bool:
        """Whether to schedule another attempt after the trial's
        ``infra_failures``-th infra-class failure.

        The budget counts infra FAILURES, not attempts started:
        preemptions (and restart-resumed attempts) must never consume
        the retry budget — a trial preempted twice still deserves its
        full ``max_retries`` against genuine infra faults.
        """
        if failure_class != INFRA:
            return False
        return infra_failures <= self.max_retries
