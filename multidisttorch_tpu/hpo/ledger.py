"""Crash-safe sweep ledger: append-only JSONL attempt history.

The driver's in-memory results die with the process; per-trial
checkpoints recover *weights* but not the sweep's control state (which
trials finished, which attempt a trial is on, what already diverged).
The ledger is that control state, durable: one JSON object per line,
appended and fsync'd at every attempt boundary, keyed by the trial's
**config hash** so a restarted ``run_hpo`` trusts a "completed" record
only when the configuration is byte-identical to what completed.

Crash model: an append either lands whole or tears the final line;
:func:`SweepLedger.load` skips undecodable lines, so a torn tail costs
at most the last event (which the restarted sweep then simply re-runs —
re-running a finished trial is wasteful but correct; *skipping* an
unfinished one would not be).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import time
from typing import Optional

try:  # POSIX file locking for the append/compact exclusion below
    import fcntl
except ImportError:  # non-POSIX host: degrade to unlocked (single-writer)
    fcntl = None  # type: ignore[assignment]

LEDGER_NAME = "sweep_ledger.jsonl"


def config_hash(cfg_dict: dict) -> str:
    """Deterministic hash of a trial's full config (sorted-key JSON).
    Every field participates — a completed record under epochs=1 must
    not satisfy a sweep asking for epochs=3."""
    blob = json.dumps(cfg_dict, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def wasted_steps(ev: dict) -> int:
    """Executed-but-wasted optimizer steps carried by ONE ledger event:
    a non-settled ``attempt_end``'s progress beyond its own resume
    point, or a ``compacted`` summary's carried total; 0 for anything
    else. The single copy of the goodput denominator's per-event fold —
    :meth:`SweepLedger.compact`, the chaos bench, and the multi-host
    drill all share it, so a new status or summary field name changes
    in one place."""
    if ev.get("event") == "compacted":
        return max(0, int(ev.get("wasted_steps", 0) or 0))
    if ev.get("event") != "attempt_end" or ev.get("status") not in (
        "retrying", "preempted", "failed",
    ):
        return 0
    s = ev.get("summary") or {}
    return max(
        0,
        int(s.get("steps_at_failure", 0) or 0)
        - int(s.get("resumed_from_step", 0) or 0),
    )


class SweepLedger:
    """Append-only JSONL event log under ``{out_dir}/sweep_ledger.jsonl``.

    ``enabled=False`` turns the whole ledger off (writes AND reads), so
    the driver can thread one object unconditionally. Multi-controller:
    only ``write=True`` (process 0) appends, but every process reads —
    skip decisions must be identical everywhere, over the shared
    filesystem the checkpoint/resume path already requires.
    """

    def __init__(
        self, out_dir: str, *, enabled: bool = True, write: bool = True
    ):
        self.path = os.path.join(out_dir, LEDGER_NAME)
        self.enabled = enabled
        self.write = write and enabled

    # -- writing -----------------------------------------------------

    @contextlib.contextmanager
    def _mutate_lock(self):
        """Exclusive advisory lock serializing every ledger MUTATION
        (appends and the compaction rewrite) within and across
        processes.

        Compaction is load → rewrite-to-tmp → ``os.replace``; an append
        racing that window lands on the snapshot file *after* the load
        but is then clobbered by the replace — the appended record is
        silently dropped (exactly the record a crash-recovery fold
        would need). The sweep service makes this race routine: its
        intake loop appends attempt records while the supervisor (or a
        ``ledger_view --compact`` operator) compacts between worlds.
        The lock lives on a sidecar (``.lock``) so the ledger file
        itself can still be atomically replaced; readers stay lock-free
        (the torn-tail-tolerant ``load`` never needed one)."""
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if fcntl is None:
            yield
            return
        fd = os.open(self.path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)  # closing drops the flock

    def append(self, event: dict) -> None:
        if not self.write:
            return
        line = json.dumps({**event, "ts": time.time()}, default=str)
        with self._mutate_lock(), open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    @staticmethod
    def _tag_fields(
        tenant: Optional[str], priority: Optional[int],
        submit_ts: Optional[float], trace: Optional[str] = None,
    ) -> dict:
        """Optional multi-tenant provenance (the sweep service's
        scheduling books key off these; ``trace`` is the submission's
        end-to-end trace id — docs/OBSERVABILITY.md "Tracing & SLOs").
        Absent tags serialize NOTHING — pre-service ledgers and
        single-tenant sweeps stay byte-identical, and old records
        parse unchanged."""
        out: dict = {}
        if tenant is not None:
            out["tenant"] = str(tenant)
        if priority is not None:
            out["priority"] = int(priority)
        if submit_ts is not None:
            out["submit_ts"] = float(submit_ts)
        if trace is not None:
            out["trace"] = str(trace)
        return out

    def attempt_start(
        self, trial_id: int, chash: str, attempt: int,
        *,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        submit_ts: Optional[float] = None,
        trace: Optional[str] = None,
    ) -> None:
        # Telemetry rides the ledger's call sites: every attempt
        # boundary in the driver (classic AND stacked-lane paths)
        # already funnels through these two methods, so emitting here —
        # BEFORE the write gate, which only controls the durable file —
        # observes attempts even when the ledger file itself is off.
        from multidisttorch_tpu.telemetry.events import get_bus

        tags = self._tag_fields(tenant, priority, submit_ts, trace)
        bus = get_bus()
        if bus is not None:
            bus.emit(
                "attempt_start",
                trial_id=trial_id,
                attempt=attempt,
                config_hash=chash,
                **tags,
            )
        self.append(
            {
                "event": "attempt_start",
                "trial_id": trial_id,
                "config_hash": chash,
                "attempt": attempt,
                **tags,
            }
        )

    def attempt_end(
        self,
        trial_id: int,
        chash: str,
        attempt: int,
        status: str,
        *,
        error: str = "",
        summary: Optional[dict] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        submit_ts: Optional[float] = None,
        trace: Optional[str] = None,
    ) -> None:
        """``status``: completed | diverged | retrying | failed |
        preempted. ``summary`` (completed/diverged) carries enough to
        reconstruct the TrialResult on a ledger skip."""
        from multidisttorch_tpu.hpo.supervision import SETTLED_STATUSES
        from multidisttorch_tpu.telemetry.events import get_bus
        from multidisttorch_tpu.telemetry.metrics import get_registry

        tags = self._tag_fields(tenant, priority, submit_ts, trace)
        bus = get_bus()
        if bus is not None:
            bus.emit(
                "attempt_end",
                trial_id=trial_id,
                attempt=attempt,
                config_hash=chash,
                status=status,
                error=error,
                summary=summary or {},
                **tags,
            )
        reg = get_registry()
        if reg is not None:
            # The goodput books, live: executed counts every attempt's
            # (end - resume) steps; useful counts settled outcomes only
            # — same math as the chaos bench and the run summary.
            reg.counter("attempts_total", status=status).inc()
            s = summary or {}
            done = int(s.get("steps", s.get("steps_at_failure", 0)) or 0)
            resumed = int(s.get("resumed_from_step", 0) or 0)
            reg.counter("executed_steps_total").inc(max(0, done - resumed))
            if status in SETTLED_STATUSES:
                reg.counter("useful_steps_total").inc(done)
            if status == "retrying":
                reg.counter("retries_total").inc()
        self.append(
            {
                "event": "attempt_end",
                "trial_id": trial_id,
                "config_hash": chash,
                "attempt": attempt,
                "status": status,
                "error": error,
                "summary": summary or {},
                **tags,
            }
        )

    # -- reading -----------------------------------------------------

    def load(self) -> list[dict]:
        """All decodable events, in append order. A torn final line
        (crash mid-append) is skipped, not fatal."""
        if not self.enabled or not os.path.exists(self.path):
            return []
        events = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail from a crash mid-append
        return events

    def finished(self) -> dict[str, dict]:
        """config_hash -> final attempt_end record, for every config
        whose outcome is settled (completed or diverged — the statuses a
        restarted sweep must NOT re-run). A later attempt_start for the
        same hash (a forced re-run) invalidates the earlier settlement."""
        from multidisttorch_tpu.hpo.supervision import SETTLED_STATUSES

        done: dict[str, dict] = {}
        for ev in self.load():
            h = ev.get("config_hash")
            if not h:
                continue
            if (
                ev.get("event") == "attempt_end"
                and ev.get("status") in SETTLED_STATUSES
            ):
                done[h] = ev
            elif ev.get("event") == "attempt_start" and h in done:
                if ev.get("attempt", 0) > done[h].get("attempt", 0):
                    done.pop(h, None)
        return done

    def attempts(self) -> dict[str, int]:
        """config_hash -> number of attempt_start events seen (so a
        restarted driver continues the attempt numbering, keeping the
        ledger's history monotonic). ``compacted`` summary records
        (written by :meth:`compact`) carry forward the pre-compaction
        maximum."""
        counts: dict[str, int] = {}
        for ev in self.load():
            h = ev.get("config_hash")
            if not h:
                continue
            if ev.get("event") == "attempt_start":
                counts[h] = max(counts.get(h, 0), int(ev.get("attempt", 0)))
            elif (
                ev.get("event") == "compacted"
                and int(ev.get("attempts", 0)) > 0
            ):
                counts[h] = max(counts.get(h, 0), int(ev["attempts"]))
        return counts

    def infra_failures(self) -> dict[str, int]:
        """config_hash -> infra failures recorded so far ("retrying" /
        "failed" attempt_ends). The restarted driver seeds its retry
        budgets from this — preempted attempts deliberately do NOT
        count (RetryPolicy.should_retry's contract). ``compacted``
        summary records carry the failures whose individual events
        compaction dropped."""
        counts: dict[str, int] = {}
        for ev in self.load():
            h = ev.get("config_hash")
            if not h:
                continue
            if (
                ev.get("event") == "attempt_end"
                and ev.get("status") in ("retrying", "failed")
            ):
                counts[h] = counts.get(h, 0) + 1
            elif (
                ev.get("event") == "compacted"
                and int(ev.get("infra_failures", 0)) > 0
            ):
                # zero carries add nothing — and must not materialize
                # entries the un-compacted fold never had
                counts[h] = counts.get(h, 0) + int(ev["infra_failures"])
        return counts

    # -- compaction ---------------------------------------------------

    def compact(self) -> dict:
        """Atomically rewrite the ledger to its minimal equivalent
        state.

        A restart storm (elastic world shrinks, preemption loops,
        retry-heavy chaos runs) appends attempt history without bound —
        every restarted driver then re-folds the whole file. Compaction
        keeps, per config hash, exactly what the three restart folds
        (:meth:`finished`, :meth:`attempts`, :meth:`infra_failures`)
        need:

        - one ``compacted`` summary record carrying the attempt
          high-water mark and the infra-failure count of the DROPPED
          events,
        - the newest ``attempt_start`` and the newest ``attempt_end``
          verbatim, in their original relative order (so a settlement
          invalidated by a later re-run start stays invalidated).

        The rewrite lands via tmp + fsync + ``os.replace`` + dir fsync
        — a crash mid-compaction leaves the old ledger intact; a torn
        tail in the input is skipped by :meth:`load` like any other
        read. Returns ``{"lines_before", "lines_after", "hashes"}``
        (zeros when the ledger is disabled or this process is not the
        writer — compaction respects the same write gate as appends).
        """
        if not self.write or not os.path.exists(self.path):
            return {"lines_before": 0, "lines_after": 0, "hashes": 0}
        with self._mutate_lock():
            return self._compact_locked()

    def _compact_locked(self) -> dict:
        # Under _mutate_lock: no append can land between the load below
        # and the os.replace at the end, so the rewrite can never
        # clobber a record it did not fold (the race this lock exists
        # for — a live intake/attempt appender racing a between-worlds
        # compaction used to drop the appended line).
        events = self.load()
        per_hash: dict[str, dict] = {}
        other: list[dict] = []  # hash-less events survive verbatim
        for idx, ev in enumerate(events):
            h = ev.get("config_hash")
            if not h or ev.get("event") not in (
                "attempt_start", "attempt_end", "compacted"
            ):
                other.append(ev)
                continue
            rec = per_hash.setdefault(
                h,
                {
                    "first_idx": idx,
                    "trial_id": ev.get("trial_id"),
                    "start": None,
                    "end": None,
                    "attempts": 0,
                    "infra": 0,
                    "wasted": 0,
                },
            )
            if ev.get("event") == "attempt_start":
                rec["start"] = (idx, ev)
                rec["attempts"] = max(
                    rec["attempts"], int(ev.get("attempt", 0))
                )
            elif ev.get("event") == "attempt_end":
                rec["end"] = (idx, ev)
                if ev.get("status") in ("retrying", "failed"):
                    rec["infra"] += 1
                rec["wasted"] += wasted_steps(ev)
            else:  # an earlier compaction's summary folds in
                rec["attempts"] = max(
                    rec["attempts"], int(ev.get("attempts", 0))
                )
                rec["infra"] += int(ev.get("infra_failures", 0))
                rec["wasted"] += wasted_steps(ev)
        out: list[dict] = list(other)
        for h, rec in sorted(
            per_hash.items(), key=lambda kv: kv[1]["first_idx"]
        ):
            kept = [p for p in (rec["start"], rec["end"]) if p is not None]
            kept.sort(key=lambda p: p[0])  # original relative order
            # The summary counts only what is NOT kept verbatim, so the
            # infra_failures fold never double-counts the retained end.
            kept_infra = sum(
                1
                for _, ev in kept
                if ev.get("event") == "attempt_end"
                and ev.get("status") in ("retrying", "failed")
            )
            kept_wasted = sum(wasted_steps(ev) for _, ev in kept)
            out.append(
                {
                    "event": "compacted",
                    "config_hash": h,
                    "trial_id": rec["trial_id"],
                    "attempts": rec["attempts"],
                    "infra_failures": max(0, rec["infra"] - kept_infra),
                    # Executed-but-wasted steps of the DROPPED
                    # non-settled attempt_ends (goodput's denominator
                    # input — the chaos accounting must not lose wasted
                    # work to compaction).
                    "wasted_steps": max(0, rec["wasted"] - kept_wasted),
                    "ts": time.time(),
                }
            )
            out.extend(ev for _, ev in kept)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for ev in out:
                f.write(json.dumps(ev, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        try:  # durably record the rename (best-effort, like checkpoint.py)
            fd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass
        return {
            "lines_before": len(events),
            "lines_after": len(out),
            "hashes": len(per_hash),
        }
