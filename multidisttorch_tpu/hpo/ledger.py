"""Crash-safe sweep ledger: append-only JSONL attempt history.

The driver's in-memory results die with the process; per-trial
checkpoints recover *weights* but not the sweep's control state (which
trials finished, which attempt a trial is on, what already diverged).
The ledger is that control state, durable: one JSON object per line,
appended and fsync'd at every attempt boundary, keyed by the trial's
**config hash** so a restarted ``run_hpo`` trusts a "completed" record
only when the configuration is byte-identical to what completed.

Crash model: an append either lands whole or tears the final line;
:func:`SweepLedger.load` skips undecodable lines, so a torn tail costs
at most the last event (which the restarted sweep then simply re-runs —
re-running a finished trial is wasteful but correct; *skipping* an
unfinished one would not be).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

LEDGER_NAME = "sweep_ledger.jsonl"


def config_hash(cfg_dict: dict) -> str:
    """Deterministic hash of a trial's full config (sorted-key JSON).
    Every field participates — a completed record under epochs=1 must
    not satisfy a sweep asking for epochs=3."""
    blob = json.dumps(cfg_dict, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class SweepLedger:
    """Append-only JSONL event log under ``{out_dir}/sweep_ledger.jsonl``.

    ``enabled=False`` turns the whole ledger off (writes AND reads), so
    the driver can thread one object unconditionally. Multi-controller:
    only ``write=True`` (process 0) appends, but every process reads —
    skip decisions must be identical everywhere, over the shared
    filesystem the checkpoint/resume path already requires.
    """

    def __init__(
        self, out_dir: str, *, enabled: bool = True, write: bool = True
    ):
        self.path = os.path.join(out_dir, LEDGER_NAME)
        self.enabled = enabled
        self.write = write and enabled

    # -- writing -----------------------------------------------------

    def append(self, event: dict) -> None:
        if not self.write:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        line = json.dumps({**event, "ts": time.time()}, default=str)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    def attempt_start(
        self, trial_id: int, chash: str, attempt: int
    ) -> None:
        # Telemetry rides the ledger's call sites: every attempt
        # boundary in the driver (classic AND stacked-lane paths)
        # already funnels through these two methods, so emitting here —
        # BEFORE the write gate, which only controls the durable file —
        # observes attempts even when the ledger file itself is off.
        from multidisttorch_tpu.telemetry.events import get_bus

        bus = get_bus()
        if bus is not None:
            bus.emit(
                "attempt_start",
                trial_id=trial_id,
                attempt=attempt,
                config_hash=chash,
            )
        self.append(
            {
                "event": "attempt_start",
                "trial_id": trial_id,
                "config_hash": chash,
                "attempt": attempt,
            }
        )

    def attempt_end(
        self,
        trial_id: int,
        chash: str,
        attempt: int,
        status: str,
        *,
        error: str = "",
        summary: Optional[dict] = None,
    ) -> None:
        """``status``: completed | diverged | retrying | failed |
        preempted. ``summary`` (completed/diverged) carries enough to
        reconstruct the TrialResult on a ledger skip."""
        from multidisttorch_tpu.hpo.supervision import SETTLED_STATUSES
        from multidisttorch_tpu.telemetry.events import get_bus
        from multidisttorch_tpu.telemetry.metrics import get_registry

        bus = get_bus()
        if bus is not None:
            bus.emit(
                "attempt_end",
                trial_id=trial_id,
                attempt=attempt,
                config_hash=chash,
                status=status,
                error=error,
                summary=summary or {},
            )
        reg = get_registry()
        if reg is not None:
            # The goodput books, live: executed counts every attempt's
            # (end - resume) steps; useful counts settled outcomes only
            # — same math as the chaos bench and the run summary.
            reg.counter("attempts_total", status=status).inc()
            s = summary or {}
            done = int(s.get("steps", s.get("steps_at_failure", 0)) or 0)
            resumed = int(s.get("resumed_from_step", 0) or 0)
            reg.counter("executed_steps_total").inc(max(0, done - resumed))
            if status in SETTLED_STATUSES:
                reg.counter("useful_steps_total").inc(done)
            if status == "retrying":
                reg.counter("retries_total").inc()
        self.append(
            {
                "event": "attempt_end",
                "trial_id": trial_id,
                "config_hash": chash,
                "attempt": attempt,
                "status": status,
                "error": error,
                "summary": summary or {},
            }
        )

    # -- reading -----------------------------------------------------

    def load(self) -> list[dict]:
        """All decodable events, in append order. A torn final line
        (crash mid-append) is skipped, not fatal."""
        if not self.enabled or not os.path.exists(self.path):
            return []
        events = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail from a crash mid-append
        return events

    def finished(self) -> dict[str, dict]:
        """config_hash -> final attempt_end record, for every config
        whose outcome is settled (completed or diverged — the statuses a
        restarted sweep must NOT re-run). A later attempt_start for the
        same hash (a forced re-run) invalidates the earlier settlement."""
        from multidisttorch_tpu.hpo.supervision import SETTLED_STATUSES

        done: dict[str, dict] = {}
        for ev in self.load():
            h = ev.get("config_hash")
            if not h:
                continue
            if (
                ev.get("event") == "attempt_end"
                and ev.get("status") in SETTLED_STATUSES
            ):
                done[h] = ev
            elif ev.get("event") == "attempt_start" and h in done:
                if ev.get("attempt", 0) > done[h].get("attempt", 0):
                    done.pop(h, None)
        return done

    def attempts(self) -> dict[str, int]:
        """config_hash -> number of attempt_start events seen (so a
        restarted driver continues the attempt numbering, keeping the
        ledger's history monotonic)."""
        counts: dict[str, int] = {}
        for ev in self.load():
            if ev.get("event") == "attempt_start" and ev.get("config_hash"):
                h = ev["config_hash"]
                counts[h] = max(counts.get(h, 0), int(ev.get("attempt", 0)))
        return counts

    def infra_failures(self) -> dict[str, int]:
        """config_hash -> infra failures recorded so far ("retrying" /
        "failed" attempt_ends). The restarted driver seeds its retry
        budgets from this — preempted attempts deliberately do NOT
        count (RetryPolicy.should_retry's contract)."""
        counts: dict[str, int] = {}
        for ev in self.load():
            if (
                ev.get("event") == "attempt_end"
                and ev.get("config_hash")
                and ev.get("status") in ("retrying", "failed")
            ):
                h = ev["config_hash"]
                counts[h] = counts.get(h, 0) + 1
        return counts
