"""Host-side HPO driver: N concurrent trials on N disjoint submeshes.

Rebuild of the reference's trial dispatch (``/root/reference/
vae-hpo.py:177-202``), where each process loops over all groups, finds
the one it belongs to, and runs a DDP trial whose only hyperparameter is
``epochs + group_id``. Redesigned per SURVEY.md §7:

- **Real per-trial configs** (:class:`TrialConfig`: lr, β, epochs,
  batch size, seed, model dims — generalizing quirk Q7).
- **Cooperative round-robin dispatch**: all trials' jit steps are
  enqueued from one host loop; JAX's async dispatch keeps every submesh
  busy while the host cycles. A fast trial finishes and frees its
  submesh immediately — **no cross-trial barrier anywhere** (fixes Q3,
  where the reference's world-scoped barriers serialize the sweep on the
  slowest trial).
- **Per-trial output dirs** ``{out_dir}/trial-{id}/`` (fixes Q4's
  ``results-{rank}`` collision where group 0 and 1 overwrite each
  other's PNGs).
- In multi-controller SPMD each process runs only the trials whose
  submesh intersects its local devices (``TrialMesh.is_local_member``) —
  the same membership contract as the reference's
  ``dist.get_rank(group) >= 0`` (``vae-hpo.py:201``).
- **Elastic scheduling**: more configs than submeshes is legal — the
  reference hard-binds one trial per group forever (``vae-hpo.py:
  200-202``); here freed submeshes immediately pick up the next queued
  config (greedy single-controller; deterministic least-predicted-load
  assignment multi-controller — :func:`balanced_assignment` — where
  every process must schedule identically without communicating).
- **Failure isolation** (``resilient=True``): one trial's exception
  marks that trial failed and frees its submesh; the rest of the sweep
  proceeds. The reference has no failure handling at all — a dead rank
  hangs every world barrier (SURVEY.md §5).
- **Checkpoint/resume** (``resume=True``): per-epoch checkpoints; a
  re-run restores each trial at its last completed epoch (or skips it
  entirely if done). The reference persists nothing but PNGs.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field, asdict
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import (
    EvalDataIterator,
    StackedTrialDataIterator,
    TrialDataIterator,
)
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import TrialMesh, setup_groups
from multidisttorch_tpu.train.checkpoint import restore_state, save_state
from multidisttorch_tpu.train.steps import (
    TrialHypers,
    build_lane_state,
    create_stacked_train_state,
    create_train_state,
    make_eval_step,
    make_lane_ops,
    make_multi_step,
    make_sample_step,
    make_stacked_eval_step,
    make_stacked_multi_step,
    make_stacked_train_step,
    make_train_step,
    state_shardings,
)
from multidisttorch_tpu.utils.imaging import save_image_grid
from multidisttorch_tpu.utils.logging import log0


@dataclass(frozen=True)
class TrialConfig:
    """One trial's hyperparameters (the reference's single knob was
    ``epochs + group_id``, ``vae-hpo.py:202``)."""

    trial_id: int
    epochs: int = 3
    batch_size: int = 128
    lr: float = 1e-3  # reference Adam lr, vae-hpo.py:131
    beta: float = 1.0
    seed: int = 0
    hidden_dim: int = 400
    latent_dim: int = 20
    log_interval: int = 10  # reference train log cadence, vae-hpo.py:61
    # Train steps fused into one device dispatch (make_multi_step's
    # lax.scan). 1 = the reference's one-dispatch-per-batch loop shape;
    # >1 amortizes host dispatch, the dominant cost at this model size.
    # Changes the per-step RNG stream (keys are split per chunk instead
    # of folded per step), so it participates in the resume
    # config-match check like any other hyperparameter.
    fused_steps: int = 1
    # Reference-parity eval semantics: the reference's test() runs the
    # full sampled forward (z drawn from the posterior —
    # /root/reference/vae-hpo.py:101-105 calling model(data), :42-45).
    # Default False = posterior-mean eval (deterministic, strictly
    # tighter bound); True reproduces the reference's sampled test-loss
    # metric for apples-to-apples quality comparison.
    eval_sampled: bool = False
    # Rematerialize activations in the backward pass (jax.checkpoint):
    # trade recompute FLOPs for HBM when the model or the fused-steps
    # scan outgrows device memory. Numerically identical training.
    remat: bool = False
    # Gradient accumulation: split each batch into this many equal
    # microbatches, accumulate grads in-step, one optimizer update —
    # the effective batch size can exceed HBM. Composes with remat.
    grad_accum: int = 1


@dataclass
class TrialResult:
    trial_id: int
    group_id: int
    config: TrialConfig
    history: list = field(default_factory=list)  # per-epoch dicts
    final_train_loss: float = float("nan")  # per-sample avg, last epoch
    final_test_loss: float = float("nan")
    wall_s: float = 0.0
    steps: int = 0
    out_dir: str = ""
    checkpoint: str = ""
    status: str = "completed"  # "completed" | "failed" | "resumed_complete"
    error: str = ""
    # Data provenance: which dataset the trial actually trained on, and
    # whether it was the synthetic zero-egress stand-in. The reference
    # always trains on real MNIST (vae-hpo.py:133-144); this repo can
    # silently degrade to synthetic (data/datasets.py), so a trial's
    # recorded metrics must say which world they came from.
    dataset: str = ""
    dataset_synthetic: bool = False
    # Host↔device round-trips the trial actually paid for metric
    # fetches (the O(1)-syncs discipline: ≤ log lines + 2 per epoch;
    # regression-tested in tests/test_hpo.py). For a stacked trial this
    # counts its whole bucket's fetches during the trial's lifetime —
    # the bucket pays them once for ALL lanes.
    host_syncs: int = 0
    # True when the trial ran as one lane of a stacked bucket
    # (docs/STACKING.md): K same-shape trials vmapped through one
    # compiled program on one submesh.
    stacked: bool = False


class _TrialRun:
    """One trial's full lifecycle as a cooperative generator.

    Each ``next()`` dispatches one unit of training work async — a
    single train step, or a chunk of ``cfg.fused_steps`` scan-fused
    steps — and returns; host-device syncs happen only at the
    reference's logging cadence and at epoch boundaries. The generator
    shape is what makes the no-barrier scheduling work: the driver
    interleaves ``next()`` across trials, so every submesh has work
    queued at all times.
    """

    def __init__(
        self,
        trial: TrialMesh,
        cfg: TrialConfig,
        train_data: Dataset,
        test_data: Optional[Dataset],
        out_dir: str,
        *,
        shard_across_trials: bool = False,
        num_trials: int = 1,
        save_images: bool = True,
        save_checkpoint: bool = True,
        verbose: bool = True,
        model_builder=None,
        param_shardings_builder=None,
        resume: bool = False,
        agree_failures: bool = False,
    ):
        if cfg.fused_steps < 1:
            raise ValueError(
                f"fused_steps must be >= 1, got {cfg.fused_steps} "
                f"(trial {cfg.trial_id})"
            )
        self.trial = trial
        self.cfg = cfg
        self.out_dir = os.path.join(out_dir, f"trial-{cfg.trial_id}")
        self.result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=trial.group_id,
            config=cfg,
            out_dir=self.out_dir,
            dataset=train_data.name,
            dataset_synthetic=train_data.synthetic,
        )
        # Artifacts (images, checkpoints, metrics.json) are written by
        # exactly one process per group — on a shared filesystem,
        # every-owner-writes would race identical files (Q4's
        # multi-process half). Resume restores *state* on all owner
        # processes; only the writer re-reads sidecar metadata.
        self._is_writer = trial.is_writer_process
        # Uniform across owner processes (drives which programs are
        # compiled AND dispatched — dispatch gating must never be
        # writer-local on a process-spanning submesh, or SPMD execution
        # desynchronizes); the writer-gated flag below controls only
        # host-side fetch + file writes.
        self._images_requested = save_images
        self._save_images = save_images and self._is_writer
        self._save_checkpoint = save_checkpoint
        self._verbose = verbose
        self._test_data = test_data
        # Multi-host failure isolation (resilient sweeps on spanning
        # submeshes): writer-only host-I/O failures are deferred and
        # agreed at the epoch boundary via a submesh-scoped reduction
        # (collectives.group_all_ok), so every owner process kills the
        # trial identically instead of one process freeing the group
        # while peers keep stepping it.
        self._agree = agree_failures
        self._deferred_error: Optional[BaseException] = None
        self._host_syncs = 0

        if model_builder is None:
            model = VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
        else:
            model = model_builder(cfg)
        tx = optax.adam(cfg.lr)
        self.model, self.tx = model, tx
        # Within-trial weight sharding (TP/EP/FSDP): the builder maps
        # (trial, model) -> a param-shardings pytree (e.g.
        # models.vae.vae_tp_shardings, models.moe_vae.moe_vae_ep_shardings);
        # the derived state shardings then pin every step's layout.
        param_sh = (
            param_shardings_builder(trial, model)
            if param_shardings_builder is not None
            else None
        )
        self.state = create_train_state(
            trial, model, tx, jax.random.key(cfg.seed),
            param_shardings=param_sh,
        )
        self._state_sh = (
            state_shardings(self.state) if param_sh is not None else None
        )
        # Checkpointing a weight-sharded state: serialization needs the
        # whole array on the writer host, but on a spanning submesh the
        # writer holds only its shards. The gather-to-replicated below
        # is DISPATCHED by every owner (uniform SPMD program — the same
        # rule as every other step); only the fetch stays writer-gated.
        self._gather_state = (
            jax.jit(lambda s: s, out_shardings=trial.replicated_sharding)
            if param_sh is not None
            else None
        )
        self.train_step = make_train_step(
            trial, model, tx, beta=cfg.beta, remat=cfg.remat,
            grad_accum=cfg.grad_accum, shardings=self._state_sh,
        )
        self.multi_step = (
            make_multi_step(
                trial, model, tx, beta=cfg.beta, remat=cfg.remat,
                grad_accum=cfg.grad_accum, shardings=self._state_sh,
            )
            if cfg.fused_steps > 1
            else None
        )
        # Reconstructions are materialized (and all-gathered back to
        # replicated) only when images are wanted. Keyed on the uniform
        # save_images argument, NOT the per-process writer-gated flag:
        # all owner processes must compile the identical eval program.
        self.eval_step = make_eval_step(
            trial,
            model,
            beta=cfg.beta,
            with_recon=save_images,
            masked=True,
            sampled=cfg.eval_sampled,
            shardings=self._state_sh,
        )
        self.sample_step = make_sample_step(
            trial, model, shardings=self._state_sh
        )
        self.train_iter = TrialDataIterator(
            train_data,
            trial,
            cfg.batch_size,
            seed=cfg.seed,
            shard_across_trials=shard_across_trials,
            num_trials=num_trials,
        )
        # Full-coverage eval (reference parity, vae-hpo.py:101-105): the
        # pad-and-mask iterator consumes every test row — including test
        # sets smaller than one batch, which round 1 silently skipped.
        self.test_iter = (
            EvalDataIterator(test_data, trial, cfg.batch_size)
            if test_data is not None and len(test_data) > 0
            else None
        )
        self._first_test_batch = None
        self._key = jax.random.key(cfg.seed + 1)

        # Resume: per-epoch checkpoints carry (state, completed_epochs,
        # history); restore at the last epoch boundary. Epoch data order
        # and step RNG are deterministic in (seed, epoch) / step number,
        # so a resumed run replays the exact remaining stream.
        self._ckpt_path = os.path.join(self.out_dir, "state.msgpack")
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_error: Optional[BaseException] = None
        self._start_epoch = 1
        if resume:
            meta_path = self._ckpt_path + ".json"
            if os.path.exists(self._ckpt_path) and os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                # Guard against resuming under silently-changed
                # hyperparameters: everything except the epoch target
                # (extending epochs is the legitimate resume use) must
                # match the checkpoint's saved config. Fields absent
                # from an older checkpoint's sidecar compare against
                # their TrialConfig default — a checkpoint written
                # before a field existed was trained under its default.
                from dataclasses import MISSING, fields as dc_fields

                field_defaults = {
                    f.name: f.default
                    for f in dc_fields(TrialConfig)
                    if f.default is not MISSING
                }
                saved = {
                    k: meta.get(k, field_defaults.get(k))
                    for k in asdict(cfg)
                    if k != "epochs" and (k in meta or k in field_defaults)
                }
                current = {k: v for k, v in asdict(cfg).items() if k != "epochs"}
                if saved and saved != current:
                    diff = {
                        k: (saved.get(k), current[k])
                        for k in current
                        if saved.get(k) != current[k]
                    }
                    raise ValueError(
                        f"resume: trial {cfg.trial_id} checkpoint at "
                        f"{self._ckpt_path} was written under different "
                        f"hyperparameters {diff} (saved vs current); "
                        "refusing to continue stale weights under a "
                        "changed config"
                    )
                done = int(meta.get("completed_epochs", 0))
                if done >= 1:
                    self.state = restore_state(
                        self.state, self._ckpt_path, trial,
                        shardings=self._state_sh,
                    )
                    restored_step = int(jax.device_get(self.state.step))
                    if "step" in meta and restored_step != int(meta["step"]):
                        raise ValueError(
                            f"resume: trial {cfg.trial_id} checkpoint is "
                            f"skewed — state.msgpack is at optimizer step "
                            f"{restored_step} but the metadata sidecar "
                            f"claims step {meta['step']} (epoch {done}). "
                            "A crash likely landed between the two "
                            "checkpoint file replaces; delete "
                            f"{self._ckpt_path}* to restart this trial "
                            "from scratch rather than silently re-train "
                            "an already-applied epoch"
                        )
                    self._start_epoch = done + 1
                    self.result.history = list(meta.get("history", []))
                    if self.result.history:
                        last = self.result.history[-1]
                        self.result.final_train_loss = last.get(
                            "avg_train_loss", float("nan")
                        )
                        self.result.final_test_loss = last.get(
                            "test_loss", float("nan")
                        )

    def _log(self, *args):
        if self._verbose:
            log0(*args, trial=self.trial)

    @contextmanager
    def _guard(self):
        """Collect writer-only host-I/O failures (image/checkpoint/
        metrics writes) for epoch-boundary agreement instead of raising
        on one process of a spanning submesh. No-op outside agreement
        mode: errors raise at the fault site, reference-honest."""
        if not self._agree:
            yield
            return
        try:
            yield
        except Exception as e:  # noqa: BLE001 — deferred to agreement
            if self._deferred_error is None:
                self._deferred_error = e

    def _agree_boundary(self, where: str) -> None:
        """Epoch-boundary health agreement over the trial submesh.

        Every owner process calls this at the same point in the group's
        dispatch sequence (deterministic cadence: once per epoch + once
        at completion). If any owner deferred a failure, ALL owners
        raise here — the submesh is freed identically everywhere, and
        unrelated trials never participate (no world barrier; quirk Q3
        stays fixed). Deterministic compute failures need no agreement:
        SPMD determinism raises them identically on every owner.
        """
        if not self._agree:
            return
        from multidisttorch_tpu.parallel.collectives import group_all_ok

        err, self._deferred_error = self._deferred_error, None
        if not group_all_ok(self.trial, err is None):
            if err is not None:
                raise err
            raise RuntimeError(
                f"trial {self.cfg.trial_id}: {where} failed on a peer "
                "owner process (agreed via submesh health reduction)"
            )

    def _write_ckpt(self, host_state, meta: dict) -> None:
        """Background checkpoint write. ``result.checkpoint`` is set only
        after the (atomic) write succeeds, so a failed write can never be
        reported as a valid checkpoint; failures are re-raised on the
        next :meth:`_join_ckpt` and flow through the trial's normal
        failure isolation."""
        try:
            save_state(host_state, self._ckpt_path, metadata=meta)
            self.result.checkpoint = self._ckpt_path
        except BaseException as e:  # re-raised at the next join
            self._ckpt_error = e

    def _join_ckpt(self) -> None:
        if self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._ckpt_thread = None
        if self._ckpt_error is not None:
            e, self._ckpt_error = self._ckpt_error, None
            raise RuntimeError(
                f"trial {self.cfg.trial_id}: checkpoint write to "
                f"{self._ckpt_path} failed"
            ) from e

    def run(self) -> Iterator[None]:
        cfg = self.cfg
        t0 = time.time()
        if self._start_epoch > cfg.epochs:
            # Fully-trained checkpoint found: nothing to replay.
            self.result.status = "resumed_complete"
            self.result.steps = int(jax.device_get(self.state.step))
            self.result.checkpoint = self._ckpt_path
            self._log(f"Trial {cfg.trial_id} already complete; resumed.")
            return
        n_per_epoch = self.train_iter.samples_per_epoch
        # state.step counts optimizer updates, so it doubles as the
        # resume-safe global step for RNG folding.
        step_no = int(jax.device_get(self.state.step))
        for epoch in range(self._start_epoch, cfg.epochs + 1):
            # On-device loss accumulation (mirrors the eval path below):
            # each batch's contribution is an async device add; the
            # single float() at the epoch boundary is the train loop's
            # only non-logging host sync.
            epoch_sum_dev = None

            def log_batch(epoch, i, loss_sum):
                if not self._verbose:
                    return  # don't pay the device sync for a dropped line
                # sync point for THIS trial only (reference logs
                # loss.item() here, vae-hpo.py:76-86)
                self._host_syncs += 1
                per_sample = float(loss_sum) / cfg.batch_size
                self._log(
                    "Train Epoch: {} [{}/{} ({:.0f}%)]\tLoss: {:.6f}".format(
                        epoch,
                        i * cfg.batch_size,
                        n_per_epoch,
                        100.0 * i / self.train_iter.num_batches,
                        per_sample,
                    )
                )

            if self.multi_step is None:
                for i, batch in enumerate(self.train_iter.epoch(epoch)):
                    rng = jax.random.fold_in(self._key, step_no)
                    self.state, metrics = self.train_step(
                        self.state, batch, rng
                    )
                    step_no += 1
                    s = metrics["loss_sum"]  # on device, async
                    epoch_sum_dev = s if epoch_sum_dev is None else epoch_sum_dev + s
                    if i % cfg.log_interval == 0:
                        log_batch(epoch, i, metrics["loss_sum"])
                    yield  # hand the host loop to the next trial
            else:
                # Scan-fused dispatch: fused_steps optimizer updates per
                # host round-trip. The log cadence is preserved exactly —
                # the chunk's per-step losses are indexable, so the batch
                # that would have logged in the per-step loop still does.
                K = cfg.fused_steps
                for item in self.train_iter.epoch_chunks(epoch, K):
                    i0, chunk = item[0], item[1]
                    c = chunk.shape[0]
                    if c == K:
                        rng = jax.random.fold_in(self._key, step_no)
                        self.state, metrics = self.multi_step(
                            self.state, chunk, rng
                        )
                        step_no += c
                        losses = metrics["loss_sum"]  # (K,) on device
                        s = losses.sum()  # device add, async
                        epoch_sum_dev = (
                            s if epoch_sum_dev is None else epoch_sum_dev + s
                        )
                        # Every batch index that would have logged in the
                        # per-step loop still logs (there can be several
                        # per chunk when log_interval < fused_steps).
                        j = -(-i0 // cfg.log_interval) * cfg.log_interval
                        while j < i0 + c:
                            log_batch(epoch, j, losses[j - i0])
                            j += cfg.log_interval
                    else:
                        # Tail shorter than the compiled chunk: step it
                        # batch-by-batch (no extra compilation).
                        for j in range(c):
                            rng = jax.random.fold_in(self._key, step_no)
                            self.state, metrics = self.train_step(
                                self.state, chunk[j], rng
                            )
                            step_no += 1
                            s = metrics["loss_sum"]
                            epoch_sum_dev = (
                                s
                                if epoch_sum_dev is None
                                else epoch_sum_dev + s
                            )
                            if (i0 + j) % cfg.log_interval == 0:
                                log_batch(epoch, i0 + j, metrics["loss_sum"])
                    yield

            # One fetch for the whole epoch's average (O(1)-syncs rule).
            self._host_syncs += 1
            avg = float(epoch_sum_dev) / n_per_epoch
            self._log(
                "====> Epoch: {} Average loss: {:.4f}".format(epoch, avg)
            )
            epoch_record = {"epoch": epoch, "avg_train_loss": avg}

            if self.test_iter is not None:
                # On-device loss accumulation: the per-batch adds are
                # async dispatches; the single float() at the end is the
                # epoch's only eval host sync (round 1 synced every
                # batch, the last per-batch round-trip on the hot path).
                test_sum_dev, first_batch, first_recon = None, None, None
                for j, (tbatch, tweights) in enumerate(
                    self.test_iter.batches()
                ):
                    if cfg.eval_sampled:
                        # Distinct key per (epoch, batch), disjoint from
                        # the train stream (offset past any step count).
                        erng = jax.random.fold_in(
                            self._key, 2**28 + epoch * 2**16 + j
                        )
                        out = self.eval_step(
                            self.state, tbatch, tweights, erng
                        )
                    else:
                        out = self.eval_step(self.state, tbatch, tweights)
                    test_sum_dev = (
                        out["loss_sum"]
                        if test_sum_dev is None
                        else test_sum_dev + out["loss_sum"]
                    )
                    if j == 0 and self._save_images:
                        # batch values from the deterministic host view
                        # (the device batch is data-sharded and, on a
                        # process-spanning submesh, not fetchable whole);
                        # recon is replicated, hence fetchable anywhere.
                        if self._first_test_batch is None:
                            self._first_test_batch = (
                                self.test_iter.first_host_batch()
                            )
                        first_batch = self._first_test_batch
                        first_recon = np.asarray(out["recon"])
                    yield
                # Exact-count divisor: every real row was evaluated, the
                # padded rows carried weight 0.0.
                self._host_syncs += 1
                test_avg = float(test_sum_dev) / self.test_iter.num_rows
                self._log("====> Test set loss: {:.4f}".format(test_avg))
                epoch_record["test_loss"] = test_avg
                self.result.final_test_loss = test_avg
                if self._save_images and first_batch is not None:
                    with self._guard():
                        # input-vs-recon grid (vae-hpo.py:106-116)
                        n = min(8, first_batch.shape[0])
                        comparison = np.concatenate(
                            [first_batch[:n], first_recon[:n]]
                        )
                        save_image_grid(
                            comparison,
                            os.path.join(
                                self.out_dir, f"reconstruction_{epoch}.png"
                            ),
                            nrow=n,
                        )

            if self._images_requested:
                # prior-sample grid (vae-hpo.py:163-170). The dispatch is
                # UNIFORM across owner processes (a jit program on the
                # submesh — writer-gating it would desynchronize SPMD on
                # a spanning group); only the fetch + PNG write below are
                # writer-only.
                # sample keys live in a disjoint fold_in range (steps
                # count up from 0; fold_in data must be non-negative)
                sample_out = self.sample_step(
                    self.state, jax.random.fold_in(self._key, 2**30 + epoch)
                )
                if self._save_images:
                    with self._guard():
                        save_image_grid(
                            np.asarray(sample_out),
                            os.path.join(self.out_dir, f"sample_{epoch}.png"),
                        )

            self.result.history.append(epoch_record)
            self.result.final_train_loss = avg
            if self._save_checkpoint:
                # Sharded states gather to replicated first — dispatched
                # on ALL owners (uniform program; a writer-local gather
                # would desynchronize a spanning submesh), making every
                # leaf fully addressable for the writer's fetch below.
                snap = (
                    self._gather_state(self.state)
                    if self._gather_state is not None
                    else self.state
                )
            if self._save_checkpoint and self._is_writer:
                with self._guard():
                    # Per-epoch checkpoint = the resume boundary. Keep
                    # the scheduler loop responsive: start the
                    # device→host copy async, yield once so other trials
                    # keep dispatching, then hand the serialize+disk-
                    # write to a background thread. The snapshot is
                    # taken before the next epoch's first step, so
                    # donation can't invalidate it (the gathered copy is
                    # its own buffer in the sharded case).
                    jax.tree.map(lambda x: x.copy_to_host_async(), snap)
                    yield
                    host_state = jax.device_get(snap)
                    meta = {
                        **asdict(cfg),
                        "completed_epochs": epoch,
                        # Optimizer-step count at this epoch boundary:
                        # resume cross-checks it against the restored
                        # state so a crash landing between the two
                        # atomic replaces (state newer than sidecar) is
                        # detected, not silently re-trained.
                        "step": int(host_state.step),
                        "history": list(self.result.history),
                    }
                    self._join_ckpt()
                    self._ckpt_thread = threading.Thread(
                        target=self._write_ckpt,
                        args=(host_state, meta),
                        # Non-daemon: interpreter exit waits for the
                        # write (atexit joins it), so a crash elsewhere
                        # in the sweep can't kill a checkpoint
                        # mid-flight.
                        daemon=False,
                    )
                    self._ckpt_thread.start()
            # One agreement per epoch: all owners of a spanning submesh
            # kill the trial together if any of them deferred a failure.
            self._agree_boundary(f"epoch {epoch} boundary work")

        # drain the pipeline so wall-clock covers real completion
        jax.block_until_ready(self.state.params)
        with self._guard():
            self._join_ckpt()
        self.result.wall_s = time.time() - t0
        self.result.steps = step_no
        self.result.host_syncs = self._host_syncs
        if self._is_writer:
            with self._guard():
                os.makedirs(self.out_dir, exist_ok=True)
                with open(
                    os.path.join(self.out_dir, "metrics.json"), "w"
                ) as f:
                    json.dump(
                        {
                            "trial_id": self.result.trial_id,
                            "group_id": self.result.group_id,
                            "config": asdict(cfg),
                            "dataset": self.result.dataset,
                            "dataset_synthetic": self.result.dataset_synthetic,
                            "history": self.result.history,
                            "wall_s": self.result.wall_s,
                            "steps": self.result.steps,
                        },
                        f,
                        indent=2,
                    )
        self._agree_boundary("completion work")
        self._log(f"Done. time: {self.result.wall_s:f}")


def stack_bucket_key(cfg: TrialConfig) -> tuple:
    """The shape signature under which trials may share one compiled
    stacked program: everything that changes an array shape or the
    compiled step structure. Scalar hypers (lr, beta, seed) and the
    epoch target deliberately stay OUT — they are the vmapped axis."""
    return (
        cfg.batch_size,
        cfg.hidden_dim,
        cfg.latent_dim,
        cfg.fused_steps,
        cfg.grad_accum,
        cfg.remat,
    )


def config_is_stackable(cfg: TrialConfig) -> bool:
    """Whether a config can ride a stacked bucket at all. Sampled eval
    is the one per-trial knob the stacked eval step does not carry
    (posterior-mean eval only); such configs run the classic path."""
    return not cfg.eval_sampled


class _StackedBucketRun:
    """One shape-bucket of K stacked trials on ONE submesh, as a
    cooperative generator (the stacked sibling of :class:`_TrialRun`).

    All lanes advance in lockstep rounds of ``num_batches`` optimizer
    steps (one round = one epoch for every lane, since bucket members
    share dataset and batch size by construction); each dispatch is one
    vmapped program advancing every lane at once, scan-chunked by the
    bucket's ``fused_steps``. A lane that reaches its config's epoch
    target retires — its result and checkpoint are captured from a
    compiled lane-slice read — and is refilled in place from the
    bucket's pending queue (``write_lane``; traced lane index, so no
    recompilation ever) or masked inactive when the queue is dry.

    Per-trial RNG discipline matches the unstacked *per-step* path
    exactly (``fold_in(key(seed+1), step)``), so a stacked trial's
    weights are bit-identical to the same config run unstacked with
    ``fused_steps=1`` — the parity contract tests/test_stacking.py
    enforces.
    """

    def __init__(
        self,
        trial: TrialMesh,
        items: Sequence[tuple[int, TrialConfig]],
        train_data: Dataset,
        test_data: Optional[Dataset],
        out_dir: str,
        *,
        max_lanes: int = 8,
        save_checkpoint: bool = True,
        verbose: bool = True,
    ):
        template = items[0][1]
        for _, cfg in items:
            if stack_bucket_key(cfg) != stack_bucket_key(template):
                raise ValueError(
                    "stacked bucket mixes shape keys: "
                    f"{stack_bucket_key(cfg)} vs {stack_bucket_key(template)}"
                )
        self.trial = trial
        self.out_dir = out_dir
        self.queue: list[tuple[int, TrialConfig]] = list(items)
        self.results: dict[int, TrialResult] = {}
        self._save_checkpoint = save_checkpoint
        self._verbose = verbose
        self._host_syncs = 0
        self._is_writer = trial.is_writer_process

        self.model = VAE(
            hidden_dim=template.hidden_dim, latent_dim=template.latent_dim
        )
        self.fused = template.fused_steps
        self.batch_size = template.batch_size
        self._train_name = train_data.name
        self._train_synthetic = train_data.synthetic

        k = min(len(self.queue), max_lanes)
        first = [self.queue.pop(0) for _ in range(k)]
        # Per-lane host bookkeeping; None = lane retired and unfillable.
        self.lanes: list[Optional[dict]] = [
            self._fresh_lane(i, cfg) for i, cfg in first
        ]
        self.data = StackedTrialDataIterator(
            train_data, trial, self.batch_size,
            seeds=[lane["cfg"].seed for lane in self.lanes],
        )
        self.test_iter = (
            EvalDataIterator(test_data, trial, self.batch_size)
            if test_data is not None and len(test_data) > 0
            else None
        )
        step_kw = dict(remat=template.remat, grad_accum=template.grad_accum)
        self.sstep = make_stacked_train_step(trial, self.model, **step_kw)
        self.smulti = (
            make_stacked_multi_step(trial, self.model, **step_kw)
            if self.fused > 1
            else None
        )
        self.seval = (
            make_stacked_eval_step(trial, self.model)
            if self.test_iter is not None
            else None
        )
        self.read_lane, self.write_lane = make_lane_ops(trial)
        self.state = create_stacked_train_state(
            trial, self.model, [lane["cfg"].seed for lane in self.lanes]
        )
        self._refresh_lane_arrays()

    def _fresh_lane(self, idx: int, cfg: TrialConfig) -> dict:
        return {
            "idx": idx,
            "cfg": cfg,
            "epochs_done": 0,
            "history": [],
            "steps": 0,
            "t0": time.time(),
            "syncs0": self._host_syncs,
        }

    def _refresh_lane_arrays(self) -> None:
        """Rebuild the per-dispatch (K,) arrays after fill/retire/refill.
        Retired lanes keep placeholder hypers under a 0.0 active mask —
        the compiled program never changes shape."""
        def per_lane(fn, default):
            return [
                fn(lane["cfg"]) if lane is not None else default
                for lane in self.lanes
            ]

        self.hypers = TrialHypers.stack(
            per_lane(lambda c: c.lr, 1e-3),
            per_lane(lambda c: c.beta, 1.0),
            active=per_lane(lambda c: 1.0, 0.0),
        )
        self.base_rngs = jnp.stack(
            [
                jax.random.key((lane["cfg"].seed if lane else 0) + 1)
                for lane in self.lanes
            ]
        )

    def _lane_steps(self):
        return jnp.asarray(
            [lane["steps"] if lane else 0 for lane in self.lanes], jnp.int32
        )

    def _log(self, *args):
        if self._verbose:
            log0(*args, trial=self.trial)

    def _bump_steps(self, n: int) -> None:
        for lane in self.lanes:
            if lane is not None:
                lane["steps"] += n

    def _retire(self, k: int) -> None:
        """Capture lane k's result + checkpoint, then refill or mask."""
        lane = self.lanes[k]
        cfg: TrialConfig = lane["cfg"]
        lane_out_dir = os.path.join(self.out_dir, f"trial-{cfg.trial_id}")
        result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=self.trial.group_id,
            config=cfg,
            history=list(lane["history"]),
            out_dir=lane_out_dir,
            dataset=self._train_name,
            dataset_synthetic=self._train_synthetic,
            stacked=True,
        )
        last = lane["history"][-1]
        result.final_train_loss = last["avg_train_loss"]
        result.final_test_loss = last.get("test_loss", float("nan"))
        result.steps = lane["steps"]
        result.wall_s = time.time() - lane["t0"]
        result.host_syncs = self._host_syncs - lane["syncs0"]

        # Lane slice out of the stacked state: a compiled dynamic-index
        # read (traced k — every retirement reuses one executable).
        lane_state = self.read_lane(self.state, np.int32(k))
        if self._is_writer:
            if self._save_checkpoint:
                host_state = jax.device_get(lane_state)
                ckpt = os.path.join(lane_out_dir, "state.msgpack")
                save_state(
                    host_state,
                    ckpt,
                    metadata={
                        **asdict(cfg),
                        "completed_epochs": lane["epochs_done"],
                        "step": int(host_state.step),
                        "history": list(lane["history"]),
                    },
                )
                result.checkpoint = ckpt
            os.makedirs(lane_out_dir, exist_ok=True)
            with open(os.path.join(lane_out_dir, "metrics.json"), "w") as f:
                json.dump(
                    {
                        "trial_id": result.trial_id,
                        "group_id": result.group_id,
                        "config": asdict(cfg),
                        "dataset": result.dataset,
                        "dataset_synthetic": result.dataset_synthetic,
                        "history": result.history,
                        "wall_s": result.wall_s,
                        "steps": result.steps,
                        "stacked": True,
                    },
                    f,
                    indent=2,
                )
        self.results[lane["idx"]] = result
        self._log(
            f"Trial {cfg.trial_id} done (stacked lane {k}). "
            f"time: {result.wall_s:f}"
        )

        if self.queue:
            idx, nxt = self.queue.pop(0)
            self.lanes[k] = self._fresh_lane(idx, nxt)
            self.state = self.write_lane(
                self.state,
                self.trial.device_put(build_lane_state(self.model, nxt.seed)),
                np.int32(k),
            )
            self.data.set_lane(k, nxt.seed)
            self._log(
                f"Trial {nxt.trial_id} refilled into stacked lane {k} "
                "(no recompilation)"
            )
        else:
            self.lanes[k] = None  # masked out by active=0.0
        self._refresh_lane_arrays()

    def unfinished(self) -> list[tuple[int, TrialConfig]]:
        """Config items not yet completed (failure-isolation support)."""
        live = [
            (lane["idx"], lane["cfg"])
            for lane in self.lanes
            if lane is not None and lane["idx"] not in self.results
        ]
        return live + list(self.queue)

    def run(self) -> Iterator[None]:
        n_per_epoch = self.data.samples_per_epoch
        while any(lane is not None for lane in self.lanes):
            round_sum_dev = None  # (K,) on-device

            def add(dev_sums):
                nonlocal round_sum_dev
                round_sum_dev = (
                    dev_sums
                    if round_sum_dev is None
                    else round_sum_dev + dev_sums
                )

            if self.smulti is None:
                for batch in self.data.round_batches():
                    self.state, m = self.sstep(
                        self.state, self.hypers, batch,
                        self.base_rngs, self._lane_steps(),
                    )
                    self._bump_steps(1)
                    add(m["loss_sum"])
                    yield
            else:
                for start, chunk in self.data.round_chunks(self.fused):
                    s = chunk.shape[0]
                    if s == self.fused:
                        self.state, m = self.smulti(
                            self.state, self.hypers, chunk,
                            self.base_rngs, self._lane_steps(),
                        )
                        self._bump_steps(s)
                        add(m["loss_sum"].sum(axis=0))
                    else:
                        # Tail shorter than the compiled chunk: per-step
                        # stacked dispatches (no extra compilation).
                        for j in range(s):
                            self.state, m = self.sstep(
                                self.state, self.hypers, chunk[j],
                                self.base_rngs, self._lane_steps(),
                            )
                            self._bump_steps(1)
                            add(m["loss_sum"])
                    yield

            # One fetch for every lane's epoch average (O(1)-syncs rule:
            # the bucket pays per-round what one trial used to pay).
            self._host_syncs += 1
            train_sums = np.asarray(round_sum_dev)

            test_sums = None
            if self.test_iter is not None:
                test_dev = None
                for tbatch, tweights in self.test_iter.batches():
                    out = self.seval(self.state, self.hypers, tbatch, tweights)
                    test_dev = (
                        out["loss_sum"]
                        if test_dev is None
                        else test_dev + out["loss_sum"]
                    )
                    yield
                self._host_syncs += 1
                test_sums = np.asarray(test_dev)

            retiring = []
            for k, lane in enumerate(self.lanes):
                if lane is None:
                    continue
                lane["epochs_done"] += 1
                avg = float(train_sums[k]) / n_per_epoch
                record = {"epoch": lane["epochs_done"], "avg_train_loss": avg}
                self._log(
                    "Trial {} ====> Epoch: {} Average loss: {:.4f}".format(
                        lane["cfg"].trial_id, lane["epochs_done"], avg
                    )
                )
                if test_sums is not None:
                    t = float(test_sums[k]) / self.test_iter.num_rows
                    record["test_loss"] = t
                    self._log(
                        "Trial {} ====> Test set loss: {:.4f}".format(
                            lane["cfg"].trial_id, t
                        )
                    )
                lane["history"].append(record)
                if lane["epochs_done"] >= lane["cfg"].epochs:
                    retiring.append(k)
            for k in retiring:
                self._retire(k)
                yield
        jax.block_until_ready(self.state.params)


def run_hpo(
    configs: Sequence[TrialConfig],
    train_data: Dataset,
    test_data: Optional[Dataset] = None,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    num_groups: Optional[int] = None,
    out_dir: str = "results",
    shard_across_trials: bool = False,
    save_images: bool = True,
    save_checkpoints: bool = True,
    verbose: bool = True,
    model_builder=None,
    model_parallel: int = 1,
    param_shardings_builder=None,
    resilient: bool = False,
    resume: bool = False,
    profile_dir: Optional[str] = None,
    stack_trials: bool = False,
    stack_max_lanes: int = 8,
) -> list[TrialResult]:
    """Run the configs over disjoint submeshes, concurrently, with no
    cross-trial synchronization.

    ``groups`` defaults to ``setup_groups(num_groups or len(configs))``.
    **More configs than groups is legal**: excess configs queue, and a
    submesh picks up its next trial the moment its current one finishes
    (greedy in single-controller mode; in multi-controller SPMD the
    assignment is the deterministic least-predicted-load schedule of
    :func:`balanced_assignment` — every process must make identical
    scheduling decisions without communicating, and trial durations are
    predictable from the configs). Trials whose submesh has no local
    devices are skipped on this process (multi-controller membership,
    ``vae-hpo.py:200-202``).

    ``model_builder(cfg)`` swaps the model family (e.g. ``ConvVAE`` for
    the β-VAE CIFAR config) while reusing all scaffolding; default is
    the flagship MLP VAE.

    ``model_parallel=m`` carves each trial's submesh 2-D (data × model),
    and ``param_shardings_builder(trial, model)`` maps a trial to its
    weight shardings (e.g. ``models.vae.vae_tp_shardings(trial)`` for
    Megatron TP, ``models.moe_vae.moe_vae_ep_shardings`` for expert
    parallelism, ``parallel.fsdp.fsdp_param_shardings`` for ZeRO-style
    state sharding) — every train/eval/sample step then pins that
    layout. Within-trial model sharding composed with trial parallelism
    from one driver call; the reference is DP-only (SURVEY.md §2c).

    ``resilient=True`` isolates failures: a trial raising marks its
    result ``status="failed"`` (exception text in ``.error``), frees the
    submesh, and the sweep continues. Default re-raises (honest errors,
    SURVEY.md Q8). Works multi-controller too: deterministic failures
    resolve identically on every owner process by SPMD determinism, and
    writer-only host-I/O failures are agreed at setup/epoch boundaries
    through a submesh-scoped health reduction — one trial's death frees
    its submesh on every owning process with no world barrier (contrast
    the reference, where a failed rank hangs the world's collectives).

    ``resume=True`` restores each trial from its per-epoch checkpoint
    under ``{out_dir}/trial-{id}/`` (skipping fully-trained trials), so
    an interrupted sweep re-run completes only the remaining work.

    ``profile_dir`` wraps the whole sweep in a JAX profiler trace
    (TensorBoard/Perfetto-loadable, device timelines included on TPU) —
    the tool for confirming submeshes stay busy and finding host-side
    dispatch contention (SURVEY.md §7 "hard parts").

    ``stack_trials=True`` enables the trial-stacking execution mode
    (docs/STACKING.md): when trials outnumber groups, configs sharing a
    shape bucket (:func:`stack_bucket_key` — same architecture and
    batch size, any lr/beta/seed/epochs) run K-at-a-time on ONE submesh
    through one vmapped program (``train.steps.make_stacked_*``), with
    finished trials retired and refilled in place without recompiling.
    Falls back to the classic one-trial-per-group path when there is
    nothing to stack (too few configs, or unstackable knobs). At most
    ``stack_max_lanes`` trials share one program. Single-controller
    only, default model family only; the driver raises on contradictory
    settings (``resume``, ``shard_across_trials``, custom
    ``model_builder`` / weight sharding) rather than silently running a
    different sweep; ``save_images`` is ignored for stacked buckets
    (no reconstruction/sample grids — run image trials unstacked).

    Returns results for locally-run trials, in config order.
    """
    if profile_dir is not None:
        from multidisttorch_tpu.utils.profiling import profile_trace

        trace_ctx = profile_trace(profile_dir)
    else:
        import contextlib

        trace_ctx = contextlib.nullcontext()
    with trace_ctx:
        return _run_hpo_body(
            configs,
            train_data,
            test_data,
            groups=groups,
            num_groups=num_groups,
            out_dir=out_dir,
            shard_across_trials=shard_across_trials,
            save_images=save_images,
            save_checkpoints=save_checkpoints,
            verbose=verbose,
            model_builder=model_builder,
            model_parallel=model_parallel,
            param_shardings_builder=param_shardings_builder,
            resilient=resilient,
            resume=resume,
            stack_trials=stack_trials,
            stack_max_lanes=stack_max_lanes,
        )


def predicted_cost(cfg: TrialConfig, train_rows: int) -> int:
    """Relative duration estimate for one trial: optimizer steps to run.

    ``epochs`` is the reference's only duration knob (``vae-hpo.py:202``)
    and ``batch_size`` sets steps per epoch; both are known to every
    process before any trial starts, which is what lets the
    multi-controller scheduler balance load without communicating.
    """
    steps_per_epoch = max(1, train_rows // max(1, cfg.batch_size))
    return cfg.epochs * steps_per_epoch


def balanced_assignment(costs: Sequence[int], num_groups: int) -> list[int]:
    """Deterministic least-loaded assignment: config i → the group whose
    accumulated predicted cost is smallest (ties → lowest group index).

    Pure function of (costs, num_groups), so every process computes the
    identical schedule — the same no-communication constraint that
    forced the previous static round-robin. Least-loaded usually beats
    round-robin when epoch counts differ (costs [4,1,1,1] over 2 groups:
    round-robin loads (5,2), this gives (4,3)) but, like any online
    greedy rule, is not universally optimal (costs [2,1,1,2] favor
    round-robin); it never needs cost information round-robin lacks, and
    both are deterministic.
    """
    loads = [0] * num_groups
    out = []
    for c in costs:
        g = min(range(num_groups), key=lambda j: (loads[j], j))
        loads[g] += c
        out.append(g)
    return out


def _run_hpo_body(
    configs,
    train_data,
    test_data,
    *,
    groups,
    num_groups,
    out_dir,
    shard_across_trials,
    save_images,
    save_checkpoints,
    verbose,
    model_builder,
    model_parallel,
    param_shardings_builder,
    resilient,
    resume,
    stack_trials=False,
    stack_max_lanes=8,
) -> list[TrialResult]:
    if groups is None:
        groups = setup_groups(
            num_groups if num_groups is not None else len(configs),
            model_parallel=model_parallel,
        )
    elif model_parallel != 1:
        raise ValueError(
            "model_parallel applies only when the driver carves the "
            "groups; carve your own with setup_groups(..., "
            "model_parallel=m) when passing groups="
        )
    if len(configs) < len(groups):
        raise ValueError(
            f"{len(configs)} configs but {len(groups)} device groups "
            "(fewer configs than groups would idle submeshes; carve "
            "fewer groups instead)"
        )
    # Multi-host failure isolation: failures must resolve identically on
    # every process owning a trial's submesh, or one process frees the
    # group while peers keep stepping it (desynchronized collectives —
    # the reference's failure mode is worse still: a dead rank hangs the
    # world, SURVEY.md §5). Two mechanisms, by failure class:
    #  - Deterministic failures (bad config, model build, NaN guards,
    #    data exhaustion): SPMD determinism raises them at the same
    #    dispatch point on every owner — identical local handling IS the
    #    agreement.
    #  - Writer-only host-I/O failures (image/checkpoint/metrics
    #    writes): deferred by _TrialRun._guard and agreed at setup /
    #    epoch boundaries via a submesh-scoped health reduction
    #    (collectives.group_all_ok) — no world barrier, unrelated trials
    #    unaffected.
    # Out of scope (documented): asymmetric failures *inside* the
    # dispatch stream (host OOM, device loss mid-epoch) — those desync
    # the submesh's program sequence itself and need runtime-level
    # preemption, which no SPMD framework recovers from at this layer.
    def needs_agreement(g: TrialMesh) -> bool:
        return resilient and jax.process_count() > 1 and g.spans_processes

    def make_run(trial: TrialMesh, cfg: TrialConfig) -> _TrialRun:
        return _TrialRun(
            trial,
            cfg,
            train_data,
            test_data,
            out_dir,
            shard_across_trials=shard_across_trials,
            # Shard by submesh, not by config: with elastic scheduling
            # (more configs than groups) group_id::len(groups) is still a
            # valid partition of the dataset, config-count-based sharding
            # would leave rows unassigned.
            num_trials=len(groups),
            save_images=save_images,
            save_checkpoint=save_checkpoints,
            verbose=verbose,
            model_builder=model_builder,
            param_shardings_builder=param_shardings_builder,
            resume=resume,
            agree_failures=needs_agreement(trial),
        )

    # Queue configs per group. Single-controller: one shared queue,
    # greedy — whichever submesh frees first takes the next config
    # (optimal when trials have unequal epoch counts). Multi-controller:
    # every process must make identical assignments WITHOUT
    # communicating, so the schedule is computed deterministically from
    # shared state (the configs themselves): each config goes to the
    # group with the least accumulated predicted cost (epochs x steps
    # per epoch — the knobs that set trial duration, vae-hpo.py:202).
    # Typically better than round-robin under unequal epoch counts
    # (queues are sized to their trials' predicted lengths up front; see
    # balanced_assignment's docstring for the caveat) while remaining
    # process-independent.
    single = jax.process_count() == 1
    if stack_trials:
        # Trial stacking is single-controller, default-model-family
        # territory; contradictory settings fail loudly rather than
        # silently running a different sweep than asked for.
        if not single:
            raise ValueError(
                "stack_trials: stacking is single-controller only (the "
                "stacked state lives on one submesh; multi-controller "
                "lane scheduling would need cross-process agreement)"
            )
        if resume:
            raise ValueError(
                "stack_trials is incompatible with resume= (lane "
                "restore into a stacked bucket is not implemented; run "
                "the resume sweep unstacked)"
            )
        if shard_across_trials:
            raise ValueError(
                "stack_trials is incompatible with shard_across_trials "
                "(stacked lanes each see the full dataset)"
            )
        if model_builder is not None or param_shardings_builder is not None \
                or model_parallel != 1:
            raise ValueError(
                "stack_trials supports the default VAE family with "
                "replicated weights only (custom model_builder / "
                "param_shardings_builder / model_parallel cannot share "
                "one vmapped program)"
            )

    # Work items: ("single", [(i, cfg)]) or ("bucket", [(i, cfg), ...]).
    # Stacking applies only when trials outnumber groups — otherwise
    # every trial gets its own submesh and stacking would only serialize.
    def build_items() -> list[tuple[str, list[tuple[int, TrialConfig]]]]:
        indexed = list(enumerate(configs))
        if not (stack_trials and len(configs) > len(groups)):
            return [("single", [item]) for item in indexed]
        buckets: dict[tuple, list] = {}
        singles: list = []
        for item in indexed:
            if config_is_stackable(item[1]):
                buckets.setdefault(stack_bucket_key(item[1]), []).append(item)
            else:
                singles.append(item)
        items = []
        for members in buckets.values():
            if len(members) >= 2:
                items.append(("bucket", members))
            else:
                singles.extend(members)
        items.extend(("single", [m]) for m in singles)
        # Don't idle submeshes behind one mega-bucket: split the largest
        # bucket until there is at least one work item per group (or
        # nothing left to split).
        while len(items) < len(groups):
            big = max(
                (it for it in items if it[0] == "bucket" and len(it[1]) >= 4),
                key=lambda it: len(it[1]),
                default=None,
            )
            if big is None:
                break
            items.remove(big)
            half = len(big[1]) // 2
            items.append(("bucket", big[1][:half]))
            items.append(("bucket", big[1][half:]))
        # Deterministic order: by first member's config index.
        items.sort(key=lambda it: it[1][0][0])
        return items

    shared = build_items()
    per_group: dict[int, list] = {g.group_id: [] for g in groups}
    if not single:
        assignment = balanced_assignment(
            [predicted_cost(cfg, len(train_data)) for cfg in configs],
            len(groups),
        )
        for i, cfg in enumerate(configs):
            per_group[groups[assignment[i]].group_id].append(
                ("single", [(i, cfg)])
            )
    queue_of = (
        (lambda g: shared) if single else (lambda g: per_group[g.group_id])
    )

    local_groups = [g for g in groups if g.is_local_member]
    results: dict[int, TrialResult] = {}
    # group -> (kind, config_index_or_None, run, generator) in flight
    active: dict[int, tuple] = {}

    def fail_items(g, members, error_text) -> None:
        for i, cfg in members:
            results[i] = TrialResult(
                trial_id=cfg.trial_id,
                group_id=g.group_id,
                config=cfg,
                status="failed",
                error=error_text,
            )

    def start_next(g: TrialMesh) -> bool:
        q = queue_of(g)
        while q:
            kind, members = q.pop(0)
            if kind == "bucket":
                try:
                    brun = _StackedBucketRun(
                        g, members, train_data, test_data, out_dir,
                        max_lanes=stack_max_lanes,
                        save_checkpoint=save_checkpoints,
                        verbose=verbose,
                    )
                except Exception as e:  # noqa: BLE001 — setup isolation
                    error_text = f"{type(e).__name__}: {e}"
                    fail_items(g, members, error_text)
                    if not resilient:
                        raise
                    log0(
                        f"Stacked bucket of {len(members)} trials FAILED "
                        f"at setup ({error_text}); sweep continues",
                        trial=g,
                    )
                    continue
                active[g.group_id] = ("bucket", None, brun, brun.run())
                return True
            i, cfg = members[0]
            err: Optional[BaseException] = None
            run: Optional[_TrialRun] = None
            try:
                run = make_run(g, cfg)
            except Exception as e:  # noqa: BLE001 — setup failure isolation
                err = e
            if needs_agreement(g):
                # Setup agreement: owners of a spanning submesh must all
                # start stepping or all skip — an asymmetric setup
                # failure (e.g. one host's data path) would otherwise
                # leave peers dispatching a trial that never runs here.
                from multidisttorch_tpu.parallel.collectives import (
                    group_all_ok,
                )

                ok = group_all_ok(g, err is None)
            else:
                ok = err is None
            if not ok:
                error_text = (
                    f"{type(err).__name__}: {err}"
                    if err is not None
                    else "setup failed on a peer owner process"
                )
                results[i] = TrialResult(
                    trial_id=cfg.trial_id,
                    group_id=g.group_id,
                    config=cfg,
                    status="failed",
                    error=error_text,
                )
                if not resilient:
                    if err is not None:
                        raise err
                    raise RuntimeError(error_text)
                log0(
                    f"Trial {cfg.trial_id} FAILED at setup "
                    f"({error_text}); sweep continues",
                    trial=g,
                )
                continue
            active[g.group_id] = ("single", i, run, run.run())
            return True
        return False

    for g in local_groups:
        start_next(g)

    # Cooperative round-robin: one async step dispatch per trial (or
    # stacked bucket — K trials per dispatch) per cycle. A finished (or
    # failed) item frees its submesh, which immediately starts its next
    # queued work — the sweep's wall-clock is bounded by real work,
    # never by barriers (Q3 fixed).
    while active:
        for g in local_groups:
            if g.group_id not in active:
                continue
            kind, i, run, gen = active[g.group_id]
            try:
                next(gen)
            except StopIteration:
                if kind == "bucket":
                    results.update(run.results)
                else:
                    results[i] = run.result
                del active[g.group_id]
                start_next(g)
            except Exception as e:  # noqa: BLE001 — failure isolation
                error_text = f"{type(e).__name__}: {e}"
                if kind == "bucket":
                    # Lanes already retired keep their completed
                    # results; everything in flight or queued in the
                    # bucket fails together (they shared the broken
                    # program/state).
                    results.update(run.results)
                    fail_items(g, run.unfinished(), error_text)
                    del active[g.group_id]
                    if not resilient:
                        raise
                    log0(
                        f"Stacked bucket FAILED ({error_text}); "
                        "submesh freed, sweep continues",
                        trial=g,
                    )
                    start_next(g)
                    continue
                run.result.status = "failed"
                run.result.error = error_text
                results[i] = run.result
                del active[g.group_id]
                # Drain any in-flight checkpoint write before freeing the
                # submesh: run_hpo must not return while a writer thread
                # is still mutating result.checkpoint, and a failed write
                # must surface in the error, not vanish with the thread.
                try:
                    run._join_ckpt()
                except Exception as ce:  # noqa: BLE001
                    run.result.error += f"; also: {type(ce).__name__}: {ce}"
                if not resilient:
                    raise
                log0(
                    f"Trial {run.cfg.trial_id} FAILED ({run.result.error}); "
                    "submesh freed, sweep continues",
                    trial=g,
                )
                start_next(g)
    return [results[i] for i in sorted(results)]
