"""Host-side HPO driver: N concurrent trials on N disjoint submeshes.

Rebuild of the reference's trial dispatch (``/root/reference/
vae-hpo.py:177-202``), where each process loops over all groups, finds
the one it belongs to, and runs a DDP trial whose only hyperparameter is
``epochs + group_id``. Redesigned per SURVEY.md §7:

- **Real per-trial configs** (:class:`TrialConfig`: lr, β, epochs,
  batch size, seed, model dims — generalizing quirk Q7).
- **Cooperative round-robin dispatch**: all trials' jit steps are
  enqueued from one host loop; JAX's async dispatch keeps every submesh
  busy while the host cycles. A fast trial finishes and frees its
  submesh immediately — **no cross-trial barrier anywhere** (fixes Q3,
  where the reference's world-scoped barriers serialize the sweep on the
  slowest trial).
- **Per-trial output dirs** ``{out_dir}/trial-{id}/`` (fixes Q4's
  ``results-{rank}`` collision where group 0 and 1 overwrite each
  other's PNGs).
- In multi-controller SPMD each process runs only the trials whose
  submesh intersects its local devices (``TrialMesh.is_local_member``) —
  the same membership contract as the reference's
  ``dist.get_rank(group) >= 0`` (``vae-hpo.py:201``).
- **Elastic scheduling**: more configs than submeshes is legal — the
  reference hard-binds one trial per group forever (``vae-hpo.py:
  200-202``); here freed submeshes immediately pick up the next queued
  config (greedy single-controller; deterministic least-predicted-load
  assignment multi-controller — :func:`balanced_assignment` — where
  every process must schedule identically without communicating).
- **Failure isolation** (``resilient=True``): one trial's exception
  marks that trial failed and frees its submesh; the rest of the sweep
  proceeds. The reference has no failure handling at all — a dead rank
  hangs every world barrier (SURVEY.md §5).
- **Checkpoint/resume** (``resume=True``): per-epoch checkpoints; a
  re-run restores each trial at its last completed epoch (or skips it
  entirely if done). The reference persists nothing but PNGs.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field, asdict
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import (
    EvalDataIterator,
    StackedTrialDataIterator,
    TrialDataIterator,
)
from multidisttorch_tpu.hpo.ledger import SweepLedger, config_hash
from multidisttorch_tpu.hpo.supervision import (
    DIVERGENCE,
    FATAL,
    INFRA,
    PREEMPTION,
    RetryPolicy,
    UnretryableError,
    classify_failure,
)
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import TrialMesh, setup_groups
from multidisttorch_tpu.train.checkpoint import (
    RAM_SNAPSHOT,
    default_format,
    restore_latest_valid,
    restore_state,
    save_state,
    snapshot_cache,
)
from multidisttorch_tpu.train.guards import DivergenceError, check_finite
from multidisttorch_tpu.train.steps import (
    TrialHypers,
    build_lane_state,
    create_stacked_train_state,
    create_train_state,
    make_eval_step,
    make_lane_ops,
    make_multi_step,
    make_sample_step,
    make_stacked_eval_step,
    make_stacked_multi_step,
    make_stacked_train_step,
    make_train_step,
    state_shardings,
    wrap_step_with_hooks,
)
from multidisttorch_tpu.telemetry import device as tele_device
from multidisttorch_tpu.telemetry.anomaly import get_monitor
from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.telemetry.metrics import get_registry
from multidisttorch_tpu.utils.imaging import save_image_grid
from multidisttorch_tpu.utils.logging import log0, log0_enabled


@dataclass(frozen=True)
class TrialConfig:
    """One trial's hyperparameters (the reference's single knob was
    ``epochs + group_id``, ``vae-hpo.py:202``)."""

    trial_id: int
    epochs: int = 3
    batch_size: int = 128
    lr: float = 1e-3  # reference Adam lr, vae-hpo.py:131
    beta: float = 1.0
    seed: int = 0
    hidden_dim: int = 400
    latent_dim: int = 20
    log_interval: int = 10  # reference train log cadence, vae-hpo.py:61
    # Train steps fused into one device dispatch (make_multi_step's
    # lax.scan). 1 = the reference's one-dispatch-per-batch loop shape;
    # >1 amortizes host dispatch, the dominant cost at this model size.
    # Changes the per-step RNG stream (keys are split per chunk instead
    # of folded per step), so it participates in the resume
    # config-match check like any other hyperparameter.
    fused_steps: int = 1
    # Reference-parity eval semantics: the reference's test() runs the
    # full sampled forward (z drawn from the posterior —
    # /root/reference/vae-hpo.py:101-105 calling model(data), :42-45).
    # Default False = posterior-mean eval (deterministic, strictly
    # tighter bound); True reproduces the reference's sampled test-loss
    # metric for apples-to-apples quality comparison.
    eval_sampled: bool = False
    # Rematerialize activations in the backward pass (jax.checkpoint):
    # trade recompute FLOPs for HBM when the model or the fused-steps
    # scan outgrows device memory. Numerically identical training.
    remat: bool = False
    # Gradient accumulation: split each batch into this many equal
    # microbatches, accumulate grads in-step, one optimizer update —
    # the effective batch size can exceed HBM. Composes with remat.
    grad_accum: int = 1
    # Per-trial dataset reference (docs/DATA.md): "" = the sweep's
    # shared train_data (the pre-ref behavior, byte-compatible). A
    # non-empty spec ("synthetic-mnist?rows=512&seed=3", "file:...",
    # "cas:<sha256>") resolves through data/store.resolve_dataset — the
    # service resolves it against its content-addressed cache at
    # admission, run_hpo at sweep entry. It participates in the config
    # hash and the resume config-match like any other hyperparameter
    # (weights trained on one dataset must not silently resume under
    # another). Trials with DIFFERENT datasets of the same shape class
    # still co-pack into one stacked bucket (heterogeneous lanes).
    dataset: str = ""
    # ZeRO-style sharded weight update (docs/PARALLEL.md): partition
    # the Adam moments over the trial submesh's data axis — GSPMD
    # reduce-scatters the gradient into the owned shard's update and
    # all-gathers the fresh params (arXiv 2004.13336). Params stay
    # replicated, so the forward/backward is the plain DDP program;
    # per-device optimizer memory drops to ~1/n_data of replicated.
    # Runs the classic (unstacked) path; no-op on 1-device submeshes.
    zero_update: bool = False
    # Cross-submesh MPMD pipeline parallelism (docs/PARALLEL.md): >1
    # makes this trial a VECTOR of slice requests — each stage owns its
    # own submesh and programs, driven on a GPipe microbatch schedule
    # with device_put transfers between stages. `grad_accum` doubles as
    # the microbatch count M (the schedule IS gradient accumulation;
    # the single-mesh grad_accum=M step is the parity reference).
    # Placed by the sweep service (all-or-nothing multi-block) or run
    # directly via hpo.pipeline_run.run_pipeline_trial; run_hpo's
    # equal-groups carve cannot host it and rejects such configs.
    pipeline_stages: int = 1


@dataclass
class TrialResult:
    trial_id: int
    group_id: int
    config: TrialConfig
    history: list = field(default_factory=list)  # per-epoch dicts
    final_train_loss: float = float("nan")  # per-sample avg, last epoch
    final_test_loss: float = float("nan")
    wall_s: float = 0.0
    steps: int = 0
    out_dir: str = ""
    checkpoint: str = ""
    # "completed" | "failed" | "resumed_complete" | "diverged"
    # ("diverged" = non-finite loss: a terminal RESULT of the config,
    # recorded and never retried — see hpo/supervision.py)
    status: str = "completed"
    error: str = ""
    # Which attempt produced this result (1 = first try; >1 means the
    # supervisor retried infra faults — the ledger holds the history).
    attempt: int = 1
    # Optimizer step this attempt resumed from (0 = scratch): the
    # difference steps - resumed_from_step is the attempt's EXECUTED
    # work — what the chaos bench's goodput accounting sums.
    resumed_from_step: int = 0
    # Data provenance: which dataset the trial actually trained on, and
    # whether it was the synthetic zero-egress stand-in. The reference
    # always trains on real MNIST (vae-hpo.py:133-144); this repo can
    # silently degrade to synthetic (data/datasets.py), so a trial's
    # recorded metrics must say which world they came from.
    dataset: str = ""
    dataset_synthetic: bool = False
    # Host↔device round-trips the trial actually paid for metric
    # fetches (the O(1)-syncs discipline: ≤ log lines + 2 per epoch;
    # regression-tested in tests/test_hpo.py). For a stacked trial this
    # counts its whole bucket's fetches during the trial's lifetime —
    # the bucket pays them once for ALL lanes.
    host_syncs: int = 0
    # True when the trial ran as one lane of a stacked bucket
    # (docs/STACKING.md): K same-shape trials vmapped through one
    # compiled program on one submesh.
    stacked: bool = False
    # Analytic per-device optimizer-state footprint (docs/PARALLEL.md
    # memory books): what ONE device holds for this trial's Adam
    # moments, from each leaf's concrete sharding — the ZeRO win is
    # visible here without memory_stats() (CPU included). For a
    # stacked lane this is the lane's share of the bucket's stacked
    # state; for a pipelined trial, the sum over its stages.
    optimizer_state_bytes: int = 0


def config_mismatch_vs_meta(cfg: TrialConfig, meta: dict) -> dict:
    """Fields (epochs excluded — extending epochs is the legitimate
    resume use) where a checkpoint's recorded config differs from
    ``cfg``; empty dict = match. Fields absent from an older
    checkpoint's sidecar compare against their TrialConfig default —
    a checkpoint written before a field existed was trained under its
    default. The ONE copy of the resume config-match rule: the classic
    ``_TrialRun`` and the pipelined runner's per-stage scan restore
    both gate on it."""
    from dataclasses import MISSING, fields as dc_fields

    field_defaults = {
        f.name: f.default
        for f in dc_fields(TrialConfig)
        if f.default is not MISSING
    }
    saved = {
        k: meta.get(k, field_defaults.get(k))
        for k in asdict(cfg)
        if k != "epochs" and (k in meta or k in field_defaults)
    }
    current = {k: v for k, v in asdict(cfg).items() if k != "epochs"}
    if not saved or saved == current:
        return {}
    return {
        k: (saved.get(k), current[k])
        for k in current
        if saved.get(k) != current[k]
    }


def _result_summary(result: TrialResult) -> dict:
    """The ledger's attempt_end payload: enough to reconstruct a
    TrialResult when a restarted sweep skips the trial entirely."""
    return {
        "group_id": result.group_id,
        "history": list(result.history),
        "final_train_loss": result.final_train_loss,
        "final_test_loss": result.final_test_loss,
        "wall_s": result.wall_s,
        "steps": result.steps,
        "out_dir": result.out_dir,
        "checkpoint": result.checkpoint,
        "dataset": result.dataset,
        "dataset_synthetic": result.dataset_synthetic,
        "stacked": result.stacked,
        "resumed_from_step": result.resumed_from_step,
        "optimizer_state_bytes": result.optimizer_state_bytes,
    }


def _result_from_summary(
    cfg: TrialConfig, rec: dict, status: str
) -> TrialResult:
    """Rebuild a TrialResult from a ledger attempt_end record (the
    restarted-sweep skip path — no state is touched)."""
    s = rec.get("summary") or {}
    return TrialResult(
        trial_id=cfg.trial_id,
        group_id=int(s.get("group_id", -1)),
        config=cfg,
        history=list(s.get("history", [])),
        final_train_loss=float(s.get("final_train_loss", float("nan"))),
        final_test_loss=float(s.get("final_test_loss", float("nan"))),
        wall_s=float(s.get("wall_s", 0.0)),
        steps=int(s.get("steps", 0)),
        out_dir=s.get("out_dir", ""),
        checkpoint=s.get("checkpoint", ""),
        status=status,
        error=rec.get("error", ""),
        dataset=s.get("dataset", ""),
        dataset_synthetic=bool(s.get("dataset_synthetic", False)),
        stacked=bool(s.get("stacked", False)),
        attempt=int(rec.get("attempt", 1)),
        resumed_from_step=int(s.get("resumed_from_step", 0)),
        optimizer_state_bytes=int(s.get("optimizer_state_bytes", 0)),
    )


class _TrialRun:
    """One trial's full lifecycle as a cooperative generator.

    Each ``next()`` dispatches one unit of training work async — a
    single train step, or a chunk of ``cfg.fused_steps`` scan-fused
    steps — and returns; host-device syncs happen only at the
    reference's logging cadence and at epoch boundaries. The generator
    shape is what makes the no-barrier scheduling work: the driver
    interleaves ``next()`` across trials, so every submesh has work
    queued at all times.
    """

    def __init__(
        self,
        trial: TrialMesh,
        cfg: TrialConfig,
        train_data: Dataset,
        test_data: Optional[Dataset],
        out_dir: str,
        *,
        shard_across_trials: bool = False,
        num_trials: int = 1,
        save_images: bool = True,
        save_checkpoint: bool = True,
        verbose: bool = True,
        model_builder=None,
        param_shardings_builder=None,
        resume=False,  # False | True (strict) | "scan" (supervised)
        agree_failures: bool = False,
        agree_timeout_s: Optional[float] = None,
        wedge_timeout_s: Optional[float] = None,
        injector=None,  # faults.inject.FaultInjector | None
        ckpt_keep_last: int = 1,
        ckpt_format: Optional[str] = None,
        ram_restore: bool = False,
        attempt: int = 1,
    ):
        if cfg.fused_steps < 1:
            raise ValueError(
                f"fused_steps must be >= 1, got {cfg.fused_steps} "
                f"(trial {cfg.trial_id})"
            )
        if cfg.pipeline_stages != 1:
            raise ValueError(
                f"trial {cfg.trial_id} has pipeline_stages="
                f"{cfg.pipeline_stages}: an MPMD pipelined trial is a "
                "vector of submeshes and runs through "
                "hpo.pipeline_run._PipelineTrialRun (service placement "
                "or run_pipeline_trial), not _TrialRun"
            )
        self.trial = trial
        self.cfg = cfg
        self.out_dir = os.path.join(out_dir, f"trial-{cfg.trial_id}")
        self.result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=trial.group_id,
            config=cfg,
            out_dir=self.out_dir,
            dataset=train_data.name,
            dataset_synthetic=train_data.synthetic,
        )
        # Artifacts (images, checkpoints, metrics.json) are written by
        # exactly one process per group — on a shared filesystem,
        # every-owner-writes would race identical files (Q4's
        # multi-process half). Resume restores *state* on all owner
        # processes; only the writer re-reads sidecar metadata.
        self._is_writer = trial.is_writer_process
        # Uniform across owner processes (drives which programs are
        # compiled AND dispatched — dispatch gating must never be
        # writer-local on a process-spanning submesh, or SPMD execution
        # desynchronizes); the writer-gated flag below controls only
        # host-side fetch + file writes.
        self._images_requested = save_images
        self._save_images = save_images and self._is_writer
        self._save_checkpoint = save_checkpoint
        self._verbose = verbose
        self._test_data = test_data
        # Multi-host failure isolation (resilient sweeps on spanning
        # submeshes): writer-only host-I/O failures are deferred and
        # agreed at the epoch boundary via a submesh-scoped reduction
        # (collectives.group_all_ok), so every owner process kills the
        # trial identically instead of one process freeing the group
        # while peers keep stepping it.
        self._agree = agree_failures
        self._agree_timeout_s = agree_timeout_s
        # Wedge watchdog deadline for device-result fetches whose value
        # transits a cross-host collective (epoch/test loss, checkpoint
        # gather, completion drain): on a spanning submesh a peer that
        # stopped dispatching leaves these blocked forever — the
        # watchdog turns that into a named WedgedCollective within the
        # deadline (classified as preemption; exit-code contract in
        # docs/RESILIENCE.md). None/0 = unbounded; single-process and
        # non-spanning trials never pay the watchdog thread.
        self._wedge_timeout_s = wedge_timeout_s
        # This run's attempt number (1-based, ledger-monotonic): scopes
        # the cross-host restore agreement's sideband keys.
        self._attempt = attempt
        self._deferred_error: Optional[BaseException] = None
        self._host_syncs = 0
        # Fault-injection seams (None in production): chaos drills route
        # through the SAME dispatch/data/checkpoint paths real faults
        # take — see faults/inject.py for the hook contract.
        self._injector = injector
        self._ckpt_keep_last = ckpt_keep_last
        # Checkpoint data-plane format (docs/RESILIENCE.md "Checkpoint
        # format v2"): the driver writes v2 chunked manifests by
        # default (MDT_CKPT_FORMAT=v1 opts back into full-msgpack);
        # restore always sniffs per file, so a v1 history under a v2
        # primary resumes fine.
        self._ckpt_format = (
            ckpt_format if ckpt_format is not None else default_format()
        )
        # RAM-snapshot restore is an explicit opt-in (the service's
        # same-process re-place after a snapshot drain): supervised
        # retry drills outside the service keep pure disk semantics —
        # a chaos test that corrupts the on-disk history must observe
        # the scan-back degrade, not a warm cache.
        self._ram_restore = bool(ram_restore)
        self._last_ckpt_stats: dict = {}
        # Optimizer-step cursor mirrored as an attribute so the
        # injection hooks (closures built below, called from inside the
        # compiled-step wrappers) always see the current step.
        self._step_no = 0
        self._epoch_base_step = 0
        # Telemetry (all None when off — the zero-cost contract;
        # captured once so the hot loop pays one attribute read).
        # Step timings flow into the sweep-wide metrics registry under
        # this trial's series key; lifecycle events ride the bus; the
        # anomaly monitor watches step times and epoch losses; the
        # device books (cost analysis, memory watermarks) are recorded
        # through _device_seam at the same guarded sites.
        self._mreg = get_registry()
        self._mkey = f"trial-{cfg.trial_id}"
        self._amon = get_monitor()
        self._cost_done = False

        if model_builder is None:
            model = VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
        else:
            model = model_builder(cfg)
        tx = optax.adam(cfg.lr)
        self.model, self.tx = model, tx
        # Within-trial weight sharding (TP/EP/FSDP): the builder maps
        # (trial, model) -> a param-shardings pytree (e.g.
        # models.vae.vae_tp_shardings, models.moe_vae.moe_vae_ep_shardings);
        # the derived state shardings then pin every step's layout.
        param_sh = (
            param_shardings_builder(trial, model)
            if param_shardings_builder is not None
            else None
        )
        # AOT eligibility (docs/COMPILE.md): the program vocabulary
        # describes exactly the default model family with replicated
        # weights on a single controller — the same envelope as trial
        # stacking. Everything else keeps the plain jit paths.
        # MDT_AOT_ADMISSION=0 is the kill switch.
        aot_eligible = (
            model_builder is None
            and param_shardings_builder is None
            # The sharded-update variant pins different state shardings
            # into its programs — the registry's single-path keys don't
            # carry the mode, so a zero trial must never take (or
            # donate) a replicated twin's executable.
            and not cfg.zero_update
            and jax.process_count() == 1
            and os.environ.get("MDT_AOT_ADMISSION", "1") != "0"
        )
        self.state = None
        if aot_eligible:
            # The state-init program is itself part of the compile tax
            # (flax init traces+compiles per trial): take the farm's
            # executable if ready, else compile inline through the
            # registry — timed, attributed, and shared by every
            # same-bucket trial (lr twins included; init bakes no
            # hypers). Bit-identical to the eager path by construction
            # (elementwise RNG + zeros_like; regression-tested), and
            # any failure falls back to it.
            self.state = self._registry_init_state()
        if self.state is None:
            self.state = create_train_state(
                trial, model, tx, jax.random.key(cfg.seed),
                param_shardings=param_sh,
            )
        self._state_sh = (
            state_shardings(self.state) if param_sh is not None else None
        )
        # Sharded weight update (docs/PARALLEL.md): re-place the Adam
        # moments data-sharded and pin the layout into every step. The
        # forward/backward stays the replicated program; only the
        # update's reduce-scatter/all-gather schedule changes.
        if cfg.zero_update and trial.data_size > 1:
            if param_sh is not None:
                raise ValueError(
                    f"trial {cfg.trial_id}: zero_update composes with "
                    "weight sharding via parallel.fsdp."
                    "fsdp_compose_shardings, not via both knobs at once "
                    "(the param_shardings_builder already owns the "
                    "state layout)"
                )
            from multidisttorch_tpu.parallel.fsdp import place_zero_state

            self.state, self._state_sh = place_zero_state(trial, self.state)
        # Checkpointing a weight-sharded state: serialization needs the
        # whole array on the writer host. On a PROCESS-SPANNING submesh
        # the writer holds only its shards, so a gather-to-replicated
        # is DISPATCHED by every owner (uniform SPMD program — the same
        # rule as every other step); only the fetch stays writer-gated.
        # Single-controller sharded states (ZeRO, TP, FSDP) skip the
        # gather entirely under the v2 format — every shard is locally
        # addressable, the host fetch assembles them without a device
        # collective, and the manifest records the NamedSharding layout
        # the state trained under (the sharded-native save path).
        self._gather_state = (
            jax.jit(lambda s: s, out_shardings=trial.replicated_sharding)
            if self._state_sh is not None
            and (self._ckpt_format == "v1" or trial.spans_processes)
            else None
        )
        # Memory books (docs/PARALLEL.md): the analytic per-device
        # optimizer footprint from the placed state's CONCRETE
        # shardings — the ZeRO win is visible on every backend, no
        # memory_stats() needed.
        from multidisttorch_tpu.parallel.fsdp import optimizer_state_bytes

        _ob = optimizer_state_bytes(self.state)
        self.result.optimizer_state_bytes = _ob["per_device_bytes"]
        _bus = get_bus()
        if _bus is not None:
            _bus.emit(
                "optimizer_state",
                trial_id=cfg.trial_id,
                group_id=trial.group_id,
                per_device_bytes=_ob["per_device_bytes"],
                total_bytes=_ob["total_bytes"],
                zero_update=bool(cfg.zero_update),
            )
        self.train_step = make_train_step(
            trial, model, tx, beta=cfg.beta, remat=cfg.remat,
            grad_accum=cfg.grad_accum, shardings=self._state_sh,
        )
        self.multi_step = (
            make_multi_step(
                trial, model, tx, beta=cfg.beta, remat=cfg.remat,
                grad_accum=cfg.grad_accum, shardings=self._state_sh,
            )
            if cfg.fused_steps > 1
            else None
        )
        # Raw jit programs kept unwrapped for the AOT admission path
        # (compile/registry.py): a registry executable replaces the RAW
        # program, and the chaos hook-wrapping is re-applied around
        # whichever wins — hooks are pure host code either way.
        self._train_raw = self.train_step
        self._multi_raw = self.multi_step
        self.train_step = self._wrap_train(self.train_step)
        if self.multi_step is not None:
            self.multi_step = self._wrap_multi(self.multi_step)
        # AOT admission: "take the finished executable if ready, else
        # compile inline" (docs/COMPILE.md) — for the train programs,
        # resolved cooperatively in run() before the first dispatch.
        self._aot_keys: dict = {}
        self._admission = {"outcome": "jit", "wait_s": 0.0, "program": None}
        self._first_dispatched = False
        if aot_eligible:
            from multidisttorch_tpu.compile import programs as _cprog

            bucket = stack_bucket_key(cfg)
            self._aot_keys["train"] = _cprog.single_train_key(
                trial, cfg, bucket
            )
            if cfg.fused_steps > 1:
                self._aot_keys["multi"] = _cprog.single_multi_key(
                    trial, cfg, bucket
                )
        # Reconstructions are materialized (and all-gathered back to
        # replicated) only when images are wanted. Keyed on the uniform
        # save_images argument, NOT the per-process writer-gated flag:
        # all owner processes must compile the identical eval program.
        self.eval_step = make_eval_step(
            trial,
            model,
            beta=cfg.beta,
            with_recon=save_images,
            masked=True,
            sampled=cfg.eval_sampled,
            shardings=self._state_sh,
        )
        self.sample_step = make_sample_step(
            trial, model, shardings=self._state_sh
        )
        self.train_iter = TrialDataIterator(
            train_data,
            trial,
            cfg.batch_size,
            seed=cfg.seed,
            shard_across_trials=shard_across_trials,
            num_trials=num_trials,
            fault_hook=(
                None if injector is None else self._data_fault_hook
            ),
        )
        # Full-coverage eval (reference parity, vae-hpo.py:101-105): the
        # pad-and-mask iterator consumes every test row — including test
        # sets smaller than one batch, which round 1 silently skipped.
        self.test_iter = (
            EvalDataIterator(test_data, trial, cfg.batch_size)
            if test_data is not None and len(test_data) > 0
            else None
        )
        self._first_test_batch = None
        self._key = jax.random.key(cfg.seed + 1)

        # Resume: per-epoch checkpoints carry (state, completed_epochs,
        # history); restore at the last epoch boundary. Epoch data order
        # and step RNG are deterministic in (seed, epoch) / step number,
        # so a resumed run replays the exact remaining stream.
        self._ckpt_path = os.path.join(self.out_dir, "state.msgpack")
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_error: Optional[BaseException] = None
        self._start_epoch = 1
        if resume == "scan":
            # Supervised retry-with-resume: scan back past torn/corrupt
            # checkpoints to the newest VALID one whose recorded config
            # matches (train/checkpoint.py's CRC machinery); nothing
            # valid means retry from scratch. No strict errors here —
            # the supervisor's contract is "recover the most work
            # possible", not "diagnose for a human". On a spanning
            # submesh the choice is AGREED across owner processes
            # (min-over-hosts valid step) so one host's torn view of
            # the newest candidate cannot desynchronize SPMD.
            got = self._restore_scan()
            if got is not None:
                restored, meta, used = got
                done = int(meta.get("completed_epochs", 0))
                if done >= 1:
                    self.state = restored
                    self._start_epoch = done + 1
                    self._adopt_history(meta)
                    log0(
                        f"Trial {cfg.trial_id} retry resumes from epoch "
                        f"{done} checkpoint ({used})",
                        trial=trial,
                    )
        elif resume:
            meta_path = self._ckpt_path + ".json"
            if os.path.exists(self._ckpt_path) and os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                # Guard against resuming under silently-changed
                # hyperparameters: everything except the epoch target
                # (extending epochs is the legitimate resume use) must
                # match the checkpoint's saved config.
                diff = self._config_mismatch(meta)
                if diff:
                    raise UnretryableError(
                        f"resume: trial {cfg.trial_id} checkpoint at "
                        f"{self._ckpt_path} was written under different "
                        f"hyperparameters {diff} (saved vs current); "
                        "refusing to continue stale weights under a "
                        "changed config"
                    )
                done = int(meta.get("completed_epochs", 0))
                if done >= 1:
                    self.state = restore_state(
                        self.state, self._ckpt_path, trial,
                        shardings=self._state_sh,
                    )
                    restored_step = int(jax.device_get(self.state.step))
                    if "step" in meta and restored_step != int(meta["step"]):
                        raise UnretryableError(
                            f"resume: trial {cfg.trial_id} checkpoint is "
                            f"skewed — state.msgpack is at optimizer step "
                            f"{restored_step} but the metadata sidecar "
                            f"claims step {meta['step']} (epoch {done}). "
                            "A crash likely landed between the two "
                            "checkpoint file replaces; delete "
                            f"{self._ckpt_path}* to restart this trial "
                            "from scratch rather than silently re-train "
                            "an already-applied epoch"
                        )
                    self._start_epoch = done + 1
                    self._adopt_history(meta)
        # Executed-work accounting (chaos goodput): what step this
        # attempt starts from. Epoch data order is drop-tail-stable, so
        # the resume step is exactly epochs-done x batches-per-epoch.
        self.result.resumed_from_step = (
            (self._start_epoch - 1) * self.train_iter.num_batches
        )

    def _config_mismatch(self, meta: dict) -> dict:
        return config_mismatch_vs_meta(self.cfg, meta)

    def _restore_scan(self):
        """Scan-back restore for supervised retries and elastic
        restarts; returns ``(state, meta, used_path)`` or ``None`` for
        scratch.

        Single-owner submeshes take the plain local scan
        (``restore_latest_valid``). A PROCESS-SPANNING submesh runs the
        **cross-host restore agreement** (docs/RESILIENCE.md "Elastic
        multi-host", ``train.checkpoint.agreed_restore_step``): every
        owner verifies its candidates locally, the group agrees on the
        min of the newest locally-valid steps and confirms everyone
        holds the agreed candidate — over the coordination-service
        sideband (``cluster.agree_min_int``), never an on-mesh
        collective: recovery must work when the device world is the
        broken thing. Shared-filesystem views can disagree
        (close-to-open NFS races, a write torn under one reader) —
        without the agreement, owners would resume different weights
        and silently desync SPMD. Any disagreement degrades to scratch
        on every owner, never an error: recovery must degrade, not
        wedge.
        """
        def accept(meta: dict) -> bool:
            return not self._config_mismatch(meta)

        # Warm re-place (docs/RESILIENCE.md "Snapshot-fast drain"): a
        # preempted trial re-placed in the SAME process restores from
        # the still-warm RAM snapshot — no chunk reads, no msgpack
        # decode. The cache entry is written at the same device→host
        # fetch that feeds the durable write, so it is never older
        # than the newest disk candidate for this path; config-match
        # gates it exactly like a disk candidate's sidecar.
        snap = (
            snapshot_cache().get(self._ckpt_path)
            if self._ram_restore
            else None
        )
        if snap is not None:
            host_state, meta = snap
            if accept(meta) and int(meta.get("completed_epochs", 0)) >= 1:
                try:
                    restored = self.trial.device_put(
                        host_state, self._state_sh
                    )
                except Exception:  # noqa: BLE001 — fall back to disk
                    restored = None
                if restored is not None:
                    from multidisttorch_tpu.train.checkpoint import _count

                    _count(restores=1, restores_ram=1)
                    bus = get_bus()
                    if bus is not None:
                        bus.emit(
                            "ckpt_restore",
                            group_id=self.trial.group_id,
                            path=RAM_SNAPSHOT,
                            format="ram",
                            trial_id=self.cfg.trial_id,
                            step=meta.get("step"),
                        )
                    return restored, dict(meta), RAM_SNAPSHOT
            snapshot_cache().drop(self._ckpt_path)

        if not (jax.process_count() > 1 and self.trial.spans_processes):
            return restore_latest_valid(
                self.state,
                self._ckpt_path,
                self.trial,
                shardings=self._state_sh,
                accept_meta=accept,
            )
        from multidisttorch_tpu.train.checkpoint import agreed_restore_step

        got = agreed_restore_step(
            self._ckpt_path,
            # Attempt-scoped agreement keys: a retried trial's new
            # agreement never reads the previous attempt's votes (and
            # every re-formed world gets a fresh coordinator anyway).
            name=f"trial{self.cfg.trial_id}:a{self._attempt}",
            participants=self.trial.owner_processes,
            accept_meta=lambda meta: (
                accept(meta) and int(meta.get("completed_epochs", 0)) >= 1
            ),
            timeout_s=self._agree_timeout_s,
            what=(
                f"trial {self.cfg.trial_id} restore agreement over "
                f"submesh group {self.trial.group_id}"
            ),
            trial_id=self.cfg.trial_id,
            group_id=self.trial.group_id,
        )
        if got is None:
            return None  # disagreement degrades to scratch everywhere
        _step, cand, meta = got
        restored = restore_state(
            self.state, cand, self.trial, shardings=self._state_sh
        )
        return restored, meta, cand

    def _wedged_fetch(self, fn, what: str):
        """Run a host-side device fetch under the wedge watchdog when
        its result transits a cross-host collective (spanning submesh,
        multi-controller). A peer that stopped dispatching blocks such
        fetches forever; the watchdog converts that into a
        ``WedgedCollective`` within the deadline. Local fetches call
        straight through — no watchdog thread, no overhead."""
        if (
            not self._wedge_timeout_s
            or jax.process_count() == 1
            or not self.trial.spans_processes
        ):
            return fn()
        from multidisttorch_tpu.parallel.cluster import (
            WedgedCollective,
            call_with_timeout,
        )

        return call_with_timeout(
            fn,
            self._wedge_timeout_s,
            f"trial {self.cfg.trial_id} {what}",
            error_cls=WedgedCollective,
        )

    def _adopt_history(self, meta: dict) -> None:
        self.result.history = list(meta.get("history", []))
        if self.result.history:
            last = self.result.history[-1]
            self.result.final_train_loss = last.get(
                "avg_train_loss", float("nan")
            )
            self.result.final_test_loss = last.get(
                "test_loss", float("nan")
            )

    def _data_fault_hook(self, epoch: int, batch_index: int) -> None:
        """Data-iterator injection seam: maps the iterator's
        (epoch, batch_index) to the trial's global optimizer step."""
        self._injector.data_hook(
            self.cfg.trial_id, self._epoch_base_step + batch_index
        )

    def _log(self, *args, level: int = logging.INFO):
        if self._verbose:
            log0(*args, trial=self.trial, level=level)

    def _registry_init_state(self):
        """Materialize this trial's TrainState through the compile
        registry's init executable (docs/COMPILE.md): take the farm's
        finished program if READY, else compile it inline through the
        registry (coalescing with a mid-compile farm worker — never
        longer than the eager init compile this replaces, and the
        executable then serves every same-bucket trial). Returns the
        PLACED state, or None for the eager ``create_train_state``
        fallback (failed compile, torn registry, any exception)."""
        from multidisttorch_tpu.compile import programs as _cprog
        from multidisttorch_tpu.compile.registry import (
            READY,
            SOURCE_INLINE,
            get_executable_registry,
        )

        cfg, trial = self.cfg, self.trial
        try:
            key = _cprog.single_init_key(trial, cfg, stack_bucket_key(cfg))
            reg = get_executable_registry()
            ex = reg.take(key)
            if ex is None:
                entry = reg.compile_now(
                    key,
                    _cprog.build_init_fn(cfg, self.model),
                    _cprog.init_avals(),
                    source=SOURCE_INLINE,
                )
                if entry.status == READY:
                    ex = entry.compiled
            if ex is None:
                return None
            return trial.device_put(ex(jax.random.key(cfg.seed)))
        except Exception:  # noqa: BLE001 — init must never be the
            # reason a trial cannot start; the eager path always works.
            return None

    def _wrap_train(self, fn):
        """Chaos hook-wrapping for a single-step program (jit fn or AOT
        executable — both are plain callables to the hooks)."""
        if self._injector is None:
            return fn
        injector, tid = self._injector, self.cfg.trial_id
        return wrap_step_with_hooks(
            fn,
            before=lambda b: injector.step_hook(tid, self._step_no, 1),
            transform_batch=lambda b: injector.poison_batch(
                tid, self._step_no, b, 1
            ),
        )

    def _wrap_multi(self, fn):
        if self._injector is None:
            return fn
        injector, tid = self._injector, self.cfg.trial_id
        return wrap_step_with_hooks(
            fn,
            before=lambda b: injector.step_hook(
                tid, self._step_no, b.shape[0]
            ),
            transform_batch=lambda b: injector.poison_batch(
                tid, self._step_no, b, b.shape[0]
            ),
        )

    def _admit_programs(self) -> Iterator[None]:
        """Cooperative AOT admission (docs/COMPILE.md): swap registry
        executables in for the raw jit programs before the first
        dispatch. Yields while a farm worker is mid-compile — the host
        loop keeps every OTHER submesh stepping, so admission never
        blocks on XLA."""
        if not self._aot_keys:
            return
        from multidisttorch_tpu.compile import programs as _cprog

        primary = "multi" if self.cfg.fused_steps > 1 else "train"
        raw = {"train": self._train_raw, "multi": self._multi_raw}
        taken, self._admission = yield from _aot_admit(
            self._aot_keys,
            raw,
            lambda: _cprog.single_avals(self.cfg),
            self.state,
            primary,
        )
        if "train" in taken:
            self.train_step = self._wrap_train(taken["train"])
        if "multi" in taken:
            self.multi_step = self._wrap_multi(taken["multi"])

    def _note_first_dispatch(self) -> None:
        """One event per trial, right after the first step dispatch
        returns: its timestamp minus the attempt_start's is the trial's
        admission latency (setup + compile — the cold-start books'
        headline number), and the data says how the program arrived
        (hit/wait/inline/jit)."""
        self._first_dispatched = True
        bus = get_bus()
        if bus is not None:
            bus.emit(
                "first_dispatch",
                trial_id=self.cfg.trial_id,
                group_id=self.trial.group_id,
                **self._admission,
            )

    def _device_seam(self, dt, fn, args, *, steps: int = 1) -> None:
        """Per-dispatch device-book seam (reached only with telemetry
        ON — call sites sit inside the ``self._mreg is not None``
        guard): record the compiled step's XLA cost analysis ONCE per
        trial (shapes don't change after the first dispatch), then feed
        the straggler detector the per-step time the registry just
        measured (``dt`` is ``step_mark``'s return — no second clock
        read)."""
        if not self._cost_done:
            self._cost_done = True
            tele_device.record_step_cost(
                self._mkey, fn, args, steps=steps,
                devices=self.trial.devices,
                trial_id=self.cfg.trial_id,
                group_id=self.trial.group_id,
                # Same shape bucket + same arg shapes = same compiled
                # program up to scalar hypers: one AOT analysis serves
                # every same-shape trial and every retry attempt.
                cache_key=("single", stack_bucket_key(self.cfg)),
            )
            # The AOT lower+compile above took real wall time inside an
            # open interval — re-open so the next mark doesn't charge
            # the compile as one giant dispatch (it would inflate the
            # dispatch p95, deflate MFU, and seed the straggler
            # detector's baseline with a bogus sample).
            self._mreg.step_series(self._mkey).open_interval()
        if self._amon is not None and dt is not None:
            self._amon.observe_step(
                self._mkey, dt,
                trial_id=self.cfg.trial_id, step=self._step_no,
            )

    @contextmanager
    def _guard(self):
        """Collect writer-only host-I/O failures (image/checkpoint/
        metrics writes) for epoch-boundary agreement instead of raising
        on one process of a spanning submesh. No-op outside agreement
        mode: errors raise at the fault site, reference-honest."""
        if not self._agree:
            yield
            return
        try:
            yield
        except Exception as e:  # noqa: BLE001 — deferred to agreement
            # Preemption-class failures (host going away, wedged or
            # expired collective) are NOT writer-I/O failures to vote
            # on at the next boundary — the distributed state is
            # already unusable, and the next boundary's reduction
            # would wedge too. Propagate immediately.
            from multidisttorch_tpu.faults.inject import HostPreemption
            from multidisttorch_tpu.parallel.cluster import AgreementTimeout

            if isinstance(e, (HostPreemption, AgreementTimeout)):
                raise
            if self._deferred_error is None:
                self._deferred_error = e

    def _agree_boundary(self, where: str) -> None:
        """Epoch-boundary health agreement over the trial submesh.

        Every owner process calls this at the same point in the group's
        dispatch sequence (deterministic cadence: once per epoch + once
        at completion). If any owner deferred a failure, ALL owners
        raise here — the submesh is freed identically everywhere, and
        unrelated trials never participate (no world barrier; quirk Q3
        stays fixed). Deterministic compute failures need no agreement:
        SPMD determinism raises them identically on every owner.
        """
        if not self._agree:
            return
        from multidisttorch_tpu.parallel.cluster import WedgedCollective
        from multidisttorch_tpu.parallel.collectives import group_all_ok

        err, self._deferred_error = self._deferred_error, None
        # Deadline-bounded: a dead peer owner would otherwise hang this
        # reduction forever (the reference's exact lost-rank behavior).
        # On expiry a WedgedCollective propagates through the trial's
        # normal failure isolation (classified as preemption), naming
        # the trial and boundary.
        if not group_all_ok(
            self.trial,
            err is None,
            timeout_s=self._agree_timeout_s,
            what=(
                f"trial {self.cfg.trial_id} {where} health agreement "
                f"over submesh group {self.trial.group_id}"
            ),
            error_cls=WedgedCollective,
        ):
            if err is not None:
                raise err
            raise RuntimeError(
                f"trial {self.cfg.trial_id}: {where} failed on a peer "
                "owner process (agreed via submesh health reduction)"
            )

    def _write_ckpt(self, host_state, meta: dict) -> None:
        """Background checkpoint write. ``result.checkpoint`` is set only
        after the (atomic) write succeeds, so a failed write can never be
        reported as a valid checkpoint; failures are re-raised on the
        next :meth:`_join_ckpt` and flow through the trial's normal
        failure isolation."""
        try:
            save_state(
                host_state,
                self._ckpt_path,
                metadata=meta,
                keep_last=self._ckpt_keep_last,
                format=self._ckpt_format,
                # The layout record describes what was SNAPSHOTTED: a
                # gathered (replicated) snapshot must not claim the
                # live state's sharded layout.
                layouts=(
                    self._state_sh if self._gather_state is None else None
                ),
                stats_out=self._last_ckpt_stats,
            )
            self.result.checkpoint = self._ckpt_path
            if self._injector is not None:
                # Chaos seam: CKPT_CORRUPT garbles the file AFTER the
                # write lands — the bit-rot/torn artifact that
                # restore_latest_valid must scan past on retry.
                self._injector.checkpoint_hook(
                    self.cfg.trial_id,
                    int(meta.get("completed_epochs", 0)),
                    self._ckpt_path,
                )
        except BaseException as e:  # re-raised at the next join
            self._ckpt_error = e

    def _join_ckpt(self) -> None:
        if self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._ckpt_thread = None
        if self._ckpt_error is not None:
            e, self._ckpt_error = self._ckpt_error, None
            raise RuntimeError(
                f"trial {self.cfg.trial_id}: checkpoint write to "
                f"{self._ckpt_path} failed"
            ) from e

    def _ckpt_idle(self) -> bool:
        """No persist in flight (non-blocking — the snapshot-fast
        drain's poll; :meth:`_join_ckpt` is the blocking/raising
        sibling)."""
        t = self._ckpt_thread
        return t is None or not t.is_alive()

    def run(self) -> Iterator[None]:
        cfg = self.cfg
        t0 = time.time()
        if self._start_epoch > cfg.epochs:
            # Fully-trained checkpoint found: nothing to replay.
            self.result.status = "resumed_complete"
            self.result.steps = int(jax.device_get(self.state.step))
            self.result.checkpoint = self._ckpt_path
            self._log(f"Trial {cfg.trial_id} already complete; resumed.")
            return
        # AOT admission before the first dispatch: take/wait-for/claim
        # this trial's compiled programs (cooperative — yields keep the
        # other submeshes stepping while a farm worker compiles ours).
        yield from self._admit_programs()
        n_per_epoch = self.train_iter.samples_per_epoch
        # state.step counts optimizer updates, so it doubles as the
        # resume-safe global step for RNG folding. Kept as an attribute:
        # the fault-injection hook closures read it mid-dispatch.
        self._step_no = int(jax.device_get(self.state.step))
        for epoch in range(self._start_epoch, cfg.epochs + 1):
            self._epoch_base_step = self._step_no
            # Fresh timing interval per epoch: the gap since the last
            # mark holds boundary work (eval, checkpoint, a retry's
            # backoff), not a dispatch — without the break it reads as
            # one giant "step" and trips the straggler detector.
            if self._mreg is not None:
                self._mreg.step_series(self._mkey).open_interval()
            # On-device loss accumulation (mirrors the eval path below):
            # each batch's contribution is an async device add; the
            # single float() at the epoch boundary is the train loop's
            # only non-logging host sync.
            epoch_sum_dev = None

            def log_batch(epoch, i, loss_sum):
                # Per-STEP chatter rides DEBUG (per-trial lines stay
                # INFO): a sweep that raises the logger level skips the
                # device sync below entirely, not just the print.
                if not self._verbose or not log0_enabled(logging.DEBUG):
                    return  # don't pay the device sync for a dropped line
                # sync point for THIS trial only (reference logs
                # loss.item() here, vae-hpo.py:76-86)
                self._host_syncs += 1
                per_sample = float(loss_sum) / cfg.batch_size
                self._log(
                    "Train Epoch: {} [{}/{} ({:.0f}%)]\tLoss: {:.6f}".format(
                        epoch,
                        i * cfg.batch_size,
                        n_per_epoch,
                        100.0 * i / self.train_iter.num_batches,
                        per_sample,
                    ),
                    level=logging.DEBUG,
                )

            if self.multi_step is None:
                for i, batch in enumerate(self.train_iter.epoch(epoch)):
                    rng = jax.random.fold_in(self._key, self._step_no)
                    self.state, metrics = self.train_step(
                        self.state, batch, rng
                    )
                    self._step_no += 1
                    if not self._first_dispatched:
                        self._note_first_dispatch()
                    s = metrics["loss_sum"]  # on device, async
                    epoch_sum_dev = s if epoch_sum_dev is None else epoch_sum_dev + s
                    if self._mreg is not None:
                        dt = self._mreg.step_mark(self._mkey, s)
                        self._device_seam(
                            dt, self.train_step, (self.state, batch, rng)
                        )
                    if i % cfg.log_interval == 0:
                        log_batch(epoch, i, metrics["loss_sum"])
                    yield  # hand the host loop to the next trial
            else:
                # Scan-fused dispatch: fused_steps optimizer updates per
                # host round-trip. The log cadence is preserved exactly —
                # the chunk's per-step losses are indexable, so the batch
                # that would have logged in the per-step loop still does.
                K = cfg.fused_steps
                for item in self.train_iter.epoch_chunks(epoch, K):
                    i0, chunk = item[0], item[1]
                    c = chunk.shape[0]
                    if c == K:
                        rng = jax.random.fold_in(self._key, self._step_no)
                        self.state, metrics = self.multi_step(
                            self.state, chunk, rng
                        )
                        self._step_no += c
                        if not self._first_dispatched:
                            self._note_first_dispatch()
                        losses = metrics["loss_sum"]  # (K,) on device
                        s = losses.sum()  # device add, async
                        epoch_sum_dev = (
                            s if epoch_sum_dev is None else epoch_sum_dev + s
                        )
                        if self._mreg is not None:
                            dt = self._mreg.step_mark(self._mkey, s, steps=c)
                            self._device_seam(
                                dt, self.multi_step,
                                (self.state, chunk, rng), steps=c,
                            )
                        # Every batch index that would have logged in the
                        # per-step loop still logs (there can be several
                        # per chunk when log_interval < fused_steps).
                        j = -(-i0 // cfg.log_interval) * cfg.log_interval
                        while j < i0 + c:
                            log_batch(epoch, j, losses[j - i0])
                            j += cfg.log_interval
                    else:
                        # Tail shorter than the compiled chunk: step it
                        # batch-by-batch (no extra compilation).
                        for j in range(c):
                            rng = jax.random.fold_in(self._key, self._step_no)
                            self.state, metrics = self.train_step(
                                self.state, chunk[j], rng
                            )
                            self._step_no += 1
                            if not self._first_dispatched:
                                self._note_first_dispatch()
                            s = metrics["loss_sum"]
                            epoch_sum_dev = (
                                s
                                if epoch_sum_dev is None
                                else epoch_sum_dev + s
                            )
                            if self._mreg is not None:
                                dt = self._mreg.step_mark(self._mkey, s)
                                self._device_seam(
                                    dt, self.train_step,
                                    (self.state, chunk[j], rng),
                                )
                            if (i0 + j) % cfg.log_interval == 0:
                                log_batch(epoch, i0 + j, metrics["loss_sum"])
                    yield

            # One fetch for the whole epoch's average (O(1)-syncs rule).
            # Wedge-watchdog-bounded on spanning submeshes: the sum
            # transits the step's cross-host reduction, so a peer that
            # stopped dispatching wedges THIS fetch first.
            self._host_syncs += 1
            avg = self._wedged_fetch(
                lambda: float(epoch_sum_dev),
                f"epoch {epoch} loss fetch",
            ) / n_per_epoch
            # Device memory books ride the sync just paid (never the
            # dispatch hot loop) — sampled BEFORE the divergence gate
            # below so even a diverging trial's books close.
            if self._mreg is not None:
                tele_device.sample_memory(
                    self._mkey, self.trial.devices, where="epoch",
                    trial_id=cfg.trial_id, group_id=self.trial.group_id,
                )
            # Divergence gate at the sync the loop already pays: a
            # non-finite epoch average is a terminal trial RESULT
            # (deterministic training replays the same NaN on retry) —
            # raised before the checkpoint write below so NaN weights
            # are never persisted over a valid checkpoint.
            check_finite(
                avg,
                "epoch average train loss",
                step=self._step_no,
                trial_id=cfg.trial_id,
            )
            # Loss watch sees only finite losses: a non-finite average
            # is already a *terminal* verdict, not a precursor.
            if self._amon is not None:
                self._amon.observe_loss(
                    cfg.trial_id, epoch=epoch, train_loss=avg
                )
            self._log(
                "====> Epoch: {} Average loss: {:.4f}".format(epoch, avg)
            )
            epoch_record = {"epoch": epoch, "avg_train_loss": avg}

            if self.test_iter is not None:
                # On-device loss accumulation: the per-batch adds are
                # async dispatches; the single float() at the end is the
                # epoch's only eval host sync (round 1 synced every
                # batch, the last per-batch round-trip on the hot path).
                test_sum_dev, first_batch, first_recon = None, None, None
                for j, (tbatch, tweights) in enumerate(
                    self.test_iter.batches()
                ):
                    if cfg.eval_sampled:
                        # Distinct key per (epoch, batch), disjoint from
                        # the train stream (offset past any step count).
                        erng = jax.random.fold_in(
                            self._key, 2**28 + epoch * 2**16 + j
                        )
                        out = self.eval_step(
                            self.state, tbatch, tweights, erng
                        )
                    else:
                        out = self.eval_step(self.state, tbatch, tweights)
                    test_sum_dev = (
                        out["loss_sum"]
                        if test_sum_dev is None
                        else test_sum_dev + out["loss_sum"]
                    )
                    if j == 0 and self._save_images:
                        # batch values from the deterministic host view
                        # (the device batch is data-sharded and, on a
                        # process-spanning submesh, not fetchable whole);
                        # recon is replicated, hence fetchable anywhere.
                        if self._first_test_batch is None:
                            self._first_test_batch = (
                                self.test_iter.first_host_batch()
                            )
                        first_batch = self._first_test_batch
                        first_recon = np.asarray(out["recon"])
                    yield
                # Exact-count divisor: every real row was evaluated, the
                # padded rows carried weight 0.0.
                self._host_syncs += 1
                test_avg = self._wedged_fetch(
                    lambda: float(test_sum_dev),
                    f"epoch {epoch} test loss fetch",
                ) / self.test_iter.num_rows
                self._log("====> Test set loss: {:.4f}".format(test_avg))
                epoch_record["test_loss"] = test_avg
                self.result.final_test_loss = test_avg
                if self._save_images and first_batch is not None:
                    with self._guard():
                        # input-vs-recon grid (vae-hpo.py:106-116)
                        n = min(8, first_batch.shape[0])
                        comparison = np.concatenate(
                            [first_batch[:n], first_recon[:n]]
                        )
                        save_image_grid(
                            comparison,
                            os.path.join(
                                self.out_dir, f"reconstruction_{epoch}.png"
                            ),
                            nrow=n,
                        )

            if self._images_requested:
                # prior-sample grid (vae-hpo.py:163-170). The dispatch is
                # UNIFORM across owner processes (a jit program on the
                # submesh — writer-gating it would desynchronize SPMD on
                # a spanning group); only the fetch + PNG write below are
                # writer-only.
                # sample keys live in a disjoint fold_in range (steps
                # count up from 0; fold_in data must be non-negative)
                sample_out = self.sample_step(
                    self.state, jax.random.fold_in(self._key, 2**30 + epoch)
                )
                if self._save_images:
                    with self._guard():
                        save_image_grid(
                            np.asarray(sample_out),
                            os.path.join(self.out_dir, f"sample_{epoch}.png"),
                        )

            self.result.history.append(epoch_record)
            self.result.final_train_loss = avg
            bus = get_bus()
            if bus is not None:
                bus.emit(
                    "epoch",
                    trial_id=cfg.trial_id,
                    group_id=self.trial.group_id,
                    step=self._step_no,
                    **epoch_record,
                )
            if self._save_checkpoint:
                # Sharded states gather to replicated first — dispatched
                # on ALL owners (uniform program; a writer-local gather
                # would desynchronize a spanning submesh), making every
                # leaf fully addressable for the writer's fetch below.
                snap = (
                    self._gather_state(self.state)
                    if self._gather_state is not None
                    else self.state
                )
            if self._save_checkpoint and self._is_writer:
                with self._guard():
                    # Per-epoch checkpoint = the resume boundary. Keep
                    # the scheduler loop responsive: start the
                    # device→host copy async, yield once so other trials
                    # keep dispatching, then hand the serialize+disk-
                    # write to a background thread. The snapshot is
                    # taken before the next epoch's first step, so
                    # donation can't invalidate it (the gathered copy is
                    # its own buffer in the sharded case).
                    jax.tree.map(lambda x: x.copy_to_host_async(), snap)
                    yield
                    _snap_t0 = time.perf_counter()
                    host_state = self._wedged_fetch(
                        lambda: jax.device_get(snap),
                        f"epoch {epoch} checkpoint snapshot fetch",
                    )
                    # Checkpoint boundary is the trial's memory high-
                    # water moment (the gathered/host-bound snapshot is
                    # live alongside the training state) — sample it.
                    if self._mreg is not None:
                        tele_device.sample_memory(
                            self._mkey, self.trial.devices,
                            where="checkpoint",
                            trial_id=cfg.trial_id,
                            group_id=self.trial.group_id,
                        )
                    meta = {
                        **asdict(cfg),
                        "completed_epochs": epoch,
                        # Optimizer-step count at this epoch boundary:
                        # resume cross-checks it against the restored
                        # state so a crash landing between the two
                        # atomic replaces (state newer than sidecar) is
                        # detected, not silently re-trained.
                        "step": int(host_state.step),
                        "history": list(self.result.history),
                    }
                    # The device→host snapshot is the drain boundary
                    # (docs/RESILIENCE.md "Snapshot-fast drain"): once
                    # it lands in the RAM cache, a preemption can free
                    # this trial's slices and a same-process re-place
                    # can restore without touching disk — persistence
                    # below runs behind. Gated on the same opt-in as
                    # the read side: a standalone run_hpo must not pin
                    # host copies of large states nothing will read.
                    if self._ram_restore:
                        snapshot_cache().put(
                            self._ckpt_path, host_state, meta
                        )
                    if bus is not None:
                        bus.emit(
                            "ckpt_snapshot",
                            trial_id=cfg.trial_id,
                            group_id=self.trial.group_id,
                            step=int(host_state.step),
                            epoch=epoch,
                            wall_s=round(
                                time.perf_counter() - _snap_t0, 6
                            ),
                        )
                    self._join_ckpt()
                    self._ckpt_thread = threading.Thread(
                        target=self._write_ckpt,
                        args=(host_state, meta),
                        # Non-daemon: interpreter exit waits for the
                        # write (atexit joins it), so a crash elsewhere
                        # in the sweep can't kill a checkpoint
                        # mid-flight.
                        daemon=False,
                    )
                    self._ckpt_thread.start()
            # One agreement per epoch: all owners of a spanning submesh
            # kill the trial together if any of them deferred a failure.
            self._agree_boundary(f"epoch {epoch} boundary work")

        # drain the pipeline so wall-clock covers real completion
        # (wedge-watchdog-bounded: the last dispatched steps hold
        # cross-host collectives a lost peer never finishes)
        self._wedged_fetch(
            lambda: jax.block_until_ready(self.state.params),
            "completion block_until_ready",
        )
        with self._guard():
            self._join_ckpt()
        self.result.wall_s = time.time() - t0
        self.result.steps = self._step_no
        self.result.host_syncs = self._host_syncs
        if self._is_writer:
            with self._guard():
                os.makedirs(self.out_dir, exist_ok=True)
                with open(
                    os.path.join(self.out_dir, "metrics.json"), "w"
                ) as f:
                    json.dump(
                        {
                            "trial_id": self.result.trial_id,
                            "group_id": self.result.group_id,
                            "config": asdict(cfg),
                            "dataset": self.result.dataset,
                            "dataset_synthetic": self.result.dataset_synthetic,
                            "history": self.result.history,
                            "wall_s": self.result.wall_s,
                            "steps": self.result.steps,
                        },
                        f,
                        indent=2,
                    )
        self._agree_boundary("completion work")
        self._log(f"Done. time: {self.result.wall_s:f}")


# --- graceful drain on SIGTERM/SIGINT (docs/RESILIENCE.md) ----------
# run_hpo installs these around its scheduling loop. First signal: the
# loop finishes the current dispatch cycle, lands every pending
# checkpoint write, records all in-flight attempts as "preempted" in
# the ledger (fsync'd), and raises HostPreemption — a supervised
# worker maps that to cluster.PREEMPTION_EXIT_CODE
# (supervision.exit_code_for), and a resumed run_hpo loses at most one
# checkpoint cadence of work. Second signal: the operator means it —
# the default disposition is restored and the signal re-raised.
# Module-level state because the handler must outlive _run_hpo_body's
# closures and signal.signal only works on the main thread.
_DRAIN: dict = {"sig": None, "prev": None}


def _install_drain_handlers() -> None:
    import signal

    _DRAIN["sig"] = None
    if threading.current_thread() is not threading.main_thread():
        return  # signal.signal is main-thread-only; drain unavailable

    def on_signal(signum, frame):
        if _DRAIN["sig"] is not None:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        _DRAIN["sig"] = signum

    prev = {}
    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[s] = signal.signal(s, on_signal)
        except (ValueError, OSError):  # embedded/exotic hosts
            pass
    _DRAIN["prev"] = prev


def _restore_drain_handlers() -> None:
    import signal

    prev, _DRAIN["prev"] = _DRAIN.get("prev"), None
    _DRAIN["sig"] = None
    for s, h in (prev or {}).items():
        try:
            signal.signal(s, h)
        except (ValueError, OSError):
            pass


def _aot_admit(keys: dict, raw_fns: dict, avals_builder, state, primary):
    """The one admission protocol (generator), shared by the classic
    and stacked runners: for each program key, **take** a READY
    registry executable, **wait cooperatively** (yield — the host loop
    keeps other submeshes stepping) while a farm worker compiles the
    PRIMARY program, or **claim** an unstarted primary and compile it
    inline through the registry (same wall the jit path would pay at
    first dispatch, but timed, attributed, and reusable by every
    later same-program trial). Non-primary programs (the tail step of
    a fused config) are take-if-ready only — never worth waiting or
    inline-compiling for (jit compiles them lazily IF a tail exists).

    Returns ``(executables, admission)`` where ``admission`` records
    the primary's outcome: ``hit`` (ready at admission), ``wait``
    (farm finished it while we yielded), ``inline`` (we compiled it),
    ``jit`` (fallback — failed compile, aval mismatch, or wait
    deadline). A registry executable is swapped in only when its
    recorded avals structurally match the trial's REAL state (resume
    restores, vocabulary drift) — mismatch is a silent jit fallback,
    never a call-time TypeError mid-sweep.
    """
    from multidisttorch_tpu.compile import programs as _cprog
    from multidisttorch_tpu.compile.registry import (
        COMPILING,
        PENDING,
        READY,
        SOURCE_INLINE,
        get_executable_registry,
    )

    out: dict = {}
    admission = {"outcome": "jit", "wait_s": 0.0, "program": None}
    if not keys:
        return out, admission
    reg = get_executable_registry()
    t0 = time.perf_counter()
    wait_deadline = t0 + float(os.environ.get("MDT_AOT_WAIT_S", "600"))
    avals = None
    order = [primary] + [k for k in keys if k != primary]
    for which in order:
        key = keys[which]
        is_primary = which == primary
        waited = False
        if is_primary:
            # PENDING means a farm worker WILL compile this — wait for
            # it too, not just COMPILING: claiming a queued farm job
            # and compiling it inline would stall the host loop, which
            # is the one thing the farm exists to prevent. (A torn
            # farm shutdown releases its queued entries, so this wait
            # cannot outlive the farm; the deadline bounds the rest.)
            while (
                reg.status(key) in (PENDING, COMPILING)
                and time.perf_counter() < wait_deadline
            ):
                waited = True
                time.sleep(0.001)
                yield
        # The avals guard runs BEFORE take(): take() books a cache_hit
        # (event + hits counter), and a registry executable the guard
        # is about to reject (resume restores, vocabulary drift) was
        # never served — the books must show the jit fallback that
        # actually ran, not a phantom hit. (Avals are immutable once
        # READY, so check-then-take cannot race.)
        ex = None
        entry_avals = reg.avals(key)
        rejected = entry_avals is not None and not _cprog.avals_match(
            entry_avals[0], state
        )
        if not rejected:
            ex = reg.take(key)
        outcome = ("wait" if waited else "hit") if ex is not None else None
        if ex is None and not rejected and is_primary and reg.claim(key):
            if avals is None:
                try:
                    avals = avals_builder()
                except Exception as e:  # noqa: BLE001 — aval
                    # derivation failing is a registry problem, not a
                    # trial problem: the jit fallback must still run.
                    reg.fail(key, f"avals: {type(e).__name__}: {e}")
                    avals = None
            if avals is not None:
                e = reg.compile_now(
                    key, raw_fns[which], avals[which], source=SOURCE_INLINE
                )
                if e.status == READY:
                    ex = e.compiled
                    outcome = "inline"
        if ex is not None:
            entry_avals = reg.avals(key)
            if entry_avals is None or not _cprog.avals_match(
                entry_avals[0], state
            ):
                ex = None
                outcome = None
        if ex is not None:
            out[which] = ex
        if is_primary:
            admission = {
                "outcome": outcome or "jit",
                "wait_s": round(time.perf_counter() - t0, 4),
                "program": _cprog.program_label(key),
            }
    return out, admission


def stack_bucket_key(cfg: TrialConfig) -> tuple:
    """The shape signature under which trials may share one compiled
    stacked program: everything that changes an array shape or the
    compiled step structure. Scalar hypers (lr, beta, seed) and the
    epoch target deliberately stay OUT — they are the vmapped axis."""
    return (
        cfg.batch_size,
        cfg.hidden_dim,
        cfg.latent_dim,
        cfg.fused_steps,
        cfg.grad_accum,
        cfg.remat,
    )


def config_is_stackable(cfg: TrialConfig) -> bool:
    """Whether a config can ride a stacked bucket at all. Sampled eval
    is the one per-trial knob the stacked eval step does not carry
    (posterior-mean eval only); a sharded-update (zero_update) state
    shards over the submesh where stacked states replicate; a
    pipelined trial is a vector of submeshes. All three run their own
    paths."""
    return (
        not cfg.eval_sampled
        and not cfg.zero_update
        and cfg.pipeline_stages == 1
    )


def data_shape_sig(ds: Dataset, batch_size: int) -> tuple:
    """The dataset half of a co-pack decision: feature dim (batch-shape
    agreement) and per-epoch batch count (lockstep-round agreement).
    Deliberately NOT the dataset's identity — K lanes reading K
    different datasets of one shape class share a bucket (docs/DATA.md
    heterogeneous lanes)."""
    return (int(ds.images.shape[1]), len(ds) // max(1, int(batch_size)))


class _StackedBucketRun:
    """One shape-bucket of K stacked trials on ONE submesh, as a
    cooperative generator (the stacked sibling of :class:`_TrialRun`).

    All lanes advance in lockstep rounds of ``num_batches`` optimizer
    steps (one round = one epoch for every lane, since bucket members
    share dataset and batch size by construction); each dispatch is one
    vmapped program advancing every lane at once, scan-chunked by the
    bucket's ``fused_steps``. A lane that reaches its config's epoch
    target retires — its result and checkpoint are captured from a
    compiled lane-slice read — and is refilled in place from the
    bucket's pending queue (``write_lane``; traced lane index, so no
    recompilation ever) or masked inactive when the queue is dry.

    Per-trial RNG discipline matches the unstacked *per-step* path
    exactly (``fold_in(key(seed+1), step)``), so a stacked trial's
    weights are bit-identical to the same config run unstacked with
    ``fused_steps=1`` — the parity contract tests/test_stacking.py
    enforces.
    """

    def __init__(
        self,
        trial: TrialMesh,
        items: Sequence[tuple[int, TrialConfig]],
        train_data: Dataset,
        test_data: Optional[Dataset],
        out_dir: str,
        *,
        max_lanes: int = 8,
        save_checkpoint: bool = True,
        verbose: bool = True,
        injector=None,  # faults.inject.FaultInjector | None
        retry: Optional[RetryPolicy] = None,
        ledger: Optional[SweepLedger] = None,
        attempts: Optional[dict] = None,  # config index -> attempts started
        chashes: Optional[dict] = None,  # config index -> config hash
        infra_fails: Optional[dict] = None,  # config index -> infra failures
        datasets: Optional[dict] = None,  # config index -> Dataset
        ckpt_format: Optional[str] = None,
    ):
        template = items[0][1]
        for _, cfg in items:
            if stack_bucket_key(cfg) != stack_bucket_key(template):
                raise ValueError(
                    "stacked bucket mixes shape keys: "
                    f"{stack_bucket_key(cfg)} vs {stack_bucket_key(template)}"
                )
        # Heterogeneous lanes (docs/DATA.md): a member with its own
        # dataset reads it through its lane's slot of the one stacked
        # gather; members without one read the bucket's shared data.
        # Shape-class agreement (dim + per-epoch batches) is the
        # co-pack contract callers already grouped by — re-checked here
        # and by the iterator.
        self._default_data = train_data
        self._datasets = dict(datasets or {})
        self._ref_data = self._datasets.get(items[0][0], train_data)
        base_sig = data_shape_sig(self._ref_data, template.batch_size)
        for idx, _cfg in items:
            ds = self._datasets.get(idx, train_data)
            sig = data_shape_sig(ds, template.batch_size)
            if sig != base_sig:
                raise ValueError(
                    f"stacked bucket mixes dataset shape classes: "
                    f"{sig} vs {base_sig} (member {idx}, dataset "
                    f"{ds.name!r})"
                )
        self.trial = trial
        self.out_dir = out_dir
        self.queue: list[tuple[int, TrialConfig]] = list(items)
        self.results: dict[int, TrialResult] = {}
        self._save_checkpoint = save_checkpoint
        self._ckpt_format = (
            ckpt_format if ckpt_format is not None else default_format()
        )
        self._verbose = verbose
        self._host_syncs = 0
        self._is_writer = trial.is_writer_process
        # Lane supervision (docs/RESILIENCE.md): a faulted lane is
        # retired through the SAME mask-and-refill machinery finished
        # lanes use — the other K-1 lanes never stop. Retried lanes
        # restart from scratch (stacked lanes checkpoint only at
        # retirement, so there is no mid-trial checkpoint to resume;
        # the bucket queue's natural serialization stands in for
        # backoff).
        self._injector = injector
        self._retry = retry
        self._ledger = ledger
        self._attempts = attempts if attempts is not None else {}
        self._chashes = chashes if chashes is not None else {}
        self._infra_fails = infra_fails if infra_fails is not None else {}
        self._round_step0: dict[int, int] = {}
        # Telemetry: stacked step timings are attributed to the BUCKET
        # (one series per group's bucket, lanes= tagging the live lane
        # count), never to a single lane — the per-lane effective rate
        # is derived in the registry (telemetry.metrics.StepSeries).
        # Device books and straggler detection follow the same scoping:
        # the bucket is the dispatch unit, so its compiled program's
        # cost analysis and its step-time stream are bucket-keyed.
        self._mreg = get_registry()
        self._mkey = f"bucket-g{trial.group_id}"
        self._amon = get_monitor()
        self._cost_done = False
        # Cooperative bucket drain (the movable-stacked-placements
        # seam): request_drain() makes run() return at the NEXT round
        # boundary — every live lane's state then sits at an exact
        # epoch boundary, which is the only point the classic resume
        # path restores bit-identically. drain_snapshot() then fetches
        # each live lane device→host and persists the lane checkpoints
        # on one background writer (the classic runner's
        # _ckpt_thread/_ckpt_idle/_join_ckpt protocol, bucket-wide).
        self._drain_requested = False
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_error: Optional[BaseException] = None

        self.model = VAE(
            hidden_dim=template.hidden_dim, latent_dim=template.latent_dim
        )
        self.fused = template.fused_steps
        self.batch_size = template.batch_size

        k = min(len(self.queue), max_lanes)
        first = [self.queue.pop(0) for _ in range(k)]
        # Per-lane host bookkeeping; None = lane retired and unfillable.
        self.lanes: list[Optional[dict]] = [
            self._fresh_lane(i, cfg) for i, cfg in first
        ]
        for lane in self.lanes:
            self._note_attempt_start(lane)
        # Input-stall seam (docs/DATA.md): the iterator reports each
        # interval the dispatch loop sat blocked obtaining a batch.
        # Wired only when telemetry is on (metrics registry feeds the
        # StepSeries wait book; the bus gets a per-round input_wait
        # event) — OFF constructs nothing and reads no clocks.
        self._wait_counts = None
        wait_hook = None
        if self._mreg is not None or get_bus() is not None:
            self._wait_counts = {"wait_s": 0.0, "bytes": 0}
            series = (
                self._mreg.step_series(self._mkey)
                if self._mreg is not None
                else None
            )

            def wait_hook(dt, nbytes, _series=series):
                if _series is not None:
                    _series.note_wait(dt, nbytes)
                self._wait_counts["wait_s"] += dt
                self._wait_counts["bytes"] += nbytes
        self._input_t0 = time.time()
        self.data = StackedTrialDataIterator(
            self._ref_data, trial, self.batch_size,
            seeds=[lane["cfg"].seed for lane in self.lanes],
            datasets=[lane["data"] for lane in self.lanes],
            fault_hook=(
                None if injector is None else self._stacked_fault_hook
            ),
            wait_hook=wait_hook,
        )
        self.test_iter = (
            EvalDataIterator(test_data, trial, self.batch_size)
            if test_data is not None and len(test_data) > 0
            else None
        )
        step_kw = dict(remat=template.remat, grad_accum=template.grad_accum)
        self.sstep = make_stacked_train_step(trial, self.model, **step_kw)
        self.smulti = (
            make_stacked_multi_step(trial, self.model, **step_kw)
            if self.fused > 1
            else None
        )
        self.seval = (
            make_stacked_eval_step(trial, self.model)
            if self.test_iter is not None
            else None
        )
        self.read_lane, self.write_lane = make_lane_ops(trial)
        self.state = create_stacked_train_state(
            trial, self.model, [lane["cfg"].seed for lane in self.lanes]
        )
        self._refresh_lane_arrays()
        # AOT admission for the bucket's vmapped programs (the stacked
        # path is always the default family, single-controller — the
        # same eligibility envelope as the classic path's check).
        self._sstep_raw = self.sstep
        self._smulti_raw = self.smulti
        self._aot_keys: dict = {}
        self._admission = {"outcome": "jit", "wait_s": 0.0, "program": None}
        self._first_dispatched = False
        if os.environ.get("MDT_AOT_ADMISSION", "1") != "0":
            from multidisttorch_tpu.compile import programs as _cprog

            bucket = stack_bucket_key(template)
            lanes = len(self.lanes)
            self._aot_keys["train"] = _cprog.stacked_train_key(
                trial, bucket, lanes
            )
            if self.fused > 1:
                self._aot_keys["multi"] = _cprog.stacked_multi_key(
                    trial, bucket, lanes
                )
            self._aot_template = template

    def _data_of(self, idx: int) -> Dataset:
        """The dataset config-index ``idx``'s lane reads (its own per-
        submission dataset, else the bucket's shared default)."""
        return self._datasets.get(idx, self._default_data)

    def _fresh_lane(self, idx: int, cfg: TrialConfig) -> dict:
        return {
            "idx": idx,
            "cfg": cfg,
            "epochs_done": 0,
            "history": [],
            "steps": 0,
            "t0": time.time(),
            "syncs0": self._host_syncs,
            "data": self._data_of(idx),
        }

    def _refresh_lane_arrays(self) -> None:
        """Rebuild the per-dispatch (K,) arrays after fill/retire/refill.
        Retired lanes keep placeholder hypers under a 0.0 active mask —
        the compiled program never changes shape."""
        def per_lane(fn, default):
            return [
                fn(lane["cfg"]) if lane is not None else default
                for lane in self.lanes
            ]

        self.hypers = TrialHypers.stack(
            per_lane(lambda c: c.lr, 1e-3),
            per_lane(lambda c: c.beta, 1.0),
            active=per_lane(lambda c: 1.0, 0.0),
        )
        self.base_rngs = jnp.stack(
            [
                jax.random.key((lane["cfg"].seed if lane else 0) + 1)
                for lane in self.lanes
            ]
        )

    def _lane_steps(self):
        return jnp.asarray(
            [lane["steps"] if lane else 0 for lane in self.lanes], jnp.int32
        )

    def _log(self, *args, level: int = logging.INFO):
        if self._verbose:
            log0(*args, trial=self.trial, level=level)

    def _device_seam(self, dt, fn, args, *, steps: int = 1) -> None:
        """The bucket's device-book seam (telemetry ON only — call
        sites sit inside the ``self._mreg is not None`` guard). Cost
        analysis covers the COMPILED lane count (the vmapped program
        computes every lane, masked or live), recorded once per bucket;
        per-dispatch step times feed the straggler detector under the
        bucket key."""
        if not self._cost_done:
            self._cost_done = True
            template = next(
                lane for lane in self.lanes if lane is not None
            )["cfg"]
            tele_device.record_step_cost(
                self._mkey, fn, args, steps=steps, lanes=len(self.lanes),
                devices=self.trial.devices,
                group_id=self.trial.group_id,
                cache_key=(
                    "bucket", stack_bucket_key(template), len(self.lanes)
                ),
            )
            # Re-open after the AOT compile (see _TrialRun._device_seam).
            self._mreg.step_series(self._mkey).open_interval()
        if self._amon is not None and dt is not None:
            self._amon.observe_step(self._mkey, dt)

    def _emit_lane(self, kind: str, lane_k: int, trial_id=None, **data):
        """Lane-churn telemetry (retire/refill/fault/diverge/mask)."""
        bus = get_bus()
        if bus is not None:
            bus.emit(
                kind,
                trial_id=trial_id,
                lane=lane_k,
                group_id=self.trial.group_id,
                **data,
            )

    def _bump_steps(self, n: int) -> None:
        for lane in self.lanes:
            if lane is not None:
                lane["steps"] += n

    # -- lane supervision (chaos/retry support) ----------------------

    def _note_attempt_start(self, lane: dict) -> None:
        idx = lane["idx"]
        self._attempts[idx] = self._attempts.get(idx, 0) + 1
        if self._ledger is not None:
            self._ledger.attempt_start(
                lane["cfg"].trial_id,
                self._chashes.get(idx, ""),
                self._attempts[idx],
            )

    def _note_attempt_end(
        self, lane: dict, status: str, *, error: str = "", summary=None
    ) -> None:
        if self._ledger is not None:
            idx = lane["idx"]
            self._ledger.attempt_end(
                lane["cfg"].trial_id,
                self._chashes.get(idx, ""),
                self._attempts.get(idx, 1),
                status,
                error=error,
                summary=summary,
            )

    def lane_progress(self, idx: int) -> Optional[dict]:
        """Executed-work progress for config index ``idx`` if it is
        currently riding a live lane (stacked lanes always start from
        scratch, so resumed_from is 0 by construction)."""
        for lane in self.lanes:
            if lane is not None and lane["idx"] == idx:
                return {
                    "resumed_from_step": 0,
                    "steps_at_failure": lane["steps"],
                }
        return None

    def record_preempted(self, error_text: str) -> None:
        """Ledger 'preempted' events for every live lane — called when a
        preemption elsewhere in the sweep kills the driver (and this
        bucket with it)."""
        for lane in self.lanes:
            if lane is not None:
                self._note_attempt_end(
                    lane, "preempted", error=error_text,
                    summary=self.lane_progress(lane["idx"]),
                )

    def request_drain(self) -> None:
        """Arm the cooperative drain: :meth:`run` returns at the next
        round boundary instead of starting another round."""
        self._drain_requested = True

    def drain_snapshot(self, idxs, reason: str = "") -> None:
        """Snapshot every live lane in ``idxs`` at its epoch boundary
        (the PR 15 snapshot path, all lanes in one pass): each lane's
        slice is read out of the stacked state (compiled dynamic-index
        read), fetched device→host, seeded into the RAM snapshot cache
        (a same-process re-place restores without touching disk), and
        persisted to its ``trial-{id}/state.msgpack`` on ONE background
        writer thread — the classic runner's checkpoint protocol,
        bucket-wide. Callers must have driven :meth:`run` to a round
        boundary first (:meth:`request_drain`): only a boundary state
        resumes bit-identically through the classic scan restore."""
        wanted = set(idxs)
        jobs = []
        for k, lane in enumerate(self.lanes):
            if lane is None or lane["idx"] not in wanted:
                continue
            cfg: TrialConfig = lane["cfg"]
            lane_state = self.read_lane(self.state, np.int32(k))
            host_state = jax.device_get(lane_state)
            ckpt = os.path.join(
                self.out_dir, f"trial-{cfg.trial_id}", "state.msgpack"
            )
            meta = {
                **asdict(cfg),
                "completed_epochs": lane["epochs_done"],
                "step": int(host_state.step),
                "history": list(lane["history"]),
            }
            snapshot_cache().put(ckpt, host_state, meta)
            jobs.append((host_state, ckpt, meta))
        if not jobs or not self._is_writer:
            return
        self._join_ckpt()
        self._ckpt_thread = threading.Thread(
            target=self._write_drain_ckpts,
            args=(jobs, reason),
            daemon=False,
        )
        self._ckpt_thread.start()

    def _write_drain_ckpts(self, jobs, reason: str) -> None:
        try:
            for host_state, ckpt, meta in jobs:
                save_state(
                    host_state,
                    ckpt,
                    metadata=meta,
                    format=self._ckpt_format,
                )
        except BaseException as e:  # re-raised at the next join
            self._ckpt_error = e

    def _join_ckpt(self) -> None:
        if self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._ckpt_thread = None
        if self._ckpt_error is not None:
            e, self._ckpt_error = self._ckpt_error, None
            raise RuntimeError(
                f"stacked bucket g{self.trial.group_id}: drain "
                "checkpoint write failed"
            ) from e

    def _ckpt_idle(self) -> bool:
        """No drain persist in flight (the snapshot-fast drain's
        non-blocking poll; :meth:`_join_ckpt` is the blocking/raising
        sibling)."""
        t = self._ckpt_thread
        return t is None or not t.is_alive()

    def _stacked_fault_hook(self, batch_index: int, stacked):
        """Poison a DIVERGE-covered lane's slice of the (K, B, ...) host
        batch: the NaN flows through that lane only (the vmapped program
        keeps lanes independent), so exactly one trial diverges."""
        out = stacked
        for k, lane in enumerate(self.lanes):
            if lane is None:
                continue
            tid = lane["cfg"].trial_id
            step = self._round_step0.get(k, lane["steps"]) + batch_index
            if self._injector.diverge_covers(tid, step):
                if out is stacked:
                    out = np.array(stacked, copy=True)
                out[k] = self._injector.poison_batch(tid, step, out[k])
        return out

    def _round_start_faults(self) -> None:
        """Fire lane-scoped infra faults due inside the coming round.

        A faulted lane is retired and refilled through the same
        mask-and-refill path finished lanes take — the other K-1 lanes
        keep training in the same compiled program. HostPreemption is
        NOT lane-scoped (the host is going away): it propagates and
        fails the bucket, as a real preemption would.
        """
        if self._injector is None:
            return
        from multidisttorch_tpu.faults.inject import (
            HostPreemption,
            InfraFault,
        )

        round_len = self.data.num_batches
        k = 0
        while k < len(self.lanes):
            lane = self.lanes[k]
            if lane is None:
                k += 1
                continue
            tid = lane["cfg"].trial_id
            try:
                self._injector.step_hook(tid, lane["steps"], round_len)
                self._injector.data_hook(tid, lane["steps"], round_len)
            except HostPreemption:
                raise
            except InfraFault as e:
                self._fault_lane(k, e)
                # Re-scan lane k WITHOUT advancing: the refill occupant
                # is about to run its own first round, and its faults
                # due in [0, round_len) must fire now, not be skipped.
                # Bounded: max_fires caps firings, the retry budget
                # caps requeues, so the queue drains.
                continue
            k += 1

    def _fault_lane(self, k: int, exc: BaseException) -> None:
        """Infra fault scoped to one lane: retire it (no result capture
        — its weights are suspect), requeue per the retry budget, and
        refill the lane from the bucket queue."""
        lane = self.lanes[k]
        idx, cfg = lane["idx"], lane["cfg"]
        error_text = f"{type(exc).__name__}: {exc}"
        fails = self._infra_fails[idx] = self._infra_fails.get(idx, 0) + 1
        progress = {"resumed_from_step": 0, "steps_at_failure": lane["steps"]}
        retrying = self._retry is not None and self._retry.should_retry(
            fails, INFRA
        )
        self._emit_lane(
            "lane_fault",
            k,
            trial_id=cfg.trial_id,
            step=lane["steps"],
            error=error_text,
            infra_failures=fails,
            retrying=retrying,
        )
        if retrying:
            self._note_attempt_end(
                lane, "retrying", error=error_text, summary=progress
            )
            # Retry from scratch at the queue's tail: stacked lanes
            # checkpoint only at retirement, and the queue's natural
            # serialization stands in for backoff.
            self.queue.append((idx, cfg))
            self._log(
                f"Trial {cfg.trial_id} lane {k} FAULTED ({error_text}); "
                f"lane retired, trial requeued (infra failure {fails}), "
                f"{sum(l is not None for l in self.lanes) - 1} lanes "
                "continue"
            )
        else:
            result = TrialResult(
                trial_id=cfg.trial_id,
                group_id=self.trial.group_id,
                config=cfg,
                out_dir=os.path.join(self.out_dir, f"trial-{cfg.trial_id}"),
                status="failed",
                error=error_text,
                dataset=lane["data"].name,
                dataset_synthetic=lane["data"].synthetic,
                stacked=True,
                attempt=self._attempts.get(idx, 1),
            )
            self.results[idx] = result
            self._note_attempt_end(
                lane, "failed", error=error_text, summary=progress
            )
            self._log(
                f"Trial {cfg.trial_id} lane {k} FAILED ({error_text}); "
                "retry budget exhausted, lane freed"
            )
        self._refill_or_mask(k)

    def _diverge_lane(self, k: int, avg: float) -> None:
        """Terminal divergence scoped to one lane: record the result
        (never retried — the config reproduces its own NaN) and refill."""
        lane = self.lanes[k]
        idx, cfg = lane["idx"], lane["cfg"]
        err = DivergenceError(
            "lane epoch average train loss",
            avg,
            step=lane["steps"],
            trial_id=cfg.trial_id,
        )
        result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=self.trial.group_id,
            config=cfg,
            history=list(lane["history"]),
            out_dir=os.path.join(self.out_dir, f"trial-{cfg.trial_id}"),
            steps=lane["steps"],
            wall_s=time.time() - lane["t0"],
            host_syncs=self._host_syncs - lane["syncs0"],
            status="diverged",
            error=str(err),
            dataset=lane["data"].name,
            dataset_synthetic=lane["data"].synthetic,
            stacked=True,
            attempt=self._attempts.get(idx, 1),
        )
        self.results[idx] = result
        self._note_attempt_end(
            lane, "diverged", error=str(err),
            summary=_result_summary(result),
        )
        self._emit_lane(
            "lane_diverge",
            k,
            trial_id=cfg.trial_id,
            step=lane["steps"],
            avg_train_loss=avg,
        )
        self._log(
            f"Trial {cfg.trial_id} DIVERGED (stacked lane {k}, "
            f"non-finite loss at step {lane['steps']}); lane freed"
        )
        self._refill_or_mask(k)

    def _retire(self, k: int) -> None:
        """Capture lane k's result + checkpoint, then refill or mask."""
        lane = self.lanes[k]
        cfg: TrialConfig = lane["cfg"]
        lane_out_dir = os.path.join(self.out_dir, f"trial-{cfg.trial_id}")
        result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=self.trial.group_id,
            config=cfg,
            history=list(lane["history"]),
            out_dir=lane_out_dir,
            dataset=lane["data"].name,
            dataset_synthetic=lane["data"].synthetic,
            stacked=True,
        )
        last = lane["history"][-1]
        result.final_train_loss = last["avg_train_loss"]
        result.final_test_loss = last.get("test_loss", float("nan"))
        result.steps = lane["steps"]
        result.wall_s = time.time() - lane["t0"]
        result.host_syncs = self._host_syncs - lane["syncs0"]

        # Lane slice out of the stacked state: a compiled dynamic-index
        # read (traced k — every retirement reuses one executable).
        lane_state = self.read_lane(self.state, np.int32(k))
        # Memory books: a stacked lane's optimizer footprint is its
        # slice of the (replicated) stacked state — the number
        # comparable against an unstacked replicated or zero_update
        # twin in run_summary/sweep_top.
        from multidisttorch_tpu.parallel.fsdp import optimizer_state_bytes

        result.optimizer_state_bytes = optimizer_state_bytes(
            lane_state
        )["per_device_bytes"]
        _bus = get_bus()
        if _bus is not None:
            _bus.emit(
                "optimizer_state",
                trial_id=cfg.trial_id,
                group_id=self.trial.group_id,
                lane=k,
                per_device_bytes=result.optimizer_state_bytes,
                total_bytes=result.optimizer_state_bytes,
                zero_update=False,
            )
        if self._is_writer:
            if self._save_checkpoint:
                host_state = jax.device_get(lane_state)
                ckpt = os.path.join(lane_out_dir, "state.msgpack")
                save_state(
                    host_state,
                    ckpt,
                    metadata={
                        **asdict(cfg),
                        "completed_epochs": lane["epochs_done"],
                        "step": int(host_state.step),
                        "history": list(lane["history"]),
                    },
                    # Retired lanes ride the checkpoint data plane too:
                    # same-bucket lanes share one trial-dir-scoped
                    # chunk store per trial, and identical warm-start
                    # chunks dedup across retirements. Same format knob
                    # as the classic runner (the service threads its
                    # configured format through).
                    format=self._ckpt_format,
                )
                result.checkpoint = ckpt
            os.makedirs(lane_out_dir, exist_ok=True)
            with open(os.path.join(lane_out_dir, "metrics.json"), "w") as f:
                json.dump(
                    {
                        "trial_id": result.trial_id,
                        "group_id": result.group_id,
                        "config": asdict(cfg),
                        "dataset": result.dataset,
                        "dataset_synthetic": result.dataset_synthetic,
                        "history": result.history,
                        "wall_s": result.wall_s,
                        "steps": result.steps,
                        "stacked": True,
                    },
                    f,
                    indent=2,
                )
        result.attempt = self._attempts.get(lane["idx"], 1)
        self.results[lane["idx"]] = result
        self._note_attempt_end(
            lane, "completed", summary=_result_summary(result)
        )
        self._emit_lane(
            "lane_retire",
            k,
            trial_id=cfg.trial_id,
            step=lane["steps"],
            epochs=lane["epochs_done"],
            wall_s=round(result.wall_s, 6),
        )
        self._log(
            f"Trial {cfg.trial_id} done (stacked lane {k}). "
            f"time: {result.wall_s:f}"
        )
        self._refill_or_mask(k)

    def _refill_or_mask(self, k: int) -> None:
        """The mask-and-refill tail shared by retirement, lane faults,
        and lane divergence: pop the next queued config into lane ``k``
        (a compiled dynamic-index write — no recompilation), or mask the
        lane inactive when the queue is dry."""
        if self.queue:
            idx, nxt = self.queue.pop(0)
            self.lanes[k] = self._fresh_lane(idx, nxt)
            self._note_attempt_start(self.lanes[k])
            self.state = self.write_lane(
                self.state,
                self.trial.device_put(build_lane_state(self.model, nxt.seed)),
                np.int32(k),
            )
            # The data half of the refill: the new occupant's stream —
            # and, for a per-submission dataset, its own arrays — swap
            # into lane k with zero recompiles.
            self.data.set_lane(k, nxt.seed, dataset=self._data_of(idx))
            self._emit_lane("lane_refill", k, trial_id=nxt.trial_id)
            # Refill swaps a fresh lane state into the stacked tree —
            # a watermark moment (old + new lane buffers both live).
            if self._mreg is not None:
                tele_device.sample_memory(
                    self._mkey, self.trial.devices, where="lane_refill",
                    group_id=self.trial.group_id,
                )
            self._log(
                f"Trial {nxt.trial_id} refilled into stacked lane {k} "
                "(no recompilation)"
            )
        else:
            self.lanes[k] = None  # masked out by active=0.0
            self._emit_lane("lane_masked", k)
        self._refresh_lane_arrays()

    def unfinished(self) -> list[tuple[int, TrialConfig]]:
        """Config items not yet completed (failure-isolation support)."""
        live = [
            (lane["idx"], lane["cfg"])
            for lane in self.lanes
            if lane is not None and lane["idx"] not in self.results
        ]
        return live + list(self.queue)

    def _admit_programs(self) -> Iterator[None]:
        """Cooperative AOT admission for the bucket (see
        ``_TrialRun._admit_programs`` — same protocol, vmapped keys)."""
        if not self._aot_keys:
            return
        from multidisttorch_tpu.compile import programs as _cprog

        primary = "multi" if self.fused > 1 else "train"
        raw = {"train": self._sstep_raw, "multi": self._smulti_raw}
        lanes = len(self.lanes)
        taken, self._admission = yield from _aot_admit(
            self._aot_keys,
            raw,
            lambda: _cprog.stacked_avals(self._aot_template, lanes),
            self.state,
            primary,
        )
        if "train" in taken:
            self.sstep = taken["train"]
        if "multi" in taken:
            self.smulti = taken["multi"]

    def _note_first_dispatch(self) -> None:
        """Bucket sibling of ``_TrialRun._note_first_dispatch`` —
        group-scoped (no single trial owns the bucket's admission)."""
        self._first_dispatched = True
        bus = get_bus()
        if bus is not None:
            bus.emit(
                "first_dispatch",
                group_id=self.trial.group_id,
                lanes=len(self.lanes),
                **self._admission,
            )

    def run(self) -> Iterator[None]:
        yield from self._admit_programs()
        n_per_epoch = self.data.samples_per_epoch
        while any(lane is not None for lane in self.lanes):
            if self._drain_requested:
                # Cooperative drain: exit at this round boundary —
                # every live lane's state is at an exact epoch
                # boundary (epochs_done and history are settled for
                # the finished round), so drain_snapshot() writes
                # checkpoints the classic resume replays
                # bit-identically.
                return
            # Lane-scoped infra faults due this round fire BEFORE the
            # round dispatches: the faulted lane retires and refills,
            # the others never notice.
            self._round_start_faults()
            if not any(lane is not None for lane in self.lanes):
                break
            # Per-lane step counts at round start: the data fault hook
            # maps (lane, batch index) -> global optimizer step with
            # these (lane["steps"] itself advances mid-round).
            self._round_step0 = {
                k: lane["steps"]
                for k, lane in enumerate(self.lanes)
                if lane is not None
            }
            round_sum_dev = None  # (K,) on-device
            # Live lane count at round start: lanes only change at
            # round boundaries, so this tags every dispatch's metrics
            # mark with the bucket's true occupancy.
            k_live = sum(lane is not None for lane in self.lanes)
            # Fresh timing interval per round (see _TrialRun.run): the
            # gap since the last mark is boundary work — eval, lane
            # retirement/refill — not a dispatch.
            if self._mreg is not None:
                self._mreg.step_series(self._mkey).open_interval()

            def add(dev_sums):
                nonlocal round_sum_dev
                round_sum_dev = (
                    dev_sums
                    if round_sum_dev is None
                    else round_sum_dev + dev_sums
                )

            if self.smulti is None:
                for batch in self.data.round_batches():
                    self.state, m = self.sstep(
                        self.state, self.hypers, batch,
                        self.base_rngs, self._lane_steps(),
                    )
                    self._bump_steps(1)
                    if not self._first_dispatched:
                        self._note_first_dispatch()
                    add(m["loss_sum"])
                    if self._mreg is not None:
                        dt = self._mreg.step_mark(
                            self._mkey, round_sum_dev, lanes=k_live
                        )
                        self._device_seam(
                            dt, self.sstep,
                            (self.state, self.hypers, batch,
                             self.base_rngs, self._lane_steps()),
                        )
                    yield
            else:
                for start, chunk in self.data.round_chunks(self.fused):
                    s = chunk.shape[0]
                    if s == self.fused:
                        self.state, m = self.smulti(
                            self.state, self.hypers, chunk,
                            self.base_rngs, self._lane_steps(),
                        )
                        self._bump_steps(s)
                        if not self._first_dispatched:
                            self._note_first_dispatch()
                        add(m["loss_sum"].sum(axis=0))
                        if self._mreg is not None:
                            dt = self._mreg.step_mark(
                                self._mkey, round_sum_dev,
                                steps=s, lanes=k_live,
                            )
                            self._device_seam(
                                dt, self.smulti,
                                (self.state, self.hypers, chunk,
                                 self.base_rngs, self._lane_steps()),
                                steps=s,
                            )
                    else:
                        # Tail shorter than the compiled chunk: per-step
                        # stacked dispatches (no extra compilation).
                        for j in range(s):
                            self.state, m = self.sstep(
                                self.state, self.hypers, chunk[j],
                                self.base_rngs, self._lane_steps(),
                            )
                            self._bump_steps(1)
                            if not self._first_dispatched:
                                self._note_first_dispatch()
                            add(m["loss_sum"])
                            if self._mreg is not None:
                                dt = self._mreg.step_mark(
                                    self._mkey, round_sum_dev, lanes=k_live
                                )
                                self._device_seam(
                                    dt, self.sstep,
                                    (self.state, self.hypers, chunk[j],
                                     self.base_rngs, self._lane_steps()),
                                )
                    yield

            # One fetch for every lane's epoch average (O(1)-syncs rule:
            # the bucket pays per-round what one trial used to pay).
            self._host_syncs += 1
            train_sums = np.asarray(round_sum_dev)
            # Memory books ride the round boundary's existing sync.
            if self._mreg is not None:
                tele_device.sample_memory(
                    self._mkey, self.trial.devices, where="round",
                    group_id=self.trial.group_id,
                )
            # Input-stall books ride it too: one cumulative input_wait
            # event per round (docs/DATA.md) — the console/summary
            # mirror of the registry's StepSeries wait book.
            if self._wait_counts is not None:
                bus = get_bus()
                if bus is not None:
                    bus.emit(
                        "input_wait",
                        group_id=self.trial.group_id,
                        key=self._mkey,
                        wait_s=round(self._wait_counts["wait_s"], 6),
                        bytes=self._wait_counts["bytes"],
                        wall_s=round(time.time() - self._input_t0, 6),
                    )

            test_sums = None
            if self.test_iter is not None:
                test_dev = None
                for tbatch, tweights in self.test_iter.batches():
                    out = self.seval(self.state, self.hypers, tbatch, tweights)
                    test_dev = (
                        out["loss_sum"]
                        if test_dev is None
                        else test_dev + out["loss_sum"]
                    )
                    yield
                self._host_syncs += 1
                test_sums = np.asarray(test_dev)

            retiring = []
            diverged = []
            for k, lane in enumerate(self.lanes):
                if lane is None:
                    continue
                lane["epochs_done"] += 1
                avg = float(train_sums[k]) / n_per_epoch
                if not np.isfinite(avg):
                    # Terminal divergence, scoped to this lane — the
                    # vmapped program kept the NaN out of its
                    # neighbors (per-lane params/optimizer/losses).
                    diverged.append(k)
                    continue
                record = {"epoch": lane["epochs_done"], "avg_train_loss": avg}
                self._log(
                    "Trial {} ====> Epoch: {} Average loss: {:.4f}".format(
                        lane["cfg"].trial_id, lane["epochs_done"], avg
                    )
                )
                if test_sums is not None:
                    t = float(test_sums[k]) / self.test_iter.num_rows
                    record["test_loss"] = t
                    self._log(
                        "Trial {} ====> Test set loss: {:.4f}".format(
                            lane["cfg"].trial_id, t
                        )
                    )
                lane["history"].append(record)
                bus = get_bus()
                if bus is not None:
                    bus.emit(
                        "epoch",
                        trial_id=lane["cfg"].trial_id,
                        lane=k,
                        group_id=self.trial.group_id,
                        step=lane["steps"],
                        **record,
                    )
                if self._amon is not None:
                    self._amon.observe_loss(
                        lane["cfg"].trial_id,
                        epoch=lane["epochs_done"],
                        train_loss=avg,
                        lane=k,
                        group_id=self.trial.group_id,
                    )
                if lane["epochs_done"] >= lane["cfg"].epochs:
                    retiring.append(k)
            for k in diverged:
                self._diverge_lane(k, float(train_sums[k]) / n_per_epoch)
                yield
            for k in retiring:
                self._retire(k)
                yield
        jax.block_until_ready(self.state.params)


def run_hpo(
    configs: Sequence[TrialConfig],
    train_data: Dataset,
    test_data: Optional[Dataset] = None,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    num_groups: Optional[int] = None,
    out_dir: str = "results",
    shard_across_trials: bool = False,
    save_images: bool = True,
    save_checkpoints: bool = True,
    verbose: bool = True,
    model_builder=None,
    model_parallel: int = 1,
    param_shardings_builder=None,
    resilient: bool = False,
    resume: bool = False,
    profile_dir: Optional[str] = None,
    stack_trials: bool = False,
    stack_max_lanes: int = 8,
    retry: Optional[RetryPolicy] = None,
    fault_plan=None,
    ledger: bool = True,
    ckpt_keep_last: int = 1,
    agree_timeout_s: Optional[float] = None,
    precompile: Optional[bool] = None,
) -> list[TrialResult]:
    """Run the configs over disjoint submeshes, concurrently, with no
    cross-trial synchronization.

    ``groups`` defaults to ``setup_groups(num_groups or len(configs))``.
    **More configs than groups is legal**: excess configs queue, and a
    submesh picks up its next trial the moment its current one finishes
    (greedy in single-controller mode; in multi-controller SPMD the
    assignment is the deterministic least-predicted-load schedule of
    :func:`balanced_assignment` — every process must make identical
    scheduling decisions without communicating, and trial durations are
    predictable from the configs). Trials whose submesh has no local
    devices are skipped on this process (multi-controller membership,
    ``vae-hpo.py:200-202``).

    ``model_builder(cfg)`` swaps the model family (e.g. ``ConvVAE`` for
    the β-VAE CIFAR config) while reusing all scaffolding; default is
    the flagship MLP VAE.

    ``model_parallel=m`` carves each trial's submesh 2-D (data × model),
    and ``param_shardings_builder(trial, model)`` maps a trial to its
    weight shardings (e.g. ``models.vae.vae_tp_shardings(trial)`` for
    Megatron TP, ``models.moe_vae.moe_vae_ep_shardings`` for expert
    parallelism, ``parallel.fsdp.fsdp_param_shardings`` for ZeRO-style
    state sharding) — every train/eval/sample step then pins that
    layout. Within-trial model sharding composed with trial parallelism
    from one driver call; the reference is DP-only (SURVEY.md §2c).

    ``resilient=True`` isolates failures: a trial raising marks its
    result ``status="failed"`` (exception text in ``.error``), frees the
    submesh, and the sweep continues. Default re-raises (honest errors,
    SURVEY.md Q8). Works multi-controller too: deterministic failures
    resolve identically on every owner process by SPMD determinism, and
    writer-only host-I/O failures are agreed at setup/epoch boundaries
    through a submesh-scoped health reduction — one trial's death frees
    its submesh on every owning process with no world barrier (contrast
    the reference, where a failed rank hangs the world's collectives).

    ``resume=True`` restores each trial from its per-epoch checkpoint
    under ``{out_dir}/trial-{id}/`` (skipping fully-trained trials), so
    an interrupted sweep re-run completes only the remaining work.

    ``profile_dir`` wraps the whole sweep in a JAX profiler trace
    (TensorBoard/Perfetto-loadable, device timelines included on TPU) —
    the tool for confirming submeshes stay busy and finding host-side
    dispatch contention (SURVEY.md §7 "hard parts").

    ``stack_trials=True`` enables the trial-stacking execution mode
    (docs/STACKING.md): when trials outnumber groups, configs sharing a
    shape bucket (:func:`stack_bucket_key` — same architecture and
    batch size, any lr/beta/seed/epochs) run K-at-a-time on ONE submesh
    through one vmapped program (``train.steps.make_stacked_*``), with
    finished trials retired and refilled in place without recompiling.
    Falls back to the classic one-trial-per-group path when there is
    nothing to stack (too few configs, or unstackable knobs). At most
    ``stack_max_lanes`` trials share one program. Single-controller
    only, default model family only; the driver raises on contradictory
    settings (``resume``, ``shard_across_trials``, custom
    ``model_builder`` / weight sharding) rather than silently running a
    different sweep; ``save_images`` is ignored for stacked buckets
    (no reconstruction/sample grids — run image trials unstacked).

    **Trial supervision** (docs/RESILIENCE.md): ``retry=RetryPolicy()``
    turns infra-class failures (worker exceptions, data-iterator faults,
    checkpoint I/O — ``hpo/supervision.py``'s classification) into
    supervised retries with capped exponential backoff; each retry
    resumes from the trial's last *valid* checkpoint
    (``train.checkpoint.restore_latest_valid`` scans back past torn or
    corrupt files), falling back to scratch when none survives. A
    non-finite loss is classified as **divergence** — a terminal trial
    result (``status="diverged"``, recorded, never retried, never
    raised: deterministic training replays the same NaN). A
    ``HostPreemption`` always propagates out of ``run_hpo`` — per-trial
    retry is meaningless when the host is going away; restart the driver
    instead. In stacked mode a faulted lane is retired and refilled
    through the mask-and-refill machinery (the other K-1 lanes never
    stop); retried lanes restart from scratch.

    ``ledger=True`` (default) appends every attempt's config hash and
    outcome to ``{out_dir}/sweep_ledger.jsonl`` (crash-safe JSONL,
    ``hpo/ledger.py``); with ``resume=True`` a killed-and-restarted
    ``run_hpo`` skips trials the ledger settled (completed/diverged
    under a byte-identical config) and re-runs only unfinished ones —
    the driver itself is preemption-safe.

    ``fault_plan`` (a ``faults.FaultPlan`` or ``FaultInjector``) arms
    deterministic chaos injection through the driver/step/data/
    checkpoint hook seams — CI-grade recovery drills, see
    ``tools/chaos_run.py``. ``ckpt_keep_last=K`` retains K checkpoint
    generations per trial (scan-back depth for retry-with-resume).
    ``agree_timeout_s`` bounds every multi-host health agreement so a
    dead peer produces a diagnosable ``TimeoutError`` instead of an
    indefinite hang (default: ``MDT_AGREE_TIMEOUT_S`` env, else 600 s).

    **Elastic multi-host** (docs/RESILIENCE.md "Elastic multi-host"):
    ``resume="scan"`` is the elastic-restart resume mode — settled
    trials are skipped via the ledger like ``resume=True``, but
    unfinished trials restore through the supervised scan-back
    (tolerating the torn/corrupt checkpoints a killed host leaves
    behind), with a cross-host restore agreement on spanning submeshes
    (min-over-owners valid step). Every cross-host device sync in the
    driver is wedge-watchdog-bounded (``MDT_WEDGE_TIMEOUT_S``, default
    = the agreement deadline): a peer that stops dispatching produces
    a named ``WedgedCollective`` (classified as preemption) instead of
    a hang. SIGTERM/SIGINT trigger a graceful drain: pending
    checkpoint writes land, in-flight attempts are recorded
    ``preempted`` in the ledger, and ``HostPreemption`` is raised (a
    supervised worker exits ``cluster.PREEMPTION_EXIT_CODE``); a
    second signal kills immediately. ``tools/sweep_supervisor.py``
    turns these contracts into automatic world-shrink restarts.

    **Compile farm** (docs/COMPILE.md): ``precompile=True`` (default:
    the ``MDT_PRECOMPILE=1`` env) walks the sweep's pending configs at
    entry and AOT-compiles every distinct train program — shape bucket
    x baked scalar hypers x predicted submesh — on background worker
    threads, so trial admission takes a finished executable instead of
    paying ``lower→compile`` on the host loop. Admission to a program
    still mid-compile waits *cooperatively* (other submeshes keep
    stepping); a program the farm has not reached is claimed and
    compiled inline (the pre-farm behavior, now timed and attributed
    per bucket as ``compile_start``/``compile_end``/``cache_hit``
    telemetry). Single-controller, default model family — the same
    envelope as stacking; other sweeps silently skip the farm. Every
    compile lands in the process-lifetime executable registry, so
    bucket-twin trials, retries, and refilled lanes never recompile
    even with the farm off.

    Returns results for locally-run trials, in config order.
    """
    if profile_dir is not None:
        from multidisttorch_tpu.utils.profiling import profile_trace

        trace_ctx = profile_trace(profile_dir)
    else:
        import contextlib

        trace_ctx = contextlib.nullcontext()
    _install_drain_handlers()
    # The precompile farm (if the body starts one) is stashed here so
    # EVERY exit path — completion, failure isolation re-raise,
    # preemption, drain — tears it down: queued jobs are dropped and
    # in-flight compiles finish harmlessly into the registry.
    pool_holder: list = []
    try:
        with trace_ctx:
            return _run_hpo_body(
                configs,
                train_data,
                test_data,
                groups=groups,
                num_groups=num_groups,
                out_dir=out_dir,
                shard_across_trials=shard_across_trials,
                save_images=save_images,
                save_checkpoints=save_checkpoints,
                verbose=verbose,
                model_builder=model_builder,
                model_parallel=model_parallel,
                param_shardings_builder=param_shardings_builder,
                resilient=resilient,
                resume=resume,
                stack_trials=stack_trials,
                stack_max_lanes=stack_max_lanes,
                retry=retry,
                fault_plan=fault_plan,
                ledger=ledger,
                ckpt_keep_last=ckpt_keep_last,
                agree_timeout_s=agree_timeout_s,
                precompile=precompile,
                _pool_holder=pool_holder,
            )
    finally:
        for _pool in pool_holder:
            _pool.shutdown()
        _restore_drain_handlers()


def predicted_cost(cfg: TrialConfig, train_rows: int) -> int:
    """Relative duration estimate for one trial: optimizer steps to run.

    ``epochs`` is the reference's only duration knob (``vae-hpo.py:202``)
    and ``batch_size`` sets steps per epoch; both are known to every
    process before any trial starts, which is what lets the
    multi-controller scheduler balance load without communicating.
    """
    steps_per_epoch = max(1, train_rows // max(1, cfg.batch_size))
    return cfg.epochs * steps_per_epoch


def balanced_assignment(costs: Sequence[int], num_groups: int) -> list[int]:
    """Deterministic least-loaded assignment: config i → the group whose
    accumulated predicted cost is smallest (ties → lowest group index).

    Pure function of (costs, num_groups), so every process computes the
    identical schedule — the same no-communication constraint that
    forced the previous static round-robin. Least-loaded usually beats
    round-robin when epoch counts differ (costs [4,1,1,1] over 2 groups:
    round-robin loads (5,2), this gives (4,3)) but, like any online
    greedy rule, is not universally optimal (costs [2,1,1,2] favor
    round-robin); it never needs cost information round-robin lacks, and
    both are deterministic.
    """
    loads = [0] * num_groups
    out = []
    for c in costs:
        g = min(range(num_groups), key=lambda j: (loads[j], j))
        loads[g] += c
        out.append(g)
    return out


def _run_hpo_body(
    configs,
    train_data,
    test_data,
    *,
    groups,
    num_groups,
    out_dir,
    shard_across_trials,
    save_images,
    save_checkpoints,
    verbose,
    model_builder,
    model_parallel,
    param_shardings_builder,
    resilient,
    resume,
    stack_trials=False,
    stack_max_lanes=8,
    retry=None,
    fault_plan=None,
    ledger=True,
    ckpt_keep_last=1,
    agree_timeout_s=None,
    precompile=None,
    _pool_holder=None,
) -> list[TrialResult]:
    # Telemetry opt-in by environment (MDT_TELEMETRY[_DIR]) — a no-op
    # env read when off, and an explicit telemetry.configure() wins.
    from multidisttorch_tpu import telemetry as _telemetry

    _telemetry.configure_from_env()
    # Per-trial dataset references (docs/DATA.md): resolve every
    # distinct cfg.dataset ONCE at sweep entry (resolve_dataset's
    # process memo makes twin specs share one host array, preserving
    # the stacked gather's fused fast path). Resolution is
    # deterministic, so multi-controller processes agree without
    # communicating — but shard_across_trials partitions ONE shared
    # dataset across trials, which a per-trial dataset contradicts.
    if any(getattr(cfg, "pipeline_stages", 1) != 1 for cfg in configs):
        raise ValueError(
            "pipeline_stages > 1 trials are vectors of slice requests "
            "— run_hpo's equal-groups carve cannot host them. Submit "
            "them to the sweep service (multi-block placement, "
            "docs/SERVICE.md) or drive one directly with "
            "hpo.pipeline_run.run_pipeline_trial"
        )
    data_by_idx: dict[int, Dataset] = {}
    if any(getattr(cfg, "dataset", "") for cfg in configs):
        if shard_across_trials:
            raise ValueError(
                "per-trial cfg.dataset is incompatible with "
                "shard_across_trials (trial-sharding partitions the one "
                "shared dataset)"
            )
        from multidisttorch_tpu.data.store import resolve_dataset

        for i, cfg in enumerate(configs):
            if getattr(cfg, "dataset", ""):
                data_by_idx[i] = resolve_dataset(cfg.dataset)

    def data_of(i: int) -> Dataset:
        return data_by_idx.get(i, train_data)

    if groups is None:
        groups = setup_groups(
            num_groups if num_groups is not None else len(configs),
            model_parallel=model_parallel,
        )
    elif model_parallel != 1:
        raise ValueError(
            "model_parallel applies only when the driver carves the "
            "groups; carve your own with setup_groups(..., "
            "model_parallel=m) when passing groups="
        )
    if len(configs) < len(groups):
        raise ValueError(
            f"{len(configs)} configs but {len(groups)} device groups "
            "(fewer configs than groups would idle submeshes; carve "
            "fewer groups instead)"
        )
    # Multi-host failure isolation: failures must resolve identically on
    # every process owning a trial's submesh, or one process frees the
    # group while peers keep stepping it (desynchronized collectives —
    # the reference's failure mode is worse still: a dead rank hangs the
    # world, SURVEY.md §5). Two mechanisms, by failure class:
    #  - Deterministic failures (bad config, model build, NaN guards,
    #    data exhaustion): SPMD determinism raises them at the same
    #    dispatch point on every owner — identical local handling IS the
    #    agreement.
    #  - Writer-only host-I/O failures (image/checkpoint/metrics
    #    writes): deferred by _TrialRun._guard and agreed at setup /
    #    epoch boundaries via a submesh-scoped health reduction
    #    (collectives.group_all_ok) — no world barrier, unrelated trials
    #    unaffected.
    # Out of scope (documented): asymmetric failures *inside* the
    # dispatch stream (host OOM, device loss mid-epoch) — those desync
    # the submesh's program sequence itself and need runtime-level
    # preemption, which no SPMD framework recovers from at this layer.
    def needs_agreement(g: TrialMesh) -> bool:
        return resilient and jax.process_count() > 1 and g.spans_processes

    # --- trial supervision state (docs/RESILIENCE.md) ---------------
    injector = None
    if fault_plan is not None:
        from multidisttorch_tpu.faults.inject import FaultInjector
        from multidisttorch_tpu.faults.plan import FaultPlan

        if isinstance(fault_plan, FaultInjector):
            injector = fault_plan
        elif isinstance(fault_plan, FaultPlan):
            injector = FaultInjector(fault_plan)
        else:
            raise TypeError(
                f"fault_plan must be a FaultPlan or FaultInjector, got "
                f"{type(fault_plan).__name__}"
            )
        if jax.process_count() > 1:
            from multidisttorch_tpu.faults.plan import DIVERGE

            if any(s.kind == DIVERGE for s in injector.plan.specs):
                raise ValueError(
                    "fault_plan: DIVERGE injection is single-controller "
                    "only — the poison hook materializes the step's "
                    "batch host-side, which a process-spanning sharded "
                    "array cannot do. Drill divergence in a "
                    "single-process run; the other fault kinds work "
                    "multi-controller."
                )
    if agree_timeout_s is None:
        from multidisttorch_tpu.parallel.cluster import _env_timeout

        agree_timeout_s = _env_timeout("MDT_AGREE_TIMEOUT_S", 600.0)
    # The wedge watchdog's deadline for device-result fetches on
    # spanning submeshes (epoch/test loss, checkpoint gather,
    # completion drain): MDT_WEDGE_TIMEOUT_S, defaulting to the
    # agreement deadline so one knob bounds every cross-host sync.
    from multidisttorch_tpu.parallel.cluster import (
        _env_timeout as _wedge_env_timeout,
    )

    wedge_timeout_s = _wedge_env_timeout(
        "MDT_WEDGE_TIMEOUT_S", agree_timeout_s
    )
    # The sweep's durable control state: every attempt's config hash and
    # outcome. Writes are fsync'd JSONL appends (crash = at most one
    # torn, skipped line); only process 0 writes, every process reads
    # (skip decisions must be identical everywhere).
    chashes = {i: config_hash(asdict(cfg)) for i, cfg in enumerate(configs)}
    led = SweepLedger(
        out_dir, enabled=ledger, write=jax.process_index() == 0
    )
    prior_attempts = led.attempts() if led.enabled else {}
    attempts: dict[int, int] = {
        i: prior_attempts.get(chashes[i], 0) for i in range(len(configs))
    }
    # Retry budget bookkeeping is by infra FAILURE, not by attempt:
    # attempts also grow on preemption restarts, which must not eat the
    # budget (RetryPolicy.should_retry's contract).
    prior_fails = led.infra_failures() if led.enabled else {}
    infra_fails: dict[int, int] = {
        i: prior_fails.get(chashes[i], 0) for i in range(len(configs))
    }
    # Stacked-bucket SETUP failures are whole-bucket events (no lane
    # exists yet to attribute them to); their retry budget is counted
    # per bucket, keyed by the member-index tuple.
    bucket_setup_fails: dict[tuple, int] = {}

    results: dict[int, TrialResult] = {}
    skipped: set[int] = set()
    if resume and led.enabled:
        # Restart path: trials the ledger settled under a byte-identical
        # config are reconstructed from their recorded summary and never
        # scheduled — the driver re-runs only unfinished work.
        settled = led.finished()
        for i, cfg in enumerate(configs):
            rec = settled.get(chashes[i])
            if rec is None:
                continue
            status = (
                "resumed_complete"
                if rec.get("status") == "completed"
                else "diverged"
            )
            results[i] = _result_from_summary(cfg, rec, status)
            skipped.add(i)
        if skipped:
            log0(
                f"sweep ledger: {len(skipped)} of {len(configs)} trials "
                "already settled; re-running only the rest"
            )

    def make_run(
        trial: TrialMesh, i: int, cfg: TrialConfig, resume_mode,
        attempt: int = 1,
    ) -> _TrialRun:
        return _TrialRun(
            trial,
            cfg,
            data_of(i),
            test_data,
            out_dir,
            shard_across_trials=shard_across_trials,
            # Shard by submesh, not by config: with elastic scheduling
            # (more configs than groups) group_id::len(groups) is still a
            # valid partition of the dataset, config-count-based sharding
            # would leave rows unassigned.
            num_trials=len(groups),
            save_images=save_images,
            save_checkpoint=save_checkpoints,
            verbose=verbose,
            model_builder=model_builder,
            param_shardings_builder=param_shardings_builder,
            resume=resume_mode,
            agree_failures=needs_agreement(trial),
            agree_timeout_s=agree_timeout_s,
            wedge_timeout_s=wedge_timeout_s,
            injector=injector,
            ckpt_keep_last=ckpt_keep_last,
            attempt=attempt,
        )

    # Queue configs per group. Single-controller: one shared queue,
    # greedy — whichever submesh frees first takes the next config
    # (optimal when trials have unequal epoch counts). Multi-controller:
    # every process must make identical assignments WITHOUT
    # communicating, so the schedule is computed deterministically from
    # shared state (the configs themselves): each config goes to the
    # group with the least accumulated predicted cost (epochs x steps
    # per epoch — the knobs that set trial duration, vae-hpo.py:202).
    # Typically better than round-robin under unequal epoch counts
    # (queues are sized to their trials' predicted lengths up front; see
    # balanced_assignment's docstring for the caveat) while remaining
    # process-independent.
    single = jax.process_count() == 1
    if stack_trials:
        # Trial stacking is single-controller, default-model-family
        # territory; contradictory settings fail loudly rather than
        # silently running a different sweep than asked for.
        if not single:
            raise ValueError(
                "stack_trials: stacking is single-controller only (the "
                "stacked state lives on one submesh; multi-controller "
                "lane scheduling would need cross-process agreement)"
            )
        if resume:
            raise ValueError(
                "stack_trials is incompatible with resume= (lane "
                "restore into a stacked bucket is not implemented; run "
                "the resume sweep unstacked)"
            )
        if shard_across_trials:
            raise ValueError(
                "stack_trials is incompatible with shard_across_trials "
                "(stacked lanes each see the full dataset)"
            )
        if model_builder is not None or param_shardings_builder is not None \
                or model_parallel != 1:
            raise ValueError(
                "stack_trials supports the default VAE family with "
                "replicated weights only (custom model_builder / "
                "param_shardings_builder / model_parallel cannot share "
                "one vmapped program)"
            )

    # Work items: ("single", [(i, cfg)]) or ("bucket", [(i, cfg), ...]).
    # Stacking applies only when trials outnumber groups — otherwise
    # every trial gets its own submesh and stacking would only serialize.
    def build_items() -> list[tuple[str, list[tuple[int, TrialConfig]]]]:
        indexed = [
            (i, cfg) for i, cfg in enumerate(configs) if i not in skipped
        ]
        if not (stack_trials and len(configs) > len(groups)):
            return [("single", [item]) for item in indexed]
        buckets: dict[tuple, list] = {}
        singles: list = []
        for item in indexed:
            if config_is_stackable(item[1]):
                # Co-pack key = shape bucket + dataset SHAPE CLASS
                # (dim, batches/epoch) — never dataset identity, so
                # trials reading different datasets still share one
                # vmapped program (heterogeneous lanes).
                key = (
                    stack_bucket_key(item[1]),
                    data_shape_sig(data_of(item[0]), item[1].batch_size),
                )
                buckets.setdefault(key, []).append(item)
            else:
                singles.append(item)
        items = []
        for members in buckets.values():
            if len(members) >= 2:
                items.append(("bucket", members))
            else:
                singles.extend(members)
        items.extend(("single", [m]) for m in singles)
        # Don't idle submeshes behind one mega-bucket: split the largest
        # bucket until there is at least one work item per group (or
        # nothing left to split).
        bus = get_bus()
        while len(items) < len(groups):
            big = max(
                (it for it in items if it[0] == "bucket" and len(it[1]) >= 4),
                key=lambda it: len(it[1]),
                default=None,
            )
            if big is None:
                break
            items.remove(big)
            half = len(big[1]) // 2
            items.append(("bucket", big[1][:half]))
            items.append(("bucket", big[1][half:]))
            if bus is not None:
                bus.emit(
                    "stack_split",
                    members=[cfg.trial_id for _, cfg in big[1]],
                    split_at=half,
                )
        # Deterministic order: by first member's config index.
        items.sort(key=lambda it: it[1][0][0])
        if bus is not None:
            # Stacking decisions are telemetry: which trials share a
            # compiled program (and which ran classic) explains every
            # downstream lane event and throughput number.
            for kind_, members in items:
                if kind_ == "bucket":
                    bus.emit(
                        "stack_bucket",
                        members=[cfg.trial_id for _, cfg in members],
                        bucket_key=str(stack_bucket_key(members[0][1])),
                    )
            bus.emit(
                "stack_plan",
                buckets=sum(1 for it in items if it[0] == "bucket"),
                singles=sum(1 for it in items if it[0] == "single"),
            )
        return items

    # Queue items are (kind, members, ready_at): "single"/"retry" carry
    # one (i, cfg); "bucket" carries the stacked members. ready_at > now
    # = a retry still in its backoff window (skipped, not blocking —
    # other queued work runs first).
    shared = [(k, m, 0.0) for k, m in build_items()]
    # Background AOT precompile farm (docs/COMPILE.md): the work plan
    # above names every distinct program this sweep will compile, so
    # compile them NOW on worker threads — overlapped with the first
    # trials' setup and training — instead of inline at each admission.
    # Same eligibility envelope as the AOT admission path; the group
    # prediction (item j -> group j % n) only gates WHICH submesh an
    # executable is pinned to — a misprediction is a registry miss and
    # an inline compile, never a wrong program.
    if precompile is None:
        precompile = os.environ.get("MDT_PRECOMPILE") == "1"
    if (
        precompile
        and single
        and model_builder is None
        and param_shardings_builder is None
        and os.environ.get("MDT_AOT_ADMISSION", "1") != "0"
    ):
        from multidisttorch_tpu.compile.farm import PrecompilePool

        _farm = PrecompilePool()
        _farm.plan_sweep(
            [(k, m) for k, m, _ in shared],
            groups,
            max_lanes=stack_max_lanes,
        )
        if _pool_holder is not None:
            _pool_holder.append(_farm)
    per_group: dict[int, list] = {g.group_id: [] for g in groups}
    if not single:
        assignment = balanced_assignment(
            [
                predicted_cost(cfg, len(data_of(i)))
                for i, cfg in enumerate(configs)
            ],
            len(groups),
        )
        for i, cfg in enumerate(configs):
            if i in skipped:
                continue
            per_group[groups[assignment[i]].group_id].append(
                ("single", [(i, cfg)], 0.0)
            )
    queue_of = (
        (lambda g: shared) if single else (lambda g: per_group[g.group_id])
    )

    local_groups = [g for g in groups if g.is_local_member]
    # group -> (kind, config_index_or_None, run, generator) in flight
    active: dict[int, tuple] = {}

    def fail_items(g, members, error_text, *, status="failed",
                   progress_of=None) -> None:
        for i, cfg in members:
            if attempts.get(i, 0) == 0:
                # A member that never started (queued behind a bucket
                # that broke): this failure IS its first attempt — pair
                # a start with the end so the ledger's attempt history
                # stays well-formed and attempt numbering stays 1-based.
                attempts[i] = 1
                led.attempt_start(cfg.trial_id, chashes[i], 1)
            results[i] = TrialResult(
                trial_id=cfg.trial_id,
                group_id=g.group_id,
                config=cfg,
                status=status,
                error=error_text,
                attempt=attempts[i],
            )
            led.attempt_end(
                cfg.trial_id, chashes[i], attempts[i],
                status, error=error_text,
                summary=progress_of(i) if progress_of is not None else None,
            )

    def attempt_progress(run: Optional[_TrialRun]) -> dict:
        """Executed-work accounting for a failed/interrupted attempt
        (the chaos bench's goodput input)."""
        if run is None:
            return {"resumed_from_step": 0, "steps_at_failure": 0}
        return {
            "resumed_from_step": run.result.resumed_from_step,
            "steps_at_failure": run._step_no,
        }

    def schedule_retry(g: TrialMesh, i, cfg, error_text, progress=None) -> bool:
        """Consume one unit of the infra retry budget; returns False
        when the failure class or budget says the trial is done
        retrying."""
        if retry is None:
            return False
        fails = infra_fails[i] = infra_fails.get(i, 0) + 1
        if not retry.should_retry(fails, INFRA):
            return False
        # Backoff deadlines are wall-clock and therefore PROCESS-LOCAL;
        # on a spanning submesh every owner must make identical
        # scheduling decisions without communicating, so multi-
        # controller retries requeue immediately (FIFO order is shared
        # state; clocks are not). key= decorrelates jittered backoff
        # across trials felled by the same fault (thundering herd).
        delay = retry.backoff_s(fails, key=cfg.trial_id) if single else 0.0
        bus = get_bus()
        if bus is not None:
            bus.emit(
                "retry_scheduled",
                trial_id=cfg.trial_id,
                group_id=g.group_id,
                backoff_s=delay,
                infra_failures=fails,
                error=error_text,
            )
        led.attempt_end(
            cfg.trial_id, chashes[i], attempts[i], "retrying",
            error=error_text, summary=progress,
        )
        queue_of(g).append(("retry", [(i, cfg)], time.time() + delay))
        log0(
            f"Trial {cfg.trial_id} FAULTED ({error_text}); retrying from "
            f"last valid checkpoint in {delay:.2f}s "
            f"(infra failure {fails} of {retry.max_retries + 1} budget)",
            trial=g,
        )
        return True

    def record_preempted_peers(
        error_text: str = "host preemption (sweep-wide)",
    ) -> None:
        """A preemption (or drain) kills the whole driver, not one
        trial: every other in-flight attempt (single runs AND
        stacked-bucket lanes) dies with it. Record them all so restart
        accounting and the chaos goodput math see the full picture —
        after landing any in-flight checkpoint write (best-effort: the
        resumed sweep restores from it, so a write racing the death
        must finish, not vanish with its thread)."""
        for _gid, (k2, i2, run2, _g2) in list(active.items()):
            if k2 == "single":
                try:
                    run2._join_ckpt()
                except Exception:  # noqa: BLE001 — recording must go on
                    pass
                led.attempt_end(
                    run2.cfg.trial_id, chashes[i2], attempts[i2],
                    "preempted", error=error_text,
                    summary=attempt_progress(run2),
                )
            else:
                run2.record_preempted(error_text)

    def next_ready_at() -> Optional[float]:
        queues = [shared] if single else [
            per_group[g.group_id] for g in local_groups
        ]
        deadlines = [item[2] for q in queues for item in q]
        return min(deadlines) if deadlines else None

    def start_next(g: TrialMesh) -> bool:
        q = queue_of(g)
        for _ in range(len(q)):
            kind, members, ready_at = q.pop(0)
            if ready_at > time.time():
                q.append((kind, members, ready_at))  # backoff not over
                continue
            if kind == "bucket":
                try:
                    brun = _StackedBucketRun(
                        g, members, train_data, test_data, out_dir,
                        max_lanes=stack_max_lanes,
                        save_checkpoint=save_checkpoints,
                        verbose=verbose,
                        injector=injector,
                        retry=retry,
                        ledger=led,
                        attempts=attempts,
                        chashes=chashes,
                        infra_fails=infra_fails,
                        datasets={
                            i: data_by_idx[i]
                            for i, _ in members
                            if i in data_by_idx
                        },
                    )
                except Exception as e:  # noqa: BLE001 — setup isolation
                    error_text = f"{type(e).__name__}: {e}"
                    # Classified ONCE per failure: classification also
                    # emits the failure_classified telemetry event, and
                    # re-calling would duplicate it in the stream.
                    setup_class = classify_failure(e)
                    if setup_class == PREEMPTION:
                        # The host (or a peer) is gone: even resilient
                        # sweeps stop; the ledger sees every in-flight
                        # attempt before the driver dies.
                        fail_items(
                            g, members, error_text, status="preempted"
                        )
                        record_preempted_peers()
                        raise
                    # Same contract as the single-trial setup path: a
                    # transient infra fault (loader init, filesystem)
                    # gets the retry budget before K trials are failed
                    # permanently. Budget is per-bucket (no lane exists
                    # yet to charge), requeued at the queue's tail.
                    key = tuple(i for i, _ in members)
                    fails = bucket_setup_fails[key] = (
                        bucket_setup_fails.get(key, 0) + 1
                    )
                    if (
                        retry is not None
                        and setup_class == INFRA
                        and retry.should_retry(fails, INFRA)
                    ):
                        delay = (
                            retry.backoff_s(fails, key=members[0][0])
                            if single
                            else 0.0
                        )
                        q.append(("bucket", members, time.time() + delay))
                        log0(
                            f"Stacked bucket of {len(members)} trials "
                            f"FAULTED at setup ({error_text}); retrying "
                            f"in {delay:.2f}s (setup failure {fails} of "
                            f"{retry.max_retries + 1} budget)",
                            trial=g,
                        )
                        continue
                    fail_items(g, members, error_text)
                    if not resilient:
                        raise
                    log0(
                        f"Stacked bucket of {len(members)} trials FAILED "
                        f"at setup ({error_text}); sweep continues",
                        trial=g,
                    )
                    continue
                active[g.group_id] = ("bucket", None, brun, brun.run())
                return True
            i, cfg = members[0]
            attempts[i] += 1
            led.attempt_start(cfg.trial_id, chashes[i], attempts[i])
            # Retries resume via the scan-back path (tolerates the
            # torn/corrupt checkpoints a fault may have left); first
            # attempts keep the user-facing strict resume semantics.
            resume_mode = "scan" if kind == "retry" else resume
            err: Optional[BaseException] = None
            run: Optional[_TrialRun] = None
            try:
                run = make_run(g, i, cfg, resume_mode, attempt=attempts[i])
            except Exception as e:  # noqa: BLE001 — setup failure isolation
                err = e
            if needs_agreement(g):
                # Setup agreement: owners of a spanning submesh must all
                # start stepping or all skip — an asymmetric setup
                # failure (e.g. one host's data path) would otherwise
                # leave peers dispatching a trial that never runs here.
                from multidisttorch_tpu.parallel.cluster import (
                    WedgedCollective,
                )
                from multidisttorch_tpu.parallel.collectives import (
                    group_all_ok,
                )

                ok = group_all_ok(
                    g,
                    err is None,
                    timeout_s=agree_timeout_s,
                    what=f"trial {cfg.trial_id} setup agreement",
                    error_cls=WedgedCollective,
                )
            else:
                ok = err is None
            if not ok:
                error_text = (
                    f"{type(err).__name__}: {err}"
                    if err is not None
                    else "setup failed on a peer owner process"
                )
                # A broken setup (bad restore, dead data path) is an
                # infra fault like any other: supervised sweeps retry it
                # (the retry's scan-resume is what recovers a trial
                # whose strict resume chokes on a corrupt checkpoint).
                # FATAL setup errors — the strict-resume integrity
                # guards (UnretryableError) — are the exception: they
                # exist to stop for a human, and a scan-retry would
                # retrain over the checkpoint the guard protected.
                fatal = (
                    err is not None and classify_failure(err) == FATAL
                )
                if not fatal and schedule_retry(g, i, cfg, error_text):
                    continue
                results[i] = TrialResult(
                    trial_id=cfg.trial_id,
                    group_id=g.group_id,
                    config=cfg,
                    status="failed",
                    error=error_text,
                    attempt=attempts[i],
                )
                led.attempt_end(
                    cfg.trial_id, chashes[i], attempts[i], "failed",
                    error=error_text, summary=attempt_progress(run),
                )
                if not resilient:
                    if err is not None:
                        raise err
                    raise RuntimeError(error_text)
                log0(
                    f"Trial {cfg.trial_id} FAILED at setup "
                    f"({error_text}); sweep continues",
                    trial=g,
                )
                continue
            active[g.group_id] = ("single", i, run, run.run())
            return True
        return False

    bus = get_bus()
    if bus is not None:
        # Fleet identity rides the sweep header too (not just the
        # per-event tags): the console's one-line summary of a merged
        # stream needs "whose sweep_start is this" without scanning
        # tags. Only stamped when tagged — an untagged single-host
        # stream must stay byte-identical.
        fleet_id = {}
        if bus.host is not None:
            fleet_id["host_slot"] = bus.host
        if bus.world is not None:
            fleet_id["world_epoch"] = bus.world
        bus.emit(
            "sweep_start",
            configs=len(configs),
            groups=len(groups),
            stacked=bool(stack_trials),
            resume=bool(resume),
            resilient=bool(resilient),
            skipped_settled=len(skipped),
            **fleet_id,
        )

    def drain_now():
        from multidisttorch_tpu.faults.inject import (
            HostPreemption as _Drained,
        )

        sig = _DRAIN["sig"]
        error_text = f"graceful drain on signal {sig}"
        dbus = get_bus()
        if dbus is not None:
            dbus.emit("sweep_drain", signal=int(sig), in_flight=len(active))
        record_preempted_peers(error_text)
        raise _Drained(
            f"{error_text}: in-flight work checkpointed to the last "
            "epoch boundary and recorded in the ledger; resume with "
            "run_hpo(resume=True)"
        )

    for g in local_groups:
        start_next(g)

    # Cooperative round-robin: one async step dispatch per trial (or
    # stacked bucket — K trials per dispatch) per cycle. A finished (or
    # failed) item frees its submesh, which immediately starts its next
    # queued work — the sweep's wall-clock is bounded by real work,
    # never by barriers (Q3 fixed). Retries waiting out their backoff
    # never block live work; when ONLY backoff items remain, the loop
    # sleeps to the earliest deadline.
    while True:
        if _DRAIN["sig"] is not None:
            drain_now()
        for g in local_groups:
            if g.group_id not in active:
                start_next(g)  # a backoff retry may have matured
        if not active:
            deadline = next_ready_at()
            if deadline is None:
                break
            # Sliced sleep: a SIGTERM during a long backoff wait only
            # sets the drain flag (PEP 475 resumes the sleep), so one
            # monolithic sleep of up to backoff_max_s would outlast a
            # supervisor's kill grace and forfeit the drain. Wake every
            # quarter-second to honor the flag promptly.
            while time.time() < deadline and _DRAIN["sig"] is None:
                time.sleep(
                    min(0.25, max(0.0, deadline - time.time()) + 1e-3)
                )
            continue
        for g in local_groups:
            if g.group_id not in active:
                continue
            kind, i, run, gen = active[g.group_id]
            try:
                next(gen)
            except StopIteration:
                if kind == "bucket":
                    results.update(run.results)
                else:
                    run.result.attempt = attempts[i]
                    results[i] = run.result
                    led.attempt_end(
                        run.cfg.trial_id, chashes[i], attempts[i],
                        "completed", summary=_result_summary(run.result),
                    )
                del active[g.group_id]
                start_next(g)
            except Exception as e:  # noqa: BLE001 — failure isolation
                error_text = f"{type(e).__name__}: {e}"
                failure_class = classify_failure(
                    e,
                    trial_id=(
                        None if kind == "bucket" else run.cfg.trial_id
                    ),
                )
                if kind == "bucket":
                    # Lanes already retired keep their completed
                    # results; everything in flight or queued in the
                    # bucket fails together (they shared the broken
                    # program/state). Lane-scoped faults never reach
                    # here — the bucket absorbs them via mask-and-
                    # refill; this path is bucket-wide breakage.
                    results.update(run.results)
                    status = (
                        "preempted"
                        if failure_class == PREEMPTION
                        else "failed"
                    )
                    fail_items(
                        g, run.unfinished(), error_text, status=status,
                        progress_of=(
                            run.lane_progress
                            if failure_class == PREEMPTION
                            else None
                        ),
                    )
                    del active[g.group_id]
                    if failure_class == PREEMPTION:
                        record_preempted_peers()
                        raise
                    if not resilient:
                        raise
                    log0(
                        f"Stacked bucket FAILED ({error_text}); "
                        "submesh freed, sweep continues",
                        trial=g,
                    )
                    start_next(g)
                    continue
                del active[g.group_id]
                # Drain any in-flight checkpoint write before freeing the
                # submesh: run_hpo must not return while a writer thread
                # is still mutating result.checkpoint, and a failed write
                # must surface in the error, not vanish with the thread.
                try:
                    run._join_ckpt()
                except Exception as ce:  # noqa: BLE001
                    error_text += f"; also: {type(ce).__name__}: {ce}"
                if failure_class == PREEMPTION:
                    # The host is going away (or a peer already did, for
                    # an agreement TimeoutError): no per-trial retry
                    # makes sense, and even a resilient sweep must stop.
                    # The ledger records EVERY in-flight attempt — the
                    # raising trial and its still-running peers, single
                    # runs and stacked lanes alike, since they all die
                    # with the driver — so restart accounting and resume
                    # decisions see the whole picture; a restarted
                    # run_hpo(resume=True) re-runs only unfinished work.
                    led.attempt_end(
                        run.cfg.trial_id, chashes[i], attempts[i],
                        "preempted", error=error_text,
                        summary=attempt_progress(run),
                    )
                    record_preempted_peers()
                    raise
                if failure_class == DIVERGENCE:
                    # Terminal RESULT, not an error: the config drove
                    # training to a non-finite loss, and a deterministic
                    # re-run reproduces it. Recorded; never retried;
                    # never raised.
                    run.result.status = "diverged"
                    run.result.error = error_text
                    run.result.attempt = attempts[i]
                    # Steps executed up to detection: the work that
                    # produced the terminal verdict (normally stamped at
                    # completion, which a diverged run never reaches).
                    run.result.steps = run._step_no
                    results[i] = run.result
                    led.attempt_end(
                        run.cfg.trial_id, chashes[i], attempts[i],
                        "diverged", error=error_text,
                        summary=_result_summary(run.result),
                    )
                    log0(
                        f"Trial {run.cfg.trial_id} DIVERGED "
                        f"({error_text}); recorded as terminal result, "
                        "submesh freed",
                        trial=g,
                    )
                    start_next(g)
                    continue
                if failure_class != FATAL and schedule_retry(
                    g, i, run.cfg, error_text,
                    progress=attempt_progress(run),
                ):
                    start_next(g)
                    continue
                run.result.status = "failed"
                run.result.error = error_text
                run.result.attempt = attempts[i]
                # Work executed up to the failure (the completion path
                # never stamped it) — consumers of the returned results
                # see real counts, not zero, same as the diverged branch.
                run.result.steps = run._step_no
                results[i] = run.result
                led.attempt_end(
                    run.cfg.trial_id, chashes[i], attempts[i], "failed",
                    error=error_text, summary=attempt_progress(run),
                )
                if not resilient:
                    raise
                log0(
                    f"Trial {run.cfg.trial_id} FAILED ({run.result.error}); "
                    "submesh freed, sweep continues",
                    trial=g,
                )
                start_next(g)
    bus = get_bus()
    if bus is not None:
        statuses: dict[str, int] = {}
        for r in results.values():
            statuses[r.status] = statuses.get(r.status, 0) + 1
        bus.emit("sweep_end", results=len(results), statuses=statuses)
    return [results[i] for i in sorted(results)]
