"""Host-side HPO driver: N concurrent trials on N disjoint submeshes.

Rebuild of the reference's trial dispatch (``/root/reference/
vae-hpo.py:177-202``), where each process loops over all groups, finds
the one it belongs to, and runs a DDP trial whose only hyperparameter is
``epochs + group_id``. Redesigned per SURVEY.md §7:

- **Real per-trial configs** (:class:`TrialConfig`: lr, β, epochs,
  batch size, seed, model dims — generalizing quirk Q7).
- **Cooperative round-robin dispatch**: all trials' jit steps are
  enqueued from one host loop; JAX's async dispatch keeps every submesh
  busy while the host cycles. A fast trial finishes and frees its
  submesh immediately — **no cross-trial barrier anywhere** (fixes Q3,
  where the reference's world-scoped barriers serialize the sweep on the
  slowest trial).
- **Per-trial output dirs** ``{out_dir}/trial-{id}/`` (fixes Q4's
  ``results-{rank}`` collision where group 0 and 1 overwrite each
  other's PNGs).
- In multi-controller SPMD each process runs only the trials whose
  submesh intersects its local devices (``TrialMesh.is_local_member``) —
  the same membership contract as the reference's
  ``dist.get_rank(group) >= 0`` (``vae-hpo.py:201``).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field, asdict
from typing import Iterator, Optional, Sequence

import jax
import numpy as np
import optax

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import TrialDataIterator
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import TrialMesh, setup_groups
from multidisttorch_tpu.train.checkpoint import save_state
from multidisttorch_tpu.train.steps import (
    create_train_state,
    make_eval_step,
    make_sample_step,
    make_train_step,
)
from multidisttorch_tpu.utils.imaging import save_image_grid
from multidisttorch_tpu.utils.logging import log0


@dataclass(frozen=True)
class TrialConfig:
    """One trial's hyperparameters (the reference's single knob was
    ``epochs + group_id``, ``vae-hpo.py:202``)."""

    trial_id: int
    epochs: int = 3
    batch_size: int = 128
    lr: float = 1e-3  # reference Adam lr, vae-hpo.py:131
    beta: float = 1.0
    seed: int = 0
    hidden_dim: int = 400
    latent_dim: int = 20
    log_interval: int = 10  # reference train log cadence, vae-hpo.py:61


@dataclass
class TrialResult:
    trial_id: int
    group_id: int
    config: TrialConfig
    history: list = field(default_factory=list)  # per-epoch dicts
    final_train_loss: float = float("nan")  # per-sample avg, last epoch
    final_test_loss: float = float("nan")
    wall_s: float = 0.0
    steps: int = 0
    out_dir: str = ""
    checkpoint: str = ""


class _TrialRun:
    """One trial's full lifecycle as a cooperative generator.

    Each ``next()`` dispatches exactly one train step (async) and
    returns; host-device syncs happen only at the reference's logging
    cadence and at epoch boundaries. The generator shape is what makes
    the no-barrier scheduling work: the driver interleaves ``next()``
    across trials, so every submesh has work queued at all times.
    """

    def __init__(
        self,
        trial: TrialMesh,
        cfg: TrialConfig,
        train_data: Dataset,
        test_data: Optional[Dataset],
        out_dir: str,
        *,
        shard_across_trials: bool = False,
        num_trials: int = 1,
        save_images: bool = True,
        save_checkpoint: bool = True,
        verbose: bool = True,
        model_builder=None,
    ):
        self.trial = trial
        self.cfg = cfg
        self.out_dir = os.path.join(out_dir, f"trial-{cfg.trial_id}")
        self.result = TrialResult(
            trial_id=cfg.trial_id,
            group_id=trial.group_id,
            config=cfg,
            out_dir=self.out_dir,
        )
        self._save_images = save_images
        self._save_checkpoint = save_checkpoint
        self._verbose = verbose
        self._test_data = test_data

        if model_builder is None:
            model = VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
        else:
            model = model_builder(cfg)
        tx = optax.adam(cfg.lr)
        self.model, self.tx = model, tx
        self.state = create_train_state(
            trial, model, tx, jax.random.key(cfg.seed)
        )
        self.train_step = make_train_step(trial, model, tx, beta=cfg.beta)
        self.eval_step = make_eval_step(trial, model, beta=cfg.beta)
        self.sample_step = make_sample_step(trial, model)
        self.train_iter = TrialDataIterator(
            train_data,
            trial,
            cfg.batch_size,
            seed=cfg.seed,
            shard_across_trials=shard_across_trials,
            num_trials=num_trials,
        )
        self.test_iter = (
            TrialDataIterator(test_data, trial, cfg.batch_size, seed=cfg.seed)
            if test_data is not None and len(test_data) >= cfg.batch_size
            else None
        )
        self._key = jax.random.key(cfg.seed + 1)

    def _log(self, *args):
        if self._verbose:
            log0(*args, trial=self.trial)

    def run(self) -> Iterator[None]:
        cfg = self.cfg
        t0 = time.time()
        n_per_epoch = self.train_iter.samples_per_epoch
        step_no = 0
        for epoch in range(1, cfg.epochs + 1):
            epoch_loss_sums = []
            for i, batch in enumerate(self.train_iter.epoch(epoch)):
                rng = jax.random.fold_in(self._key, step_no)
                self.state, metrics = self.train_step(self.state, batch, rng)
                step_no += 1
                epoch_loss_sums.append(metrics["loss_sum"])  # device value
                if i % cfg.log_interval == 0:
                    # sync point for THIS trial only (reference logs
                    # loss.item() here, vae-hpo.py:76-86)
                    per_sample = float(metrics["loss_sum"]) / cfg.batch_size
                    self._log(
                        "Train Epoch: {} [{}/{} ({:.0f}%)]\tLoss: {:.6f}".format(
                            epoch,
                            i * cfg.batch_size,
                            n_per_epoch,
                            100.0 * i / self.train_iter.num_batches,
                            per_sample,
                        )
                    )
                yield  # hand the host loop to the next trial

            avg = float(
                np.sum([float(s) for s in epoch_loss_sums])
            ) / n_per_epoch
            self._log(
                "====> Epoch: {} Average loss: {:.4f}".format(epoch, avg)
            )
            epoch_record = {"epoch": epoch, "avg_train_loss": avg}

            if self.test_iter is not None:
                test_sum, test_n, first_batch, first_recon = 0.0, 0, None, None
                for j, tbatch in enumerate(self.test_iter.epoch(0)):
                    out = self.eval_step(self.state, tbatch)
                    test_sum += float(out["loss_sum"])
                    test_n += tbatch.shape[0]
                    if j == 0:
                        first_batch = np.asarray(tbatch)
                        first_recon = np.asarray(out["recon"])
                    yield
                test_avg = test_sum / test_n
                self._log("====> Test set loss: {:.4f}".format(test_avg))
                epoch_record["test_loss"] = test_avg
                self.result.final_test_loss = test_avg
                if self._save_images and first_batch is not None:
                    # input-vs-reconstruction grid (vae-hpo.py:106-116)
                    n = min(8, first_batch.shape[0])
                    comparison = np.concatenate(
                        [first_batch[:n], first_recon[:n]]
                    )
                    save_image_grid(
                        comparison,
                        os.path.join(
                            self.out_dir, f"reconstruction_{epoch}.png"
                        ),
                        nrow=n,
                    )

            if self._save_images:
                # prior-sample grid (vae-hpo.py:163-170)
                # sample keys live in a disjoint fold_in range (steps
                # count up from 0; fold_in data must be non-negative)
                samples = np.asarray(
                    self.sample_step(
                        self.state, jax.random.fold_in(self._key, 2**30 + epoch)
                    )
                )
                save_image_grid(
                    samples, os.path.join(self.out_dir, f"sample_{epoch}.png")
                )

            self.result.history.append(epoch_record)
            self.result.final_train_loss = avg

        # drain the pipeline so wall-clock covers real completion
        jax.block_until_ready(self.state.params)
        self.result.wall_s = time.time() - t0
        self.result.steps = step_no
        if self._save_checkpoint:
            self.result.checkpoint = save_state(
                self.state,
                os.path.join(self.out_dir, "state.msgpack"),
                metadata=asdict(cfg),
            )
        os.makedirs(self.out_dir, exist_ok=True)
        with open(os.path.join(self.out_dir, "metrics.json"), "w") as f:
            json.dump(
                {
                    "trial_id": self.result.trial_id,
                    "group_id": self.result.group_id,
                    "config": asdict(cfg),
                    "history": self.result.history,
                    "wall_s": self.result.wall_s,
                    "steps": self.result.steps,
                },
                f,
                indent=2,
            )
        self._log(f"Done. time: {self.result.wall_s:f}")


def run_hpo(
    configs: Sequence[TrialConfig],
    train_data: Dataset,
    test_data: Optional[Dataset] = None,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    out_dir: str = "results",
    shard_across_trials: bool = False,
    save_images: bool = True,
    save_checkpoints: bool = True,
    verbose: bool = True,
    model_builder=None,
) -> list[TrialResult]:
    """Run one trial per config, each on its own disjoint submesh,
    concurrently, with no cross-trial synchronization.

    ``groups`` defaults to ``setup_groups(len(configs))`` over all
    devices. Trials whose submesh has no local devices are skipped on
    this process (multi-controller membership, ``vae-hpo.py:200-202``).
    ``model_builder(cfg)`` swaps the model family (e.g. ``ConvVAE`` for
    the β-VAE CIFAR config) while reusing all scaffolding; default is
    the flagship MLP VAE. Returns results for locally-run trials, in
    config order.
    """
    if groups is None:
        groups = setup_groups(len(configs))
    if len(groups) != len(configs):
        raise ValueError(
            f"{len(configs)} configs but {len(groups)} device groups"
        )

    runs = [
        _TrialRun(
            trial,
            cfg,
            train_data,
            test_data,
            out_dir,
            shard_across_trials=shard_across_trials,
            num_trials=len(configs),
            save_images=save_images,
            save_checkpoint=save_checkpoints,
            verbose=verbose,
            model_builder=model_builder,
        )
        for trial, cfg in zip(groups, configs)
        if trial.is_local_member
    ]

    # Cooperative round-robin: one async step dispatch per trial per
    # cycle. Finished trials drop out; the loop ends when all are done —
    # the sweep's wall-clock is bounded by its slowest trial's *own*
    # work, never by barriers (Q3 fixed).
    active = [(r, r.run()) for r in runs]
    while active:
        still = []
        for r, gen in active:
            try:
                next(gen)
                still.append((r, gen))
            except StopIteration:
                pass
        active = still
    return [r.result for r in runs]
