"""Population-based training (BASELINE.md config 5: "inter-subgroup
weight broadcast/exploit across submeshes") — two execution modes over
one seeding contract.

**Per-submesh mode** (``fused=False``, the reference semantics): one
member per submesh, each generation one scan-fused dispatch per member,
exploit/explore host-side — rank the fetched scores, ``device_get`` the
winner's replicated state, ``device_put`` it onto the loser's submesh.
In the torch design this would need inter-group NCCL broadcasts
negotiated across communicators; here it is host metadata + one byte
move per exploited member.

**Fused-lane mode** (``fused=True``): the population IS the stacked
lane axis (PR 1, ``train/steps.py``) — K members run as lanes of ONE
vmapped program, and the generation boundary is an *in-program*
exploit/explore (``train.steps.pbt_exchange``): a stable lane-axis
argsort ranks members, a gather copies top-q params+opt-state into
bottom-q lanes, and the lr perturbation is a pure function of
(explore_key, generation, lane) applied to the batched ``TrialHypers``.
A whole generation (S-step train scan + E-batch eval scan + exchange)
is ONE dispatch — registered as the ``pbt_gen`` program kind in the
compile registry (``compile/programs.py``), so it compiles once ever
and every later generation (and every later ``run_pbt`` in the
process) is a registry ``cache_hit``.

Both modes follow the SAME seeding contract (docs/PBT.md): member k's
params init from ``key(seed + k)``, its per-step data RNG folds
``key(seed + k + 1)`` with the global optimizer-step count, its data
stream replays the ``(seed + k, epoch)`` permutations, and every
explore draw comes from :func:`~multidisttorch_tpu.train.steps
.pbt_perturb_factor`. That contract is what makes the two modes
bit-identical — member states, scores, exploit decisions, and lrs —
which the parity tests and the ``bench.py --pbt`` A/B artifact gate.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import (
    EvalDataIterator,
    StackedTrialDataIterator,
)
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh, setup_groups
from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.train.steps import (
    TrainState,
    TrialHypers,
    build_stacked_train_state,
    create_stacked_train_state,
    make_stacked_eval_scan,
    make_stacked_multi_step,
    pbt_explore_key,
    pbt_perturb_factor,
)
from multidisttorch_tpu.utils.logging import log0


@dataclass(frozen=True)
class PBTConfig:
    population: int = 4
    generations: int = 5
    steps_per_generation: int = 30
    batch_size: int = 64
    lr_min: float = 1e-4
    lr_max: float = 1e-2
    beta: float = 1.0
    exploit_fraction: float = 0.25  # bottom q exploits top q
    perturb_factors: tuple[float, float] = (0.8, 1.25)
    seed: int = 0
    hidden_dim: int = 400
    latent_dim: int = 20


@dataclass
class PBTResult:
    best_member: int
    best_eval_loss: float
    history: list = field(default_factory=list)  # per-generation dicts
    final_lrs: list = field(default_factory=list)
    wall_s: float = 0.0
    mode: str = "submesh"
    # Dispatch accounting for the fused-vs-submesh A/B (bench --pbt):
    # program_calls = compiled-program invocations, host_transfers =
    # exchange state moves through host memory.
    dispatch_book: dict = field(default_factory=dict)
    # Per-member final states (host pytrees, lane axis squeezed) when
    # run_pbt(return_states=True) — the bit-parity comparison surface.
    final_states: Optional[list] = None


def n_exploit_for(cfg: PBTConfig) -> int:
    """The exploit slot count: ``floor(exploit_fraction * K)`` floored
    at 1, clamped to ``K // 2`` so the top and bottom slices can never
    overlap (an overlapping slice would let an exploiter clone a state
    that was itself just overwritten in the same exchange). K=1 clamps
    to 0 — the degenerate population skips the exchange entirely."""
    n = max(1, int(np.floor(cfg.exploit_fraction * cfg.population)))
    return min(n, cfg.population // 2)


def _set_lr(
    state: TrainState, lr: float, trial: Optional[TrialMesh] = None
) -> TrainState:
    """Overwrite the injected learning rate inside an
    ``optax.inject_hyperparams`` optimizer state (the pre-lane-axis PBT
    representation; per-lane lrs now ride ``TrialHypers``, but external
    states built on inject_hyperparams still mutate through here).

    With ``trial``, the new scalar is placed replicated on the trial's
    submesh (required in multi-controller mode, where mixing a
    process-local scalar into a pytree of multi-process global arrays
    would fail at the next dispatch)."""
    opt = state.opt_state
    hp = dict(opt.hyperparams)
    new = jnp.asarray(lr, dtype=hp["learning_rate"].dtype)
    hp["learning_rate"] = trial.device_put(new) if trial is not None else new
    return state.replace(opt_state=opt._replace(hyperparams=hp))


def _init_lrs(cfg: PBTConfig) -> np.ndarray:
    """The population's initial log-uniform lrs, as f32 (the dtype the
    batched ``TrialHypers`` carry — both modes draw identically)."""
    rng = np.random.default_rng(cfg.seed)
    return np.exp(
        rng.uniform(np.log(cfg.lr_min), np.log(cfg.lr_max), cfg.population)
    ).astype(np.float32)


def _rank(sums: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side ranking, bit-matching the in-program exchange: NaN
    sanitized to +inf, stable ascending argsort (ties break by lane).
    Returns ``(order, sanitized)`` — the ONE host-side copy of the
    sanitization rule, so the exploit condition always compares the
    same values the ranking sorted (the ``jnp.where`` twin lives in
    ``train.steps.pbt_exchange``)."""
    sanitized = np.asarray(sums, np.float32).copy()
    sanitized[np.isnan(sanitized)] = np.inf
    return np.argsort(sanitized, kind="stable"), sanitized


def _emit_generation(
    mode: str,
    gen: int,
    scores: np.ndarray,
    order: np.ndarray,
    lrs: np.ndarray,
    exploits: list,
    prev_order: Optional[np.ndarray],
    global_step: int,
) -> None:
    """The ``pbt_*`` telemetry seam (zero-cost when off): one
    ``pbt_gen`` per generation boundary with the lane-axis population
    statistics (best/median loss, exploit count, rank churn, lr
    quantiles), one ``pbt_exploit`` per exchange edge."""
    bus = get_bus()
    if bus is None:
        return
    k = len(order)
    finite = scores[np.isfinite(scores)]
    churn = None
    if prev_order is not None:
        # rank churn: fraction of lanes whose rank position changed
        # since the previous generation's ordering.
        churn = float(np.mean(order != prev_order))
    data = dict(
        generation=gen,
        mode=mode,
        population=k,
        best_lane=int(order[0]),
        best_loss=float(scores[order[0]]),
        median_loss=(
            float(np.median(finite)) if finite.size else None
        ),
        exploit_count=len(exploits),
        lr_min=float(np.min(lrs)),
        lr_median=float(np.median(lrs)),
        lr_max=float(np.max(lrs)),
    )
    if churn is not None:
        data["rank_churn"] = round(churn, 4)
    bus.emit("pbt_gen", step=global_step, **data)
    for e in exploits:
        bus.emit(
            "pbt_exploit",
            step=global_step,
            lane=e["to"],
            generation=gen,
            mode=mode,
            src=e["from"],
            dst=e["to"],
            new_lr=e["new_lr"],
            src_loss=float(scores[e["from"]]),
            dst_loss=float(scores[e["to"]]),
        )


class _Member:
    """One per-submesh population member: a 1-lane stacked program.

    Running the reference members through the SAME vmapped lane body as
    the fused path (``_stacked_lane_body`` via the stacked step
    builders, K=1) is what makes fused-vs-submesh bit-parity provable:
    both modes share one step arithmetic, one RNG stream
    (``fold_in(key(seed+1), global_step)`` per inner step), and one
    data permutation recipe — only the dispatch structure differs.
    """

    def __init__(
        self,
        trial: TrialMesh,
        member_id: int,
        cfg: PBTConfig,
        model: Any,  # any VAE-family module: (recon_logits, mu, logvar)
        train_data: Dataset,
        eval_host: tuple[np.ndarray, np.ndarray],
        lr: float,
    ):
        self.trial = trial
        self.member_id = member_id
        seed = cfg.seed + member_id
        self.state = create_stacked_train_state(trial, model, [seed])
        self.hypers = trial.device_put(
            TrialHypers.stack([lr], [cfg.beta])
        )
        self.multi_step = make_stacked_multi_step(trial, model)
        self.eval_scan = make_stacked_eval_scan(trial, model)
        self.base_rngs = trial.device_put(
            jnp.stack([jax.random.key(seed + 1)])
        )
        self.train_iter = StackedTrialDataIterator(
            train_data, trial, cfg.batch_size, [seed]
        )
        self._chunks = self.train_iter.stream_chunks(
            cfg.steps_per_generation
        )
        # Pad-and-mask eval, the whole set pre-staged (E, B, ...) and
        # placed once on this member's submesh: every eval row scores
        # (the full-coverage contract of the HPO driver's test loop),
        # and a generation's scoring is ONE scan-eval dispatch —
        # structurally identical to the eval phase inside the fused
        # generation program, which is what keeps the two modes'
        # scores bit-identical (steps._scan_eval_sums).
        self.eval_batches, self.eval_weights = _place_eval(
            trial, *eval_host
        )
        self._step = 0

    def run_generation(self, book: dict):
        """Dispatch one generation's explore phase (async): S fused
        train steps on the next S batches of this member's stream."""
        batches = next(self._chunks)
        lane_steps = jnp.full((1,), self._step, jnp.int32)
        self.state, m = self.multi_step(
            self.state, self.hypers, batches, self.base_rngs, lane_steps
        )
        self._step += batches.shape[0]
        book["program_calls"] += 1
        return m

    def eval_loss_sum(self, book: dict) -> np.float32:
        """Summed masked eval loss over the full eval set (f32 — the
        rank statistic both modes share): one scan-eval dispatch, one
        host sync."""
        out = self.eval_scan(
            self.state, self.hypers, self.eval_batches, self.eval_weights
        )
        book["program_calls"] += 1
        return np.asarray(jax.device_get(out["loss_sum"]), np.float32)[0]

    def set_lr(self, lr: np.float32) -> None:
        self.hypers = self.trial.device_put(
            TrialHypers.stack([float(lr)], [float(self.hypers.beta[0])])
        )


def _final_states_from_members(
    members: dict, population: int
) -> list:
    out = [None] * population
    for i, m in members.items():
        host = jax.device_get(m.state)
        out[i] = jax.tree.map(lambda a: np.asarray(a)[0], host)
    return out


def run_pbt(
    cfg: PBTConfig,
    train_data: Dataset,
    eval_data: Dataset,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    out_dir: Optional[str] = None,
    verbose: bool = True,
    model_builder=None,
    fused: bool = False,
    return_states: bool = False,
) -> PBTResult:
    """Run synchronous-generation PBT.

    ``model_builder(cfg)`` swaps the model family, same contract as
    ``run_hpo``: any module whose apply returns ``(recon_logits, mu,
    logvar)`` (VAE, ConvVAE, MoEVAE) rides the shared train/eval steps;
    the population trains the one architecture while PBT explores lr.

    ``fused=False`` (per-submesh): one member per submesh in
    ``groups`` (default ``setup_groups(cfg.population)``), host-side
    exploit/explore. Multi-controller SPMD: every process builds only
    the members whose submesh it owns, but all processes track every
    member's score and lr so scheduling decisions are identical
    everywhere (one ``process_allgather`` per generation; a
    cross-process exploit moves the winner's bytes with
    ``broadcast_one_to_all``).

    ``fused=True`` (lane-axis): the whole population runs as K lanes of
    one vmapped program on ONE submesh — ``groups`` must then carve
    exactly one (default: all devices). A generation is a single
    dispatch of the registered ``pbt_gen`` program; see the module
    docstring and docs/PBT.md. ``return_states=True`` attaches each
    member's final host-side state to the result (the parity surface).
    """
    from multidisttorch_tpu import telemetry as _telemetry

    _telemetry.configure_from_env()
    if fused:
        return _run_pbt_fused(
            cfg, train_data, eval_data, groups=groups, out_dir=out_dir,
            verbose=verbose, model_builder=model_builder,
            return_states=return_states,
        )

    multihost = jax.process_count() > 1
    if multihost:
        from jax.experimental import multihost_utils
    if groups is None:
        groups = setup_groups(cfg.population)
    if len(groups) != cfg.population:
        raise ValueError(
            f"population {cfg.population} but {len(groups)} device groups"
        )

    model = (
        model_builder(cfg)
        if model_builder is not None
        else VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
    )
    lrs = _init_lrs(cfg)  # (K,) f32 — every process draws identically
    eval_host_imgs, eval_host_w, num_eval_rows = _stage_eval_host(
        eval_data, groups[0], cfg.batch_size
    )
    members = {
        i: _Member(
            g, i, cfg, model, train_data,
            (eval_host_imgs, eval_host_w), float(lrs[i]),
        )
        for i, g in enumerate(groups)
        if g.is_local_member
    }

    # Broadcast buffer for processes that don't own an exploit's source
    # member: the same construction path as the real member states
    # (steps.build_stacked_train_state), so the trees can never drift.
    template = (
        jax.tree.map(
            np.asarray,
            jax.device_get(build_stacked_train_state(model, [0])),
        )
        if multihost
        else None
    )

    n_exploit = n_exploit_for(cfg)
    explore_key = pbt_explore_key(cfg.seed)
    book = {"program_calls": 0, "host_transfers": 0}
    result = PBTResult(
        best_member=-1, best_eval_loss=float("inf"), mode="submesh"
    )
    prev_order: Optional[np.ndarray] = None
    t0 = time.time()

    for gen in range(cfg.generations):
        # --- explore phase: one scan-fused dispatch per local member
        # puts a full generation of steps in flight on every submesh
        for m in members.values():
            m.run_generation(book)

        # --- score every member globally: local evals, then one
        # allgather-min (non-owned slots carry +inf; NaN propagates, so
        # a diverged member stays NaN — ranked last — everywhere)
        local_sums = np.full(cfg.population, np.inf, np.float32)
        for i, m in members.items():
            local_sums[i] = m.eval_loss_sum(book)
        if multihost:
            gathered = multihost_utils.process_allgather(local_sums)
            sums = np.asarray(gathered, np.float32).min(axis=0)
        else:
            sums = local_sums
        scores = sums.astype(np.float64) / num_eval_rows
        order, sanitized = _rank(sums)
        record = {
            "generation": gen,
            "scores": {int(i): float(scores[i]) for i in order},
            "loss_sums": [float(s) for s in sums],
            "order": [int(i) for i in order],
            "lrs": {i: float(lrs[i]) for i in range(cfg.population)},
            "exploits": [],
        }

        # --- exploit/explore: bottom slot i clones top slot i iff
        # strictly worse (== skips: a tied population has no winner to
        # copy, and all-NaN sanitizes to all-inf which never exchanges).
        # Decisions derive from the global scores, and perturbations
        # from the pure (explore_key, gen, target-lane) function, so
        # every process makes identical choices — and the in-program
        # exchange (train.steps.pbt_exchange) makes the same ones.
        top = order[:n_exploit]
        bottom = order[cfg.population - n_exploit:] if n_exploit else []
        for i, bad_id in enumerate(bottom):
            bad_id = int(bad_id)
            good_id = int(top[i])
            if not sanitized[bad_id] > sanitized[good_id]:
                continue
            good_trial, bad_trial = groups[good_id], groups[bad_id]
            factor = pbt_perturb_factor(
                explore_key, gen, bad_id, cfg.perturb_factors
            )
            new_lr = np.float32(
                jnp.clip(
                    jnp.float32(lrs[good_id]) * factor,
                    cfg.lr_min,
                    cfg.lr_max,
                )
            )
            # cross-submesh weight + optimizer-state transfer: the
            # winner's replicated state moves via host memory. When the
            # source lives on another process, one broadcast (from the
            # owner of the source's first device) hands every process
            # the bytes; target owners then place them on their mesh.
            # Ownership sets are global device metadata, so every
            # process computes the same answer: when everyone who needs
            # the state already owns the source, the world-collective
            # broadcast is pure waste — a full params+moments transfer
            # skipped.
            good_owners = {d.process_index for d in good_trial.devices}
            bad_owners = {d.process_index for d in bad_trial.devices}
            if multihost and not bad_owners <= good_owners:
                is_source = (
                    good_trial.devices[0].process_index
                    == jax.process_index()
                )
                # Only the is_source process's bytes are consumed by
                # the broadcast; every other process passes the
                # shape-only template rather than paying a full
                # params+moments device_get whose result is discarded.
                payload = (
                    jax.tree.map(
                        np.asarray, jax.device_get(members[good_id].state)
                    )
                    if is_source
                    else template
                )
                host_state = multihost_utils.broadcast_one_to_all(
                    payload, is_source=is_source
                )
                book["host_transfers"] += 1
            elif bad_id in members:
                # Non-broadcast path: fetch only where the state is
                # about to be consumed (the target's owners; they also
                # own the source here, or we'd be in the broadcast
                # branch).
                host_state = jax.device_get(members[good_id].state)
                book["host_transfers"] += 1
            if bad_id in members:
                bad = members[bad_id]
                bad.state = bad_trial.device_put(host_state)
                bad.set_lr(new_lr)
                book["host_transfers"] += 1
            lrs[bad_id] = new_lr
            record["exploits"].append(
                {"from": good_id, "to": bad_id, "new_lr": float(new_lr)}
            )
            if verbose and bad_id in members:
                log0(
                    f"PBT gen {gen}: member {bad_id} "
                    f"(loss {scores[bad_id]:.2f}) exploits "
                    f"{good_id} (loss {scores[good_id]:.2f}), "
                    f"lr -> {float(new_lr):.2e}",
                    trial=bad_trial,
                )

        _emit_generation(
            "submesh", gen, scores, order, lrs, record["exploits"],
            prev_order, (gen + 1) * cfg.steps_per_generation,
        )
        prev_order = order
        result.history.append(record)
        best = int(order[0])
        if scores[best] < result.best_eval_loss:
            result.best_eval_loss = float(scores[best])
            result.best_member = best

    result.wall_s = time.time() - t0
    result.final_lrs = [float(v) for v in lrs]
    _finish_books(result, cfg, book)
    if return_states and not multihost:
        result.final_states = _final_states_from_members(
            members, cfg.population
        )
    _write_report(result, out_dir)
    return result


def _finish_books(result: PBTResult, cfg: PBTConfig, book: dict) -> None:
    gens = max(1, cfg.generations)
    result.dispatch_book = dict(
        book,
        generations=cfg.generations,
        dispatches_per_generation=round(book["program_calls"] / gens, 3),
        transfers_per_generation=round(book["host_transfers"] / gens, 3),
    )


def _write_report(result: PBTResult, out_dir: Optional[str]) -> None:
    if out_dir and jax.process_index() != 0:
        out_dir = None  # one writer process for the shared report
    if not out_dir:
        return
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "pbt.json"), "w") as f:
        json.dump(
            {
                "mode": result.mode,
                "best_member": result.best_member,
                "best_eval_loss": result.best_eval_loss,
                "final_lrs": result.final_lrs,
                "history": result.history,
                "wall_s": result.wall_s,
                "dispatch_book": result.dispatch_book,
            },
            f,
            indent=2,
        )


def _stage_eval_host(
    eval_data: Dataset, trial: TrialMesh, batch_size: int
) -> tuple[np.ndarray, np.ndarray, int]:
    """Stage the full pad-and-mask eval set host-side ONCE: the
    ``(E, B, ...)`` images + ``(E, B)`` weights every scorer scans, plus
    the real row count. The host staging is shared by all K members of
    the per-submesh mode (only the per-trial device placement,
    :func:`_place_eval`, repeats); groups share a shape, so any one
    trial validates the batch divisibility for all."""
    it = EvalDataIterator(eval_data, trial, batch_size)
    imgs, weights = [], []
    for imgs_np, _labels, w_np in it.host_batches():
        imgs.append(imgs_np)
        weights.append(w_np)
    return (
        np.stack(imgs).astype(np.float32, copy=False),
        np.stack(weights),
        it.num_rows,
    )


def _place_eval(trial: TrialMesh, stacked: np.ndarray, w: np.ndarray):
    """Place a staged eval set on one trial's submesh (dim 1
    data-sharded), once per trial — the scorers scan it on device every
    generation, so eval costs zero further host transfers."""
    sh = trial.sharding(None, DATA_AXIS)
    if jax.process_count() == 1:
        return jax.device_put(stacked, sh), jax.device_put(w, sh)
    mk = jax.make_array_from_callback
    return (
        mk(stacked.shape, sh, lambda idx: stacked[idx]),
        mk(w.shape, sh, lambda idx: w[idx]),
    )


def _admit_fused_program(
    trial: TrialMesh, model, cfg: PBTConfig, n_exploit: int, E: int
):
    """Take the fused generation executable from the process-lifetime
    compile registry (one compile EVER per program; ``cache_hit`` on
    every later take — including generation 2+ of this run via
    :func:`_take_fused_again`), compiling inline through the registry's
    coalesced, timed, event-emitting path on first admission. Custom
    ``model_builder`` families bypass the registry (their architecture
    is not captured by the key vocabulary) and jit inline — the same
    policy as the HPO driver. Returns ``(callable, key_or_None)``."""
    from multidisttorch_tpu.compile import programs as _cprog
    from multidisttorch_tpu.compile.registry import (
        READY,
        SOURCE_INLINE,
        get_executable_registry,
    )

    build = lambda: _cprog.build_pbt_generation(  # noqa: E731
        trial,
        model,
        n_exploit=n_exploit,
        perturb_factors=cfg.perturb_factors,
        lr_min=cfg.lr_min,
        lr_max=cfg.lr_max,
    )
    if not isinstance(model, VAE):
        return build(), None
    bucket = (
        cfg.batch_size, model.hidden_dim, model.latent_dim, 1, 1, False,
    )
    key = _cprog.pbt_gen_key(
        trial,
        bucket,
        lanes=cfg.population,
        steps_per_generation=cfg.steps_per_generation,
        eval_batches=E,
        n_exploit=n_exploit,
        perturb_factors=cfg.perturb_factors,
        lr_min=cfg.lr_min,
        lr_max=cfg.lr_max,
    )
    reg = get_executable_registry()
    exe = reg.take(key)
    if exe is not None:
        return exe, key
    raw = build()
    try:
        avals = _cprog.pbt_gen_avals(
            model,
            lanes=cfg.population,
            steps_per_generation=cfg.steps_per_generation,
            eval_batches=E,
            batch_size=cfg.batch_size,
        )
    except Exception:  # noqa: BLE001 — aval derivation failing is a
        # registry problem, not a sweep problem: jit fallback.
        return raw, None
    reg.claim(key)
    entry = reg.compile_now(key, raw, avals, source=SOURCE_INLINE)
    if entry.status == READY and entry.compiled is not None:
        return entry.compiled, key
    return raw, None


def _take_fused_again(key: Optional[tuple], current):
    """Generation 2+ admission: re-take from the registry so the books
    (hits counter, ``cache_hit`` events) record that the generation
    reused the one compiled executable — the acceptance surface for
    "one compile, cache_hit on generation 2+"."""
    if key is None:
        return current
    from multidisttorch_tpu.compile.registry import (
        get_executable_registry,
    )

    exe = get_executable_registry().take(key)
    return exe if exe is not None else current


def _run_pbt_fused(
    cfg: PBTConfig,
    train_data: Dataset,
    eval_data: Dataset,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    out_dir: Optional[str] = None,
    verbose: bool = True,
    model_builder=None,
    return_states: bool = False,
) -> PBTResult:
    """The fused-lane mode body (call through ``run_pbt(fused=True)``)."""
    if groups is None:
        groups = setup_groups(1)
    if len(groups) != 1:
        raise ValueError(
            "fused PBT runs the whole population as lanes of ONE "
            f"submesh; got {len(groups)} groups (carve one, e.g. "
            "setup_groups(1), or pass the shape the per-submesh A/B "
            "leg uses)"
        )
    trial = groups[0]
    K = cfg.population
    S = cfg.steps_per_generation
    model = (
        model_builder(cfg)
        if model_builder is not None
        else VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
    )
    n_exploit = n_exploit_for(cfg)
    seeds = [cfg.seed + k for k in range(K)]
    lrs = _init_lrs(cfg)

    state = create_stacked_train_state(trial, model, seeds)
    hypers = trial.device_put(
        TrialHypers.stack([float(v) for v in lrs], [cfg.beta] * K)
    )
    base_rngs = trial.device_put(
        jnp.stack([jax.random.key(s + 1) for s in seeds])
    )
    explore_key = trial.device_put(pbt_explore_key(cfg.seed))
    data_iter = StackedTrialDataIterator(
        train_data, trial, cfg.batch_size, seeds
    )
    chunks = data_iter.stream_chunks(S)
    eval_imgs, eval_w, num_eval_rows = _stage_eval_host(
        eval_data, trial, cfg.batch_size
    )
    eval_batches, eval_weights = _place_eval(trial, eval_imgs, eval_w)

    gen_step, prog_key = _admit_fused_program(
        trial, model, cfg, n_exploit, eval_imgs.shape[0]
    )

    book = {"program_calls": 0, "host_transfers": 0}
    result = PBTResult(
        best_member=-1, best_eval_loss=float("inf"), mode="fused"
    )
    prev_order: Optional[np.ndarray] = None
    t0 = time.time()

    for gen in range(cfg.generations):
        if gen > 0:
            gen_step = _take_fused_again(prog_key, gen_step)
        batches = next(chunks)
        lane_steps = trial.device_put(
            jnp.full((K,), gen * S, jnp.int32)
        )
        gen_arr = trial.device_put(jnp.asarray(gen, jnp.int32))
        lrs_before = lrs.copy()
        # ONE dispatch: S train steps x K lanes, E eval batches, and
        # the lane-axis exploit/explore — the whole generation.
        state, hypers, stats = gen_step(
            state, hypers, batches, eval_batches, eval_weights,
            base_rngs, lane_steps, gen_arr, explore_key,
        )
        book["program_calls"] += 1
        # One fetch per generation: the population books (scores,
        # ranking, exchange edges, new lrs) — K floats and ints, not
        # member states.
        host = jax.device_get(
            {k: stats[k] for k in ("order", "exploited", "src", "new_lr",
                                   "eval_loss_sum")}
        )
        sums = np.asarray(host["eval_loss_sum"], np.float32)
        order = np.asarray(host["order"])
        exploited = np.asarray(host["exploited"])
        src = np.asarray(host["src"])
        lrs = np.asarray(host["new_lr"], np.float32)
        scores = sums.astype(np.float64) / num_eval_rows
        exploits = [
            {
                "from": int(src[lane]),
                "to": int(lane),
                "new_lr": float(lrs[lane]),
            }
            # bottom slots in rank order — the same exploit-list order
            # the per-submesh path records.
            for lane in (order[K - n_exploit:] if n_exploit else [])
            if exploited[lane]
        ]
        record = {
            "generation": gen,
            "scores": {int(i): float(scores[i]) for i in order},
            "loss_sums": [float(s) for s in sums],
            "order": [int(i) for i in order],
            "lrs": {i: float(lrs_before[i]) for i in range(K)},
            "exploits": exploits,
        }
        if verbose:
            for e in exploits:
                log0(
                    f"PBT gen {gen}: lane {e['to']} "
                    f"(loss {scores[e['to']]:.2f}) exploits "
                    f"{e['from']} (loss {scores[e['from']]:.2f}), "
                    f"lr -> {e['new_lr']:.2e}",
                    trial=trial,
                )
        _emit_generation(
            "fused", gen, scores, order, lrs, exploits, prev_order,
            (gen + 1) * S,
        )
        prev_order = order
        result.history.append(record)
        best = int(order[0])
        if scores[best] < result.best_eval_loss:
            result.best_eval_loss = float(scores[best])
            result.best_member = best

    result.wall_s = time.time() - t0
    result.final_lrs = [float(v) for v in lrs]
    _finish_books(result, cfg, book)
    if return_states:
        host = jax.device_get(state)
        result.final_states = [
            jax.tree.map(lambda a, k=k: np.asarray(a)[k], host)
            for k in range(K)
        ]
    _write_report(result, out_dir)
    return result
