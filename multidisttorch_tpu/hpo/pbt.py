"""Population-based training across trial submeshes (BASELINE.md
config 5: "inter-subgroup weight broadcast/exploit across submeshes").

The reference's north-star extension: instead of N independent HPO
trials (``/root/reference/vae-hpo.py:200-202``), the N subgroups form a
*population* — periodically the worst trials clone the best trials'
weights (exploit) and perturb their hyperparameters (explore). In the
torch design this would need inter-group NCCL broadcasts negotiated
across communicators; here a cross-submesh weight move is a host-side
``device_put`` of a replicated pytree onto the target submesh — no
collective choreography at all.

The learning rate lives inside the optimizer state via
``optax.inject_hyperparams``, so exploit/explore mutates it without
recompiling the member's train step.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import EvalDataIterator, TrialDataIterator
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import TrialMesh, setup_groups
from multidisttorch_tpu.train.steps import (
    TrainState,
    build_train_state,
    create_train_state,
    make_eval_step,
    make_multi_step,
)
from multidisttorch_tpu.utils.logging import log0


@dataclass(frozen=True)
class PBTConfig:
    population: int = 4
    generations: int = 5
    steps_per_generation: int = 30
    batch_size: int = 64
    lr_min: float = 1e-4
    lr_max: float = 1e-2
    beta: float = 1.0
    exploit_fraction: float = 0.25  # bottom q exploits top q
    perturb_factors: tuple[float, float] = (0.8, 1.25)
    seed: int = 0
    hidden_dim: int = 400
    latent_dim: int = 20


@dataclass
class PBTResult:
    best_member: int
    best_eval_loss: float
    history: list = field(default_factory=list)  # per-generation dicts
    final_lrs: list = field(default_factory=list)
    wall_s: float = 0.0


def _set_lr(
    state: TrainState, lr: float, trial: Optional[TrialMesh] = None
) -> TrainState:
    """Overwrite the injected learning rate inside the optimizer state.

    With ``trial``, the new scalar is placed replicated on the trial's
    submesh (required in multi-controller mode, where mixing a
    process-local scalar into a pytree of multi-process global arrays
    would fail at the next dispatch)."""
    opt = state.opt_state
    hp = dict(opt.hyperparams)
    new = jnp.asarray(lr, dtype=hp["learning_rate"].dtype)
    hp["learning_rate"] = trial.device_put(new) if trial is not None else new
    return state.replace(opt_state=opt._replace(hyperparams=hp))


class _Member:
    def __init__(
        self,
        trial: TrialMesh,
        member_id: int,
        cfg: PBTConfig,
        model: Any,  # any VAE-family module: (recon_logits, mu, logvar)
        train_data: Dataset,
        eval_data: Dataset,
        lr: float,
    ):
        self.trial = trial
        self.member_id = member_id
        self.lr = lr
        tx = optax.inject_hyperparams(optax.adam)(learning_rate=lr)
        self.state = create_train_state(
            trial, model, tx, jax.random.key(cfg.seed + member_id)
        )
        # One generation = one scan-fused dispatch of steps_per_generation
        # optimizer updates (make_multi_step): the member's whole explore
        # phase costs a single host round-trip.
        self.multi_step = make_multi_step(trial, model, tx, beta=cfg.beta)
        self.eval_step = make_eval_step(
            trial, model, beta=cfg.beta, with_recon=False, masked=True
        )
        self.train_iter = TrialDataIterator(
            train_data, trial, cfg.batch_size, seed=cfg.seed + member_id
        )
        self._chunks = self.train_iter.stream_chunks(cfg.steps_per_generation)
        # Pad-and-mask eval: every eval row scores, regardless of how the
        # eval set divides the batch (same full-coverage contract as the
        # HPO driver's test loop).
        self.eval_iter = EvalDataIterator(eval_data, trial, cfg.batch_size)
        self._key = jax.random.key(1000 + member_id)
        self._step = 0

    def run_generation(self):
        """Dispatch one generation's explore phase (async): K fused
        train steps on the next K batches of this member's stream."""
        batches = next(self._chunks)
        rng = jax.random.fold_in(self._key, self._step)
        self.state, m = self.multi_step(self.state, batches, rng)
        self._step += batches.shape[0]
        return m

    def eval_loss(self) -> float:
        # Device-side accumulation; one host sync at the end.
        total = None
        for batch, weights in self.eval_iter.batches():
            out = self.eval_step(self.state, batch, weights)
            total = (
                out["loss_sum"] if total is None else total + out["loss_sum"]
            )
        return float(total) / self.eval_iter.num_rows


def run_pbt(
    cfg: PBTConfig,
    train_data: Dataset,
    eval_data: Dataset,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    out_dir: Optional[str] = None,
    verbose: bool = True,
    model_builder=None,
) -> PBTResult:
    """Run synchronous-generation PBT, one member per submesh.

    ``model_builder(cfg)`` swaps the model family, same contract as
    ``run_hpo``: any module whose apply returns ``(recon_logits, mu,
    logvar)`` (VAE, ConvVAE, MoEVAE) rides the shared train/eval steps;
    the population trains the one architecture while PBT explores lr.

    A generation's explore phase is one scan-fused dispatch per member
    (``steps_per_generation`` optimizer updates in a single host
    round-trip, queued async on every submesh at once); the
    exploit/explore exchange at generation boundaries is the only
    cross-trial coordination — and it is host-side metadata + one
    device_put per exploited member.

    Multi-controller SPMD: every process builds only the members whose
    submesh it owns (the same membership contract as ``run_hpo``), but
    all processes track every member's score and lr so scheduling
    decisions are identical everywhere. Scores are combined with one
    ``process_allgather`` per generation; an exploit whose source and
    target live on different processes moves the winner's host state
    with ``broadcast_one_to_all``. The torch analog would be inter-group
    NCCL broadcasts negotiated across communicators; here it is host
    metadata + one collective byte-move.
    """
    multihost = jax.process_count() > 1
    if multihost:
        from jax.experimental import multihost_utils
    if groups is None:
        groups = setup_groups(cfg.population)
    if len(groups) != cfg.population:
        raise ValueError(
            f"population {cfg.population} but {len(groups)} device groups"
        )

    model = (
        model_builder(cfg)
        if model_builder is not None
        else VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
    )
    rng = np.random.default_rng(cfg.seed)
    init_lrs = np.exp(
        rng.uniform(np.log(cfg.lr_min), np.log(cfg.lr_max), cfg.population)
    )
    # Deterministic host metadata every process tracks for ALL members;
    # device state exists only for local members.
    lrs = [float(v) for v in init_lrs]
    members = {
        i: _Member(g, i, cfg, model, train_data, eval_data, lrs[i])
        for i, g in enumerate(groups)
        if g.is_local_member
    }

    # Broadcast buffer for processes that don't own an exploit's source
    # member: the same construction path as the real member states
    # (steps.build_train_state), so the trees can never drift apart.
    template = (
        jax.tree.map(
            np.asarray,
            jax.device_get(
                build_train_state(
                    model,
                    optax.inject_hyperparams(optax.adam)(learning_rate=lrs[0]),
                    jax.random.key(0),
                )
            ),
        )
        if multihost
        else None
    )

    # clamp to half the population so the top and bottom slices can never
    # overlap (an overlapping slice would let an exploiter clone a state
    # that was itself just overwritten in the same exchange)
    n_exploit = max(1, int(np.floor(cfg.exploit_fraction * cfg.population)))
    n_exploit = min(n_exploit, cfg.population // 2)
    result = PBTResult(best_member=-1, best_eval_loss=float("inf"))
    t0 = time.time()

    for gen in range(cfg.generations):
        # --- explore phase: one scan-fused dispatch per local member
        # puts a full generation of steps in flight on every submesh
        for m in members.values():
            m.run_generation()

        # --- score every member globally: local evals, then one
        # allgather-min (non-owned slots carry +inf)
        local_scores = np.full(cfg.population, np.inf, np.float64)
        for i, m in members.items():
            local_scores[i] = m.eval_loss()
        if multihost:
            gathered = multihost_utils.process_allgather(local_scores)
            scores_arr = np.asarray(gathered).min(axis=0)
        else:
            scores_arr = local_scores
        scores = {i: float(scores_arr[i]) for i in range(cfg.population)}
        ranked = sorted(range(cfg.population), key=lambda i: (scores[i], i))
        record = {
            "generation": gen,
            "scores": {i: scores[i] for i in ranked},
            "lrs": {i: lrs[i] for i in range(cfg.population)},
            "exploits": [],
        }

        # --- exploit/explore: bottom n_exploit copy a top-n_exploit peer
        # (guard: ranked[-0:] would be the WHOLE list, so population=1 —
        # where n_exploit clamps to 0 — must skip the exchange entirely).
        # Decisions derive from the global scores, so every process makes
        # the identical choices (and draws the identical perturbations).
        top, bottom = (
            (ranked[:n_exploit], ranked[-n_exploit:]) if n_exploit else ([], [])
        )
        for i, bad_id in enumerate(bottom):
            good_id = top[i % len(top)]
            if scores[bad_id] <= scores[good_id]:
                continue
            good_trial, bad_trial = groups[good_id], groups[bad_id]
            factor = float(rng.choice(cfg.perturb_factors))
            new_lr = float(
                np.clip(lrs[good_id] * factor, cfg.lr_min, cfg.lr_max)
            )
            # cross-submesh weight + optimizer-state transfer: the
            # winner's replicated state moves via host memory. When the
            # source lives on another process, one broadcast (from the
            # owner of the source's first device) hands every process
            # the bytes; target owners then place them on their mesh.
            # Ownership sets are global device metadata, so every process
            # computes the same answer: when everyone who needs the state
            # already owns the source, the world-collective broadcast is
            # pure waste — a full params+moments transfer skipped.
            good_owners = {d.process_index for d in good_trial.devices}
            bad_owners = {d.process_index for d in bad_trial.devices}
            if multihost and not bad_owners <= good_owners:
                is_source = (
                    good_trial.devices[0].process_index == jax.process_index()
                )
                # Only the is_source process's bytes are consumed by the
                # broadcast; every other process passes the shape-only
                # template rather than paying a full params+moments
                # device_get whose result would be discarded.
                payload = (
                    jax.tree.map(
                        np.asarray, jax.device_get(members[good_id].state)
                    )
                    if is_source
                    else template
                )
                host_state = multihost_utils.broadcast_one_to_all(
                    payload, is_source=is_source
                )
            elif bad_id in members:
                # Non-broadcast path: fetch only where the state is about
                # to be consumed (the target's owners; they also own the
                # source here, or we'd be in the broadcast branch).
                host_state = jax.device_get(members[good_id].state)
            if bad_id in members:
                bad = members[bad_id]
                cloned = bad_trial.device_put(host_state)
                bad.state = _set_lr(cloned, new_lr, trial=bad_trial)
                bad.lr = new_lr
            lrs[bad_id] = new_lr
            record["exploits"].append(
                {"from": good_id, "to": bad_id, "new_lr": new_lr}
            )
            if verbose and bad_id in members:
                log0(
                    f"PBT gen {gen}: member {bad_id} "
                    f"(loss {scores[bad_id]:.2f}) exploits "
                    f"{good_id} (loss {scores[good_id]:.2f}), "
                    f"lr -> {new_lr:.2e}",
                    trial=bad_trial,
                )

        result.history.append(record)
        best = ranked[0]
        if scores[best] < result.best_eval_loss:
            result.best_eval_loss = scores[best]
            result.best_member = best

    result.wall_s = time.time() - t0
    result.final_lrs = list(lrs)
    if out_dir and jax.process_index() != 0:
        out_dir = None  # one writer process for the shared report
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "pbt.json"), "w") as f:
            json.dump(
                {
                    "best_member": result.best_member,
                    "best_eval_loss": result.best_eval_loss,
                    "final_lrs": result.final_lrs,
                    "history": result.history,
                    "wall_s": result.wall_s,
                },
                f,
                indent=2,
            )
    return result
