"""Population-based training across trial submeshes (BASELINE.md
config 5: "inter-subgroup weight broadcast/exploit across submeshes").

The reference's north-star extension: instead of N independent HPO
trials (``/root/reference/vae-hpo.py:200-202``), the N subgroups form a
*population* — periodically the worst trials clone the best trials'
weights (exploit) and perturb their hyperparameters (explore). In the
torch design this would need inter-group NCCL broadcasts negotiated
across communicators; here a cross-submesh weight move is a host-side
``device_put`` of a replicated pytree onto the target submesh — no
collective choreography at all.

The learning rate lives inside the optimizer state via
``optax.inject_hyperparams``, so exploit/explore mutates it without
recompiling the member's train step.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.data.datasets import Dataset
from multidisttorch_tpu.data.sampler import TrialDataIterator
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import TrialMesh, setup_groups
from multidisttorch_tpu.train.steps import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_multi_step,
)
from multidisttorch_tpu.utils.logging import log0


@dataclass(frozen=True)
class PBTConfig:
    population: int = 4
    generations: int = 5
    steps_per_generation: int = 30
    batch_size: int = 64
    lr_min: float = 1e-4
    lr_max: float = 1e-2
    beta: float = 1.0
    exploit_fraction: float = 0.25  # bottom q exploits top q
    perturb_factors: tuple[float, float] = (0.8, 1.25)
    seed: int = 0
    hidden_dim: int = 400
    latent_dim: int = 20


@dataclass
class PBTResult:
    best_member: int
    best_eval_loss: float
    history: list = field(default_factory=list)  # per-generation dicts
    final_lrs: list = field(default_factory=list)
    wall_s: float = 0.0


def _set_lr(state: TrainState, lr: float) -> TrainState:
    """Overwrite the injected learning rate inside the optimizer state."""
    opt = state.opt_state
    hp = dict(opt.hyperparams)
    hp["learning_rate"] = jnp.asarray(lr, dtype=hp["learning_rate"].dtype)
    return state.replace(opt_state=opt._replace(hyperparams=hp))


class _Member:
    def __init__(
        self,
        trial: TrialMesh,
        member_id: int,
        cfg: PBTConfig,
        model: VAE,
        train_data: Dataset,
        eval_data: Dataset,
        lr: float,
    ):
        self.trial = trial
        self.member_id = member_id
        self.lr = lr
        tx = optax.inject_hyperparams(optax.adam)(learning_rate=lr)
        self.state = create_train_state(
            trial, model, tx, jax.random.key(cfg.seed + member_id)
        )
        # One generation = one scan-fused dispatch of steps_per_generation
        # optimizer updates (make_multi_step): the member's whole explore
        # phase costs a single host round-trip.
        self.multi_step = make_multi_step(trial, model, tx, beta=cfg.beta)
        self.eval_step = make_eval_step(
            trial, model, beta=cfg.beta, with_recon=False
        )
        self.train_iter = TrialDataIterator(
            train_data, trial, cfg.batch_size, seed=cfg.seed + member_id
        )
        self._chunks = self.train_iter.stream_chunks(cfg.steps_per_generation)
        # eval batch must keep the per-device divisibility invariant
        eval_bs = min(cfg.batch_size, len(eval_data))
        eval_bs -= eval_bs % trial.data_size
        if eval_bs == 0:
            raise ValueError(
                f"eval set of {len(eval_data)} rows too small for a "
                f"{trial.data_size}-wide data axis"
            )
        self.eval_iter = TrialDataIterator(eval_data, trial, eval_bs, seed=0)
        self._key = jax.random.key(1000 + member_id)
        self._step = 0

    def run_generation(self):
        """Dispatch one generation's explore phase (async): K fused
        train steps on the next K batches of this member's stream."""
        batches = next(self._chunks)
        rng = jax.random.fold_in(self._key, self._step)
        self.state, m = self.multi_step(self.state, batches, rng)
        self._step += batches.shape[0]
        return m

    def eval_loss(self) -> float:
        total, n = 0.0, 0
        for batch in self.eval_iter.epoch(0):
            out = self.eval_step(self.state, batch)
            total += float(out["loss_sum"])
            n += batch.shape[0]
        return total / n


def run_pbt(
    cfg: PBTConfig,
    train_data: Dataset,
    eval_data: Dataset,
    *,
    groups: Optional[Sequence[TrialMesh]] = None,
    out_dir: Optional[str] = None,
    verbose: bool = True,
) -> PBTResult:
    """Run synchronous-generation PBT, one member per submesh.

    A generation's explore phase is one scan-fused dispatch per member
    (``steps_per_generation`` optimizer updates in a single host
    round-trip, queued async on every submesh at once); the
    exploit/explore exchange at generation boundaries is the only
    cross-trial coordination — and it is host-side metadata + one
    device_put per exploited member.
    """
    if jax.process_count() > 1:
        raise NotImplementedError(
            "run_pbt currently requires single-controller mode: the "
            "exploit step fetches remote submesh states with device_get, "
            "which cannot address devices owned by other processes. "
            "Multi-host PBT needs a cross-process transfer "
            "(multihost_utils.broadcast) — planned."
        )
    if groups is None:
        groups = setup_groups(cfg.population)
    if len(groups) != cfg.population:
        raise ValueError(
            f"population {cfg.population} but {len(groups)} device groups"
        )

    model = VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)
    rng = np.random.default_rng(cfg.seed)
    init_lrs = np.exp(
        rng.uniform(np.log(cfg.lr_min), np.log(cfg.lr_max), cfg.population)
    )
    members = [
        _Member(g, i, cfg, model, train_data, eval_data, float(init_lrs[i]))
        for i, g in enumerate(groups)
    ]

    # clamp to half the population so the top and bottom slices can never
    # overlap (an overlapping slice would let an exploiter clone a state
    # that was itself just overwritten in the same exchange)
    n_exploit = max(1, int(np.floor(cfg.exploit_fraction * cfg.population)))
    n_exploit = min(n_exploit, cfg.population // 2)
    result = PBTResult(best_member=-1, best_eval_loss=float("inf"))
    t0 = time.time()

    for gen in range(cfg.generations):
        # --- explore phase: one scan-fused dispatch per member puts a
        # full generation of steps in flight on every submesh at once
        for m in members:
            m.run_generation()

        scores = {m.member_id: m.eval_loss() for m in members}
        ranked = sorted(members, key=lambda m: scores[m.member_id])
        record = {
            "generation": gen,
            "scores": {m.member_id: scores[m.member_id] for m in ranked},
            "lrs": {m.member_id: m.lr for m in members},
            "exploits": [],
        }

        # --- exploit/explore: bottom n_exploit copy a top-n_exploit peer
        # (guard: ranked[-0:] would be the WHOLE list, so population=1 —
        # where n_exploit clamps to 0 — must skip the exchange entirely)
        top, bottom = (
            (ranked[:n_exploit], ranked[-n_exploit:]) if n_exploit else ([], [])
        )
        for i, bad in enumerate(bottom):
            good = top[i % len(top)]
            if scores[bad.member_id] <= scores[good.member_id]:
                continue
            # cross-submesh weight + optimizer-state transfer: fetch the
            # winner's replicated state, place it onto the loser's mesh
            cloned = bad.trial.device_put(jax.device_get(good.state))
            factor = float(rng.choice(cfg.perturb_factors))
            new_lr = float(
                np.clip(good.lr * factor, cfg.lr_min, cfg.lr_max)
            )
            bad.state = _set_lr(cloned, new_lr)
            bad.lr = new_lr
            record["exploits"].append(
                {
                    "from": good.member_id,
                    "to": bad.member_id,
                    "new_lr": new_lr,
                }
            )
            if verbose:
                log0(
                    f"PBT gen {gen}: member {bad.member_id} "
                    f"(loss {scores[bad.member_id]:.2f}) exploits "
                    f"{good.member_id} (loss {scores[good.member_id]:.2f}), "
                    f"lr -> {new_lr:.2e}",
                    trial=bad.trial,
                )

        result.history.append(record)
        best = ranked[0]
        if scores[best.member_id] < result.best_eval_loss:
            result.best_eval_loss = scores[best.member_id]
            result.best_member = best.member_id

    result.wall_s = time.time() - t0
    result.final_lrs = [m.lr for m in members]
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "pbt.json"), "w") as f:
            json.dump(
                {
                    "best_member": result.best_member,
                    "best_eval_loss": result.best_eval_loss,
                    "final_lrs": result.final_lrs,
                    "history": result.history,
                    "wall_s": result.wall_s,
                },
                f,
                indent=2,
            )
    return result
