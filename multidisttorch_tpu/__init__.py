"""multidisttorch_tpu — a TPU-native (JAX/XLA/pjit) framework with the
capabilities of ORNL/MultiDistTorch.

The reference framework (``/root/reference``) carves one
torch.distributed job into N process subgroups and runs an independent
DDP training trial in each (``utils.py:146-163``, ``vae-hpo.py:177-202``).
This package is the ground-up TPU rebuild: one global ``jax.sharding.Mesh``
is carved into N disjoint submeshes (pure metadata — no collective
handshake, no rendezvous server, no NIC pinning), each trial runs a
jit-compiled data-parallel train step on its own submesh, and a host-side
HPO driver dispatches trials concurrently with no cross-trial barriers.

Public API (mirrors the reference's ``from utils import *`` surface,
``utils.py:9-174``, re-designed for JAX):

- cluster/runtime bring-up: :func:`initialize_runtime`,
  :func:`detect_process_env`, :func:`parse_slurm_nodelist`,
  :func:`coordinator_address`, :func:`find_ifname`
- size/rank queries: :func:`process_world`, :func:`device_world`
- group carving: :func:`setup_groups`, :class:`TrialMesh`,
  :func:`global_mesh`
- group-scoped collectives: :func:`group_all_gather`, :func:`group_psum`,
  :func:`group_pmean`
- group-aware logging: :func:`log0`
"""

from multidisttorch_tpu.utils.compat import ensure_partitionable_rng

# Mesh-topology-invariant RNG is a framework-level correctness contract
# here (TP/stacked/DP parity tests all depend on it); see the shim's
# docstring for the measured drift under the legacy lowering.
ensure_partitionable_rng()

from multidisttorch_tpu.parallel.cluster import (
    ProcessEnv,
    coordinator_address,
    detect_process_env,
    find_ifname,
    initialize_runtime,
    parse_slurm_nodelist,
    process_world,
    sync_hosts,
)
from multidisttorch_tpu.parallel.mesh import (
    TrialMesh,
    device_world,
    global_mesh,
    setup_groups,
)
from multidisttorch_tpu.parallel.collectives import (
    group_all_gather,
    group_pmean,
    group_psum,
)
from multidisttorch_tpu.utils.logging import log0

__version__ = "0.1.0"

__all__ = [
    "ProcessEnv",
    "TrialMesh",
    "coordinator_address",
    "detect_process_env",
    "device_world",
    "find_ifname",
    "global_mesh",
    "group_all_gather",
    "group_pmean",
    "group_psum",
    "initialize_runtime",
    "log0",
    "parse_slurm_nodelist",
    "process_world",
    "setup_groups",
    "sync_hosts",
]
