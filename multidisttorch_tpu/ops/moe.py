"""Mixture-of-experts MLP with expert parallelism (GShard-style).

The reference has no MoE or expert parallelism (SURVEY.md §2c). This is
the TPU-native formulation: routing is expressed as STATIC one-hot
dispatch/combine einsums (no gather/scatter, no dynamic shapes — the
GShard/Switch recipe), so the whole block jits into a handful of
MXU-friendly contractions. Expert parallelism is then nothing but a
sharding: every expert-indexed parameter carries a leading ``(E, ...)``
axis annotated over the submesh's ``model`` axis
(:func:`moe_ep_shardings`), and GSPMD partitions the dispatch/compute/
combine einsums so each device runs only its experts, inserting the
all-to-all-equivalent collectives itself.

Top-1 routing with a capacity limit: each expert serves at most
``C = ceil(tokens/E * capacity_factor)`` tokens per batch; overflow
tokens pass through with zero contribution (standard Switch behavior).
The auxiliary load-balancing loss (Switch eq. 4) is returned alongside
the output so training can keep the router from collapsing.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Top-1-routed expert MLP: ``(B, d_in) -> (B, d_out)``.

    Parameters carry a leading expert axis — ``gate`` is a plain dense
    router, ``w1/b1/w2/b2`` are per-expert two-layer MLP weights.
    """

    num_experts: int
    hidden_dim: int
    out_dim: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        b, d = x.shape
        e, h, o = self.num_experts, self.hidden_dim, self.out_dim
        cap = max(1, math.ceil(b * self.capacity_factor / e))
        x = x.astype(self.dtype)

        init = nn.initializers.lecun_normal()
        w1 = self.param("w1", init, (e, d, h), jnp.float32).astype(self.dtype)
        b1 = self.param(
            "b1", nn.initializers.zeros, (e, h), jnp.float32
        ).astype(self.dtype)
        w2 = self.param("w2", init, (e, h, o), jnp.float32).astype(self.dtype)
        b2 = self.param(
            "b2", nn.initializers.zeros, (e, o), jnp.float32
        ).astype(self.dtype)

        gates = jax.nn.softmax(
            nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32,
                     name="gate")(x.astype(jnp.float32)),
            axis=-1,
        )  # (B, E) — router math in f32 for stable argmax/softmax
        expert_idx = jnp.argmax(gates, axis=-1)  # (B,)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (B, E)
        top_gate = jnp.sum(gates * onehot, axis=-1)  # (B,)

        # Queue position of each token within its chosen expert; tokens
        # past capacity are dropped (zero dispatch -> zero output).
        pos = jnp.cumsum(onehot, axis=0) * onehot  # (B, E), 1-based
        within = (pos > 0) & (pos <= cap)
        disp = jax.nn.one_hot(
            (pos - 1.0).astype(jnp.int32), cap, dtype=jnp.float32
        ) * within[..., None].astype(jnp.float32)  # (B, E, C)

        expert_in = jnp.einsum(
            "bec,bd->ecd", disp.astype(self.dtype), x
        )  # (E, C, d)
        hmid = jax.nn.relu(
            jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :]
        )
        out_e = jnp.einsum("ech,eho->eco", hmid, w2) + b2[:, None, :]

        combine = disp * top_gate[:, None, None]  # (B, E, C)
        y = jnp.einsum("bec,eco->bo", combine.astype(self.dtype), out_e)

        # Switch aux loss: E * sum_e (fraction routed to e) * (mean gate
        # prob of e) — minimized at uniform routing.
        frac = jnp.mean(onehot, axis=0)
        prob = jnp.mean(gates, axis=0)
        aux = e * jnp.sum(frac * prob)
        return y, aux.astype(jnp.float32)


def moe_ep_shardings(trial, params: Any) -> Any:
    """Expert-parallel shardings for a :class:`MoEMLP` param tree: every
    expert-indexed leaf (leading axis ``num_experts``) splits over the
    submesh's ``model`` axis; the router stays replicated. GSPMD then
    partitions the dispatch/compute/combine einsums per expert shard.

    Requires ``num_experts % trial.model_size == 0``.
    """
    from multidisttorch_tpu.parallel.mesh import MODEL_AXIS

    m = trial.model_size
    repl = trial.sharding()

    def rule(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w1", "b1", "w2", "b2"):
            if leaf.shape[0] % m:
                raise ValueError(
                    f"num_experts={leaf.shape[0]} not divisible by the "
                    f"model axis ({m})"
                )
            return trial.sharding(MODEL_AXIS, *([None] * (leaf.ndim - 1)))
        return repl

    return jax.tree_util.tree_map_with_path(rule, params)
