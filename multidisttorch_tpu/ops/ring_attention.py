"""Ring attention: sequence-parallel exact attention over a device axis.

The reference framework has no attention or sequence dimension at all
(SURVEY.md §5 — its model is an MLP VAE), but a framework claiming its
scale on TPU must handle long-context models whose sequences exceed one
chip's HBM. This op shards the sequence across a (sub)mesh axis and
computes **exact** softmax attention by rotating K/V blocks around the
ring with ``jax.lax.ppermute`` (ICI neighbor exchanges — the topology
ring attention was designed for), carrying the online-softmax running
max/sum so no device ever materializes the full (T, T) score matrix.

Memory per device: O(T/n · T/n) scores instead of O(T²); communication:
n-1 neighbor hops of the local K/V block, overlapped by XLA with the
per-block compute. Composes with the framework's trial parallelism: the
ring axis is any ``TrialMesh``'s data axis, so one trial can run
sequence-parallel attention while others train unrelated models.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from multidisttorch_tpu.utils.compat import shard_map as compat_shard_map
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, TrialMesh


def _attention_block(q, k, v, q_pos, k_pos, m, l, acc, *, causal, scale):
    """One online-softmax update of local Q against one K/V block.

    q: (B, Tq, H, D); k, v: (B, Tk, H, D); m, l: (B, H, Tq);
    acc: (B, Tq, H, D). Standard flash-attention running update.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # (B, H, Tq, Tk)
    if causal:
        mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
        s = jnp.where(mask, s, -jnp.inf)
    blk_max = jnp.max(s, axis=-1)  # (B, H, Tq)
    m_new = jnp.maximum(m, blk_max)
    # guard fully-masked rows (m_new == -inf): keep them at zero weight
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])  # (B, H, Tq, Tk)
    if causal:
        p = jnp.where(jnp.isfinite(s), p, 0.0)
    correction = jnp.where(
        jnp.isfinite(m), jnp.exp(m - safe_m), jnp.zeros_like(m)
    )
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return m_new, l_new, acc_new


def _ring_attention_local(
    q, k, v, *, axis_name, num_devices, causal, scale, vary_axes=None
):
    """Per-device body under shard_map: local Q stays put, K/V rotate.

    ``vary_axes`` lists every mesh axis the operands vary over — just
    the ring axis in 1-D mode, plus the model axis when heads are
    sharded (2-D sequence x head parallelism). The body itself is
    oblivious to the head count: attention is per-head local math.
    """
    my_idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_pos = my_idx * t_local + jnp.arange(t_local)

    b, _, h, d = q.shape
    # The carry starts as constants but becomes device-varying through
    # the loop body; shard_map's VMA typing requires the initial carry
    # to carry the axis annotation already.
    from multidisttorch_tpu.parallel.collectives import pvary

    axes = vary_axes if vary_axes is not None else (axis_name,)
    m0 = pvary(jnp.full((b, h, t_local), -jnp.inf, jnp.float32), axes)
    l0 = pvary(jnp.zeros((b, h, t_local), jnp.float32), axes)
    acc0 = pvary(jnp.zeros((b, t_local, h, d), jnp.float32), axes)

    def body(step, carry):
        k_blk, v_blk, m, l, acc = carry
        src_idx = (my_idx - step) % num_devices
        k_pos = src_idx * t_local + jnp.arange(t_local)
        m, l, acc = _attention_block(
            q.astype(jnp.float32),
            k_blk.astype(jnp.float32),
            v_blk.astype(jnp.float32),
            q_pos,
            k_pos,
            m,
            l,
            acc,
            causal=causal,
            scale=scale,
        )
        # rotate K/V one hop around the ring (device i -> i+1), so next
        # step this device holds the block of (my_idx - step - 1) % n
        perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(0, num_devices, body, (k, v, m0, l0, acc0))
    # normalize; fully-masked rows (l == 0) return zeros
    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


@lru_cache(maxsize=None)
def _make_ring_attention_cached(
    mesh: Mesh, axis_name: str, causal: bool, head_axis: str | None = None
):
    num_devices = int(mesh.shape[axis_name])
    # sequence sharded over the ring axis; heads over the model axis
    # when 2-D (sequence x head) parallelism is on
    spec = P(None, axis_name, head_axis, None)
    vary_axes = (axis_name,) + ((head_axis,) if head_axis else ())

    def fn(q, k, v):
        scale = 1.0 / (q.shape[-1] ** 0.5)
        return compat_shard_map(
            partial(
                _ring_attention_local,
                axis_name=axis_name,
                num_devices=num_devices,
                causal=causal,
                scale=scale,
                vary_axes=vary_axes,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return jax.jit(fn)


def _resolve_head_axis(mesh: Mesh, shard_heads) -> str | None:
    """Shared by ring and ring-flash: which mesh axis (if any) shards
    the head dimension. ``"auto"`` shards whenever the trial actually
    has a model axis — the 2-D (sequence x head) configuration."""
    from multidisttorch_tpu.parallel.mesh import MODEL_AXIS

    m = int(dict(mesh.shape).get(MODEL_AXIS, 1))
    if shard_heads == "auto":
        return MODEL_AXIS if m > 1 else None
    if shard_heads:
        if m <= 1:
            raise ValueError(
                "shard_heads=True needs a model axis on the trial mesh "
                "(setup_groups(model_parallel=...))"
            )
        return MODEL_AXIS
    return None


def _wrap_head_check(inner, mesh: Mesh, head_axis: str | None):
    """Shared by ring and ring-flash entry points: validate head
    divisibility at call time and expose ``.head_sharded``."""
    m = int(mesh.shape[head_axis]) if head_axis else 1

    def fn(q, k, v):
        if head_axis and q.shape[2] % m:
            raise ValueError(
                f"heads={q.shape[2]} not divisible by the model axis "
                f"({m}); pass shard_heads=False or adjust the model"
            )
        return inner(q, k, v)

    fn.head_sharded = head_axis is not None
    # Ring callables always run a shard_map with ppermute hops — the
    # marker pipeline staging checks (a collective cannot execute
    # inside a lax.switch branch only some devices take).
    fn.carries_collectives = True
    return fn


def make_ring_attention(
    trial: TrialMesh | Mesh, *, causal: bool = False, shard_heads="auto"
):
    """Compiled sequence-parallel attention over a trial's device axis.

    Returns ``fn(q, k, v) -> out`` for arrays of shape ``(batch, seq,
    heads, head_dim)`` with ``seq`` divisible by the data-axis extent;
    the sequence dimension is sharded across the ring, and the result
    is numerically exact attention (fp32 accumulation). On a 2-D
    ``(data x model)`` trial mesh, heads additionally shard over the
    model axis (``shard_heads="auto"``; heads must divide it) — the
    sequence x head parallel configuration that composes with
    ``transformer_tp_shardings``'s attention-column shards. The
    returned callable exposes ``.head_sharded`` for introspection.
    """
    mesh = trial.mesh if isinstance(trial, TrialMesh) else trial
    head_axis = _resolve_head_axis(mesh, shard_heads)
    inner = _make_ring_attention_cached(mesh, DATA_AXIS, causal, head_axis)
    return _wrap_head_check(inner, mesh, head_axis)


def dense_attention_reference(q, k, v, *, causal: bool = False):
    """O(T²) single-device reference for testing."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tk)[None, :] <= jnp.arange(tq)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
