from multidisttorch_tpu.ops.losses import (
    bernoulli_recon_sum,
    elbo_loss_sum,
    gaussian_kl_sum,
    softmax_cross_entropy_mean,
)
from multidisttorch_tpu.ops.moe import MoEMLP, moe_ep_shardings
from multidisttorch_tpu.ops.pallas_attention import (
    flash_attention,
    make_flash_attention,
    make_ring_flash_attention,
)
from multidisttorch_tpu.ops.pallas_elbo import fused_elbo_loss_sum
from multidisttorch_tpu.ops.ring_attention import (
    dense_attention_reference,
    make_ring_attention,
)
