from multidisttorch_tpu.ops.losses import (
    bernoulli_recon_sum,
    elbo_loss_sum,
    gaussian_kl_sum,
    softmax_cross_entropy_mean,
)
