"""Loss functions.

TPU-native rebuild of the reference's ``loss_function``
(``/root/reference/vae-hpo.py:49-58``): summed Bernoulli reconstruction
error plus the analytic Gaussian KL term. Two deliberate changes:

- The reconstruction term is computed **from logits**
  (``sigmoid_binary_cross_entropy``) instead of from post-sigmoid
  probabilities as the reference does. Mathematically identical, but
  numerically stable in bfloat16/float32 on the MXU (no ``log(p)`` of a
  saturated sigmoid) and it lets XLA fuse the sigmoid into the loss.
- ``beta`` generalizes to β-VAE (BASELINE.md config 3); ``beta=1``
  reproduces the reference exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def bernoulli_recon_per_sample(
    recon_logits: jnp.ndarray, x: jnp.ndarray
) -> jnp.ndarray:
    """Per-sample binary cross-entropy from logits, shape ``(n,)``.

    Computed stably as ``max(l,0) - l*x + log1p(exp(-|l|))`` summed over
    each sample's features — the single source of the BCE expression for
    both the summed and weighted variants below.
    """
    l = recon_logits
    per_elem = jnp.maximum(l, 0.0) - l * x + jnp.log1p(jnp.exp(-jnp.abs(l)))
    return jnp.sum(per_elem.reshape(per_elem.shape[0], -1), axis=1)


def gaussian_kl_per_sample(
    mu: jnp.ndarray, logvar: jnp.ndarray
) -> jnp.ndarray:
    """Per-sample ``-0.5 * sum(1 + logvar - mu^2 - exp(logvar))``, shape
    ``(n,)``."""
    return -0.5 * jnp.sum(
        1.0 + logvar - jnp.square(mu) - jnp.exp(logvar), axis=1
    )


def bernoulli_recon_sum(recon_logits: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Summed binary cross-entropy from logits.

    Equals ``F.binary_cross_entropy(sigmoid(logits), x, reduction="sum")``
    (``vae-hpo.py:50``) up to float rounding.
    """
    return jnp.sum(bernoulli_recon_per_sample(recon_logits, x))


def gaussian_kl_sum(mu: jnp.ndarray, logvar: jnp.ndarray) -> jnp.ndarray:
    """``-0.5 * sum(1 + logvar - mu^2 - exp(logvar))`` (``vae-hpo.py:56``)."""
    return jnp.sum(gaussian_kl_per_sample(mu, logvar))


def elbo_loss_sum(
    recon_logits: jnp.ndarray,
    x: jnp.ndarray,
    mu: jnp.ndarray,
    logvar: jnp.ndarray,
    beta: float = 1.0,
) -> jnp.ndarray:
    """Negative ELBO summed over the batch: ``BCE + beta * KLD``.

    ``beta=1.0`` is the reference's ``loss_function``
    (``vae-hpo.py:49-58``); the sum reduction (not mean) is part of the
    reference contract — per-sample figures are derived by dividing by
    the batch size at the logging sites (``vae-hpo.py:83,89,118``).
    """
    return bernoulli_recon_sum(recon_logits, x) + beta * gaussian_kl_sum(mu, logvar)


def elbo_loss_weighted_sum(
    recon_logits: jnp.ndarray,
    x: jnp.ndarray,
    mu: jnp.ndarray,
    logvar: jnp.ndarray,
    weights: jnp.ndarray,
    beta: float = 1.0,
) -> jnp.ndarray:
    """Per-sample negative ELBO dotted with a weight vector.

    ``weights`` is 1.0 for real rows and 0.0 for padding, so a padded
    final batch contributes exactly the real rows' loss — this is how
    eval consumes *every* test row under XLA's static-shape requirement
    (the reference's ``test`` iterates the full test set including the
    partial final batch, ``vae-hpo.py:101-105``; dropping the tail would
    make reported test losses non-comparable). ``weights=ones`` reduces
    to :func:`elbo_loss_sum` exactly.
    """
    per_sample = bernoulli_recon_per_sample(
        recon_logits, x
    ) + beta * gaussian_kl_per_sample(mu, logvar)
    return jnp.dot(per_sample, weights)


def softmax_cross_entropy_mean(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels (classifier HPO,
    BASELINE.md config 4)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)
