"""Loss functions.

TPU-native rebuild of the reference's ``loss_function``
(``/root/reference/vae-hpo.py:49-58``): summed Bernoulli reconstruction
error plus the analytic Gaussian KL term. Two deliberate changes:

- The reconstruction term is computed **from logits**
  (``sigmoid_binary_cross_entropy``) instead of from post-sigmoid
  probabilities as the reference does. Mathematically identical, but
  numerically stable in bfloat16/float32 on the MXU (no ``log(p)`` of a
  saturated sigmoid) and it lets XLA fuse the sigmoid into the loss.
- ``beta`` generalizes to β-VAE (BASELINE.md config 3); ``beta=1``
  reproduces the reference exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def bernoulli_recon_sum(recon_logits: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Summed binary cross-entropy from logits.

    Equals ``F.binary_cross_entropy(sigmoid(logits), x, reduction="sum")``
    (``vae-hpo.py:50``) up to float rounding, computed stably as
    ``max(l,0) - l*x + log1p(exp(-|l|))`` summed over all elements.
    """
    l = recon_logits
    per_elem = jnp.maximum(l, 0.0) - l * x + jnp.log1p(jnp.exp(-jnp.abs(l)))
    return jnp.sum(per_elem)


def gaussian_kl_sum(mu: jnp.ndarray, logvar: jnp.ndarray) -> jnp.ndarray:
    """``-0.5 * sum(1 + logvar - mu^2 - exp(logvar))`` (``vae-hpo.py:56``)."""
    return -0.5 * jnp.sum(1.0 + logvar - jnp.square(mu) - jnp.exp(logvar))


def elbo_loss_sum(
    recon_logits: jnp.ndarray,
    x: jnp.ndarray,
    mu: jnp.ndarray,
    logvar: jnp.ndarray,
    beta: float = 1.0,
) -> jnp.ndarray:
    """Negative ELBO summed over the batch: ``BCE + beta * KLD``.

    ``beta=1.0`` is the reference's ``loss_function``
    (``vae-hpo.py:49-58``); the sum reduction (not mean) is part of the
    reference contract — per-sample figures are derived by dividing by
    the batch size at the logging sites (``vae-hpo.py:83,89,118``).
    """
    return bernoulli_recon_sum(recon_logits, x) + beta * gaussian_kl_sum(mu, logvar)


def softmax_cross_entropy_mean(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels (classifier HPO,
    BASELINE.md config 4)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)
