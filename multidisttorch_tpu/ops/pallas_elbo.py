"""Fused negative-ELBO Pallas TPU kernel (forward + backward).

The ELBO (``ops/losses.py``, mirroring /root/reference/vae-hpo.py:49-58)
is a pure bandwidth-bound reduction over four arrays (logits, targets,
mu, logvar). XLA already fuses most of it; this kernel makes the fusion
explicit and total — one VMEM pass producing the scalar loss, and one
pass producing all three gradients — and serves as the repo's reference
pattern for Pallas TPU kernels (per /opt/skills/guides/pallas_guide.md).

Differentiable via ``jax.custom_vjp``: the backward kernel computes
  d/dlogits  = sigmoid(logits) - x          (BCE-from-logits)
  d/dmu      = beta * mu                    (KL)
  d/dlogvar  = beta * 0.5 * (exp(logvar) - 1)
all scaled by the upstream cotangent.

Falls back to interpreter mode off-TPU (bit-exact semantics, usable in
CPU tests), and the public entry point degrades to the plain jnp
implementation if Pallas is unavailable.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from multidisttorch_tpu.ops.losses import elbo_loss_sum

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# VMEM working-set budget for one grid step (both passes keep ≤4 operand
# blocks + ≤3 output blocks resident; v5e VMEM is 128MB/core but small
# blocks pipeline better and leave room for XLA's own buffers). Module
# constant so tests can shrink it to force multi-block grids.
_VMEM_BUDGET_BYTES = 4 * 2**20


def _block_rows(batch: int, d: int, latent: int) -> int:
    """Largest divisor of ``batch`` whose 7-buffer working set fits the
    VMEM budget (whole rows only: the feature dims stay unsplit, so the
    reduction needs no cross-column accumulator)."""
    per_row = 4 * (4 * d + 3 * latent)  # f32: l,x,dl dL blocks + mu/lv/dmu/dlv
    target = max(1, _VMEM_BUDGET_BYTES // per_row)
    if batch <= target:
        return batch
    for bb in range(target, 0, -1):
        if batch % bb == 0:
            return bb
    return batch  # unreachable (bb=1 always divides)


def _fwd_kernel(logits_ref, x_ref, mu_ref, logvar_ref, out_ref, *, beta):
    l = logits_ref[:]
    x = x_ref[:]
    # stable BCE from logits: max(l,0) - l*x + log1p(exp(-|l|))
    bce = jnp.sum(
        jnp.maximum(l, 0.0) - l * x + jnp.log1p(jnp.exp(-jnp.abs(l)))
    )
    mu = mu_ref[:]
    logvar = logvar_ref[:]
    kl = -0.5 * jnp.sum(1.0 + logvar - mu * mu - jnp.exp(logvar))
    part = bce + beta * kl

    # Scalar accumulation across the (sequential) batch-block grid: the
    # SMEM output block is the same (0,0) cell every step.
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[0, 0] = part

    @pl.when(pl.program_id(0) > 0)
    def _acc():
        out_ref[0, 0] = out_ref[0, 0] + part


def _bwd_kernel(logits_ref, x_ref, mu_ref, logvar_ref,
                dlogits_ref, dmu_ref, dlogvar_ref, *, beta):
    l = logits_ref[:]
    dlogits_ref[:] = jax.nn.sigmoid(l) - x_ref[:]
    dmu_ref[:] = beta * mu_ref[:]
    dlogvar_ref[:] = beta * 0.5 * (jnp.exp(logvar_ref[:]) - 1.0)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_elbo_loss_sum(logits, x, mu, logvar, beta=1.0):
    """Summed negative ELBO, fused in a single Pallas kernel.

    Drop-in for :func:`ops.losses.elbo_loss_sum` (same semantics as the
    reference loss at beta=1). Arrays must be float32 2-D ``(batch, D)``
    / ``(batch, latent)``.
    """
    return _fwd(logits, x, mu, logvar, beta)[0]


def _fwd(logits, x, mu, logvar, beta):
    b, d = logits.shape
    lat = mu.shape[1]
    bb = _block_rows(b, d, lat)
    out = pl.pallas_call(
        partial(_fwd_kernel, beta=beta),
        grid=(b // bb,),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        in_specs=[
            pl.BlockSpec((bb, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, lat), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, lat), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=_interpret(),
    )(logits, x, mu, logvar)
    return out[0, 0], (logits, x, mu, logvar)


def _bwd(beta, residuals, g):
    logits, x, mu, logvar = residuals
    b, d = logits.shape
    lat = mu.shape[1]
    bb = _block_rows(b, d, lat)
    wide = lambda: pl.BlockSpec(
        (bb, d), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    narrow = lambda: pl.BlockSpec(
        (bb, lat), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    dlogits, dmu, dlogvar = pl.pallas_call(
        partial(_bwd_kernel, beta=beta),
        grid=(b // bb,),
        out_shape=(
            jax.ShapeDtypeStruct(logits.shape, jnp.float32),
            jax.ShapeDtypeStruct(mu.shape, jnp.float32),
            jax.ShapeDtypeStruct(logvar.shape, jnp.float32),
        ),
        in_specs=[wide(), wide(), narrow(), narrow()],
        out_specs=(wide(), narrow(), narrow()),
        interpret=_interpret(),
    )(logits, x, mu, logvar)
    # x is data: propagate its true cotangent (-logits * g) for
    # completeness even though training never differentiates w.r.t. it.
    return (g * dlogits, g * (-logits), g * dmu, g * dlogvar)


fused_elbo_loss_sum.defvjp(_fwd, _bwd)


def elbo_loss_sum_auto(logits, x, mu, logvar, beta=1.0):
    """Use the fused kernel when Pallas is available, else plain jnp."""
    if _HAVE_PALLAS:
        return fused_elbo_loss_sum(logits, x, mu, logvar, beta)
    return elbo_loss_sum(logits, x, mu, logvar, beta)
