"""Fused negative-ELBO Pallas TPU kernel (forward + backward).

The ELBO (``ops/losses.py``, mirroring /root/reference/vae-hpo.py:49-58)
is a pure bandwidth-bound reduction over four arrays (logits, targets,
mu, logvar). XLA already fuses most of it; this kernel makes the fusion
explicit and total — one VMEM pass producing the scalar loss, and one
pass producing all three gradients — and serves as the repo's reference
pattern for Pallas TPU kernels (per /opt/skills/guides/pallas_guide.md).

Differentiable via ``jax.custom_vjp``: the backward kernel computes
  d/dlogits  = sigmoid(logits) - x          (BCE-from-logits)
  d/dmu      = beta * mu                    (KL)
  d/dlogvar  = beta * 0.5 * (exp(logvar) - 1)
all scaled by the upstream cotangent.

Falls back to interpreter mode off-TPU (bit-exact semantics, usable in
CPU tests), and the public entry point degrades to the plain jnp
implementation if Pallas is unavailable.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from multidisttorch_tpu.ops.losses import elbo_loss_sum

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# VMEM working-set budget for one grid step (both passes keep ≤4 operand
# blocks + ≤3 output blocks resident; v5e VMEM is 128MB/core but small
# blocks pipeline better and leave room for XLA's own buffers). Module
# constant so tests can shrink it to force multi-block grids.
_VMEM_BUDGET_BYTES = 4 * 2**20


def _block_rows(logits, x, mu, logvar) -> int:
    """Largest divisor of ``batch`` whose 7-buffer working set fits the
    VMEM budget (whole rows only: the feature dims stay unsplit, so the
    reduction needs no cross-column accumulator). Sized from the actual
    operand dtypes — bf16 blocks are half the bytes of f32, so the bf16
    train path gets twice the rows per grid step."""
    batch, d = logits.shape
    latent = mu.shape[1]
    # Worst-case resident set (the bwd pass): logits, x, dlogits wide;
    # mu, logvar, dmu, dlogvar narrow — outputs at their primal's dtype.
    per_row = d * (2 * logits.dtype.itemsize + x.dtype.itemsize) + latent * 2 * (
        mu.dtype.itemsize + logvar.dtype.itemsize
    )
    target = max(1, _VMEM_BUDGET_BYTES // per_row)
    if batch <= target:
        return batch
    for bb in range(target, 0, -1):
        if batch % bb == 0:
            return bb
    return batch  # unreachable (bb=1 always divides)


def _fwd_kernel(logits_ref, x_ref, mu_ref, logvar_ref, out_ref, *, beta):
    # Blocks stream in at their storage dtype (bf16 on the TPU train
    # path — half the HBM bytes of f32); the reduction itself is f32.
    l = logits_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    # stable BCE from logits: max(l,0) - l*x + log1p(exp(-|l|))
    bce = jnp.sum(
        jnp.maximum(l, 0.0) - l * x + jnp.log1p(jnp.exp(-jnp.abs(l)))
    )
    mu = mu_ref[:].astype(jnp.float32)
    logvar = logvar_ref[:].astype(jnp.float32)
    kl = -0.5 * jnp.sum(1.0 + logvar - mu * mu - jnp.exp(logvar))
    part = bce + beta * kl

    # Scalar accumulation across the (sequential) batch-block grid: the
    # SMEM output block is the same (0,0) cell every step. Every store
    # casts to the REF's dtype explicitly: Mosaic rejects a swap whose
    # value dtype strays from the ref (the round-4 hardware failure —
    # "Invalid dtype for swap: Ref float32 vs value bfloat16" — when
    # bf16 operands reached this accumulator; interpret mode casts
    # silently, so only the explicit cast keeps both worlds identical).
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[0, 0] = part.astype(out_ref.dtype)

    @pl.when(pl.program_id(0) > 0)
    def _acc():
        out_ref[0, 0] = (
            out_ref[0, 0].astype(jnp.float32) + part
        ).astype(out_ref.dtype)


def _bwd_kernel(logits_ref, x_ref, mu_ref, logvar_ref,
                dlogits_ref, dmu_ref, dlogvar_ref, *, beta):
    # f32 math, outputs stored back at each cotangent's own dtype
    # (= its primal's dtype, per custom_vjp's contract).
    l = logits_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    dlogits_ref[:] = (jax.nn.sigmoid(l) - x).astype(dlogits_ref.dtype)
    dmu_ref[:] = (beta * mu_ref[:].astype(jnp.float32)).astype(dmu_ref.dtype)
    dlogvar_ref[:] = (
        beta * 0.5 * (jnp.exp(logvar_ref[:].astype(jnp.float32)) - 1.0)
    ).astype(dlogvar_ref.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_elbo_loss_sum(logits, x, mu, logvar, beta=1.0):
    """Summed negative ELBO, fused in a single Pallas kernel.

    Drop-in for :func:`ops.losses.elbo_loss_sum` (same semantics as the
    reference loss at beta=1). Arrays are 2-D ``(batch, D)`` /
    ``(batch, latent)`` in any float dtype (mixed ok — the TPU train
    path feeds bf16 activations with f32 targets); reduction math is
    always f32, gradients come back in each primal's own dtype.
    """
    return _fwd(logits, x, mu, logvar, beta)[0]


def _fwd(logits, x, mu, logvar, beta):
    b, d = logits.shape
    lat = mu.shape[1]
    bb = _block_rows(logits, x, mu, logvar)
    out = pl.pallas_call(
        partial(_fwd_kernel, beta=beta),
        grid=(b // bb,),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        in_specs=[
            pl.BlockSpec((bb, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, lat), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, lat), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=_interpret(),
    )(logits, x, mu, logvar)
    return out[0, 0], (logits, x, mu, logvar)


def _bwd(beta, residuals, g):
    logits, x, mu, logvar = residuals
    b, d = logits.shape
    lat = mu.shape[1]
    bb = _block_rows(logits, x, mu, logvar)
    wide = lambda: pl.BlockSpec(
        (bb, d), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    narrow = lambda: pl.BlockSpec(
        (bb, lat), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    dlogits, dmu, dlogvar = pl.pallas_call(
        partial(_bwd_kernel, beta=beta),
        grid=(b // bb,),
        out_shape=(
            jax.ShapeDtypeStruct(logits.shape, logits.dtype),
            jax.ShapeDtypeStruct(mu.shape, mu.dtype),
            jax.ShapeDtypeStruct(logvar.shape, logvar.dtype),
        ),
        in_specs=[wide(), wide(), narrow(), narrow()],
        out_specs=(wide(), narrow(), narrow()),
        interpret=_interpret(),
    )(logits, x, mu, logvar)
    # x is data: propagate its true cotangent (-logits * g) for
    # completeness even though training never differentiates w.r.t. it.
    # Cotangent dtypes must equal primal dtypes (custom_vjp contract).
    return (
        (g * dlogits).astype(logits.dtype),
        (g * (-logits)).astype(x.dtype),
        (g * dmu).astype(mu.dtype),
        (g * dlogvar).astype(logvar.dtype),
    )


fused_elbo_loss_sum.defvjp(_fwd, _bwd)


def elbo_loss_sum_auto(logits, x, mu, logvar, beta=1.0):
    """Use the fused kernel when Pallas is available, else plain jnp."""
    if _HAVE_PALLAS:
        return fused_elbo_loss_sum(logits, x, mu, logvar, beta)
    return elbo_loss_sum(logits, x, mu, logvar, beta)
