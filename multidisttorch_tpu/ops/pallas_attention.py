"""Blockwise (flash) causal attention as Pallas TPU kernels.

The reference framework has no attention at all (SURVEY.md §5); this
repo's long-context story is ring attention across chips
(``ops/ring_attention.py``) — but *within* one chip the attention block
still materializes the full ``(B, H, Tq, Tk)`` score matrix in HBM,
which caps single-chip context length and wastes bandwidth on the
framework's own TransformerLM. This module is the single-chip half of
the long-context design: an exact, online-softmax attention that tiles
Q/K/V into VMEM blocks, keeps the running max/sum in VMEM scratch, and
never writes scores to HBM. Forward and backward are both Pallas
kernels wired through ``jax.custom_vjp`` (the backward recomputes
probabilities from the saved per-row logsumexp — the standard
flash-attention memory trade).

Layout contract matches ``make_ring_attention``: ``(batch, seq, heads,
head_dim)``; bf16 or f32 in, accumulation always f32. Off-TPU the
kernels run in interpreter mode (bit-exact semantics, used by the CPU
test suite). Sequence lengths divisible by 128 tile at the MXU edge;
other lengths run as one whole-sequence block (see
:func:`flash_attention`). The dense fallback applies only when Pallas
itself is unavailable.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from multidisttorch_tpu.utils.compat import (
    pallas_tpu_compiler_params,
    shard_map as compat_shard_map,
)
from multidisttorch_tpu.ops.ring_attention import dense_attention_reference

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Q/K tile edge: 128 matches the MXU systolic array; shorter sequences
# use the whole sequence as one block.
_BLOCK = 128
_NEG_INF = -1e30  # finite sentinel: -inf rows poison exp() on the VPU

# Largest non-128-divisible T allowed to run as one whole-sequence
# block. The whole-block path keeps the (T, T) f32 score tile plus
# three (T, d) operand tiles resident in VMEM — ~4.5 MB at T=1024,
# d=64, comfortably inside a v5e core's budget; at T=8256 the score
# tile alone is 272 MB and the kernel fails at Mosaic compile time.
# Above this, causal inputs are padded to the tile edge (exact — see
# flash_attention) and non-causal inputs get a clear error instead of
# a compile-time blowup (ADVICE r4).
_MAX_WHOLE_BLOCK = 1024


def _blocks(t: int) -> int:
    return _BLOCK if t % _BLOCK == 0 else t


def _out_struct(shape, dtype, like):
    """``ShapeDtypeStruct`` carrying the operands' varying-mesh-axes
    type. Under a ``check_vma=True`` ``shard_map`` (e.g. the pipeline's
    staged forward, parallel/pipeline.py) a pallas_call must declare
    its outputs' VMA explicitly or tracing rejects it; propagating the
    input's vma makes the kernels VMA-transparent (outside shard_map
    ``typeof(x).vma`` is empty and this is a no-op). Jaxlibs that
    predate VMA typing (0.4.x — no ``jax.typeof``, no ``vma=`` kwarg,
    and shard_map runs with the legacy ``check_rep`` checker instead,
    utils/compat.py) need no annotation at all."""
    if hasattr(jax, "typeof"):
        return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_sc, l_sc,
                *, scale, causal, block_q, block_k):
    """Grid (BH, nq, nk), nk innermost ("arbitrary"): one Q block's
    online-softmax accumulation across K blocks, carried in VMEM
    scratch; outputs written on the last K step."""
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc[:] = jnp.zeros_like(acc)

    # Causal: K blocks strictly above the diagonal contribute nothing.
    # (`causal` is static; the block comparison is traced — they can't
    # share one boolean expression.)
    q_start = iq * block_q
    k_start = ik * block_k

    def _block():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_sc[:]  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # rows at _NEG_INF underflow to 0 exactly
        corr = jnp.exp(m_prev - m_new)
        l_sc[:] = l_sc[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:] = m_new
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_block)
    else:
        _block()

    @pl.when(ik == nk - 1)
    def _emit():
        l = l_sc[:]
        denom = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc[:] / denom).astype(o_ref.dtype)
        # logsumexp per row — the one residual the backward needs to
        # rebuild p without the (Tq, Tk) matrix.
        lse_ref[0] = (m_sc[:] + jnp.log(denom))[:, 0]


def _fwd_call(q, k, v, scale, causal):
    bh, t, d = q.shape
    bq, bk = _blocks(t), _blocks(t)
    kernel = partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk
    )
    grid = (bh, t // bq, t // bk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            _out_struct((bh, t, d), q.dtype, q),
            _out_struct((bh, t), jnp.float32, q),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),   # acc
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running sum
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    """Grid (BH, nq, nk): dQ for one Q block, accumulated across K."""
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = ik * block_k

    def _block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, None])  # exact probs via saved lse
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, None]) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_block)
    else:
        _block()

    @pl.when(ik == nk - 1)
    def _emit():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k):
    """Grid (BH, nk, nq): dK/dV for one K block, accumulated across Q."""
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * block_q
    k_start = ik * block_k

    def _block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, None])  # (block_q, block_k)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, None]) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_block)
    else:
        _block()

    @pl.when(iq == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, scale, causal, g_lse=None):
    bh, t, d = q.shape
    bq, bk = _blocks(t), _blocks(t)
    # delta_i = rowsum(dO ⊙ O): tiny elementwise reduce; XLA fuses it.
    # An lse cotangent folds in here with no kernel change: the shared
    # score gradient is ds = p·(dp − delta + g_lse), and the kernels
    # compute ds = p·(dp − delta'), so delta' = delta − g_lse.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (bh, t)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)

    wide = lambda blk: pl.BlockSpec(
        (1, blk, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
    )
    row = pl.BlockSpec((1, bq), lambda b, i, j: (b, i),
                       memory_space=pltpu.VMEM)
    other = lambda blk: pl.BlockSpec(
        (1, blk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM
    )
    other_row = pl.BlockSpec((1, bq), lambda b, i, j: (b, j),
                             memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, scale=scale, causal=causal,
                block_q=bq, block_k=bk),
        grid=(bh, t // bq, t // bk),
        in_specs=[wide(bq), other(bk), other(bk), wide(bq), row, row],
        out_specs=wide(bq),
        out_shape=_out_struct(q.shape, q.dtype, q),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                block_q=bq, block_k=bk),
        grid=(bh, t // bk, t // bq),
        in_specs=[other(bq), wide(bk), wide(bk), other(bq),
                  other_row, other_row],
        out_specs=(wide(bk), wide(bk)),
        out_shape=(
            _out_struct(k.shape, k.dtype, k),
            _out_struct(v.shape, v.dtype, v),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------
# public entry (custom_vjp over the (BH, T, D)-flattened layout)
# ---------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_flat_lse(q, k, v, scale, causal):
    """``(o, lse)`` over the flattened ``(BH, T, D)`` layout.

    Exposing lse (per-row logsumexp of the scores) with a real VJP is
    what lets :func:`make_ring_flash_attention` combine per-hop partial
    attentions differentiably — the hop weights are ``exp(lse_h − m)``,
    so gradients flow into lse, not just into ``o``.
    """
    return _fwd_call(q, k, v, scale, causal)


def _flash_flat_fwd(q, k, v, scale, causal):
    o, lse = _fwd_call(q, k, v, scale, causal)
    return (o, lse), (q, k, v, o, lse)


def _flash_flat_bwd(scale, causal, res, g):
    q, k, v, o, lse = res
    g_o, g_lse = g
    dq, dk, dv = _bwd_call(
        q, k, v, o, lse, g_o, scale, causal, g_lse=g_lse
    )
    return dq, dk, dv


_flash_flat_lse.defvjp(_flash_flat_fwd, _flash_flat_bwd)


def flash_attention(q, k, v, *, causal: bool = False):
    """Exact blockwise attention; drop-in for
    :func:`ops.ring_attention.dense_attention_reference`.

    ``q, k, v``: ``(batch, seq, heads, head_dim)``, bf16 or f32. Scores
    and the softmax never touch HBM; memory is O(T·D) instead of O(T²).
    Sequences that are a multiple of 128 tile at the MXU edge; shorter
    non-divisible sequences (≤ ``_MAX_WHOLE_BLOCK``) run as one
    whole-sequence block. A LARGE non-divisible T is handled per the
    mask structure: causal inputs are zero-padded up to the tile edge
    and the output sliced back — exact, because the causal mask keeps
    every real query from seeing the appended keys, and the sliced
    rows carry zero cotangent so padded queries contribute nothing to
    dK/dV — while non-causal inputs (where appended keys WOULD be
    attended) raise instead of blowing VMEM at Mosaic compile time.
    """
    if not _HAVE_PALLAS:
        return dense_attention_reference(q, k, v, causal=causal)
    b, t, h, d = q.shape
    if t % _BLOCK and t > _MAX_WHOLE_BLOCK:
        if not causal:
            raise ValueError(
                f"flash_attention: non-causal seq_len {t} is neither a "
                f"multiple of {_BLOCK} nor small enough "
                f"(<= {_MAX_WHOLE_BLOCK}) for the whole-sequence block "
                f"path; pad the sequence to a multiple of {_BLOCK} and "
                "mask in the caller"
            )
        pad = -t % _BLOCK
        spec = ((0, 0), (0, pad), (0, 0), (0, 0))
        return flash_attention(
            jnp.pad(q, spec), jnp.pad(k, spec), jnp.pad(v, spec),
            causal=True,
        )[:, :t]
    scale = 1.0 / (d**0.5)
    # (B, T, H, D) -> (B*H, T, D): each (batch, head) pair is an
    # independent attention problem and a grid row.
    to_flat = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    o, _ = _flash_flat_lse(to_flat(q), to_flat(k), to_flat(v), scale, causal)
    return o.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def make_flash_attention(*, causal: bool = True):
    """An ``attention=`` callable for :class:`models.transformer
    .TransformerLM` using the Pallas kernel on the chip-local sequence.

    TP note (ADVICE r4): the math is per-head-local, but the callable
    runs as one ``pallas_call`` under ``jit`` with no partitioning
    spec, so GSPMD cannot split it over a model axis —
    ``transformer_tp_shardings(..., "auto")`` therefore keeps the
    attention projections replicated when this callable is installed.
    That decision is signaled explicitly via ``head_sharded = False``
    (the same introspection attribute the ring factories set) rather
    than falling out of a missing attribute. For head-parallel TP with
    flash semantics, use :func:`make_ring_flash_attention` with
    ``shard_heads="auto"`` — its ``shard_map`` places one flash kernel
    per model-axis shard.
    """

    def attn(q, k, v):
        return flash_attention(q, k, v, causal=causal)

    attn.head_sharded = False  # single unsharded pallas_call: auto TP
    # must keep q/k/v/proj replicated for this callable
    attn.carries_collectives = False  # safe inside a pipeline stage
    return attn


# ---------------------------------------------------------------------
# ring-flash: sequence parallelism across chips, flash within each hop
# ---------------------------------------------------------------------


def _ring_flash_local(q, k, v, *, axis_name, num_devices, causal, scale):
    """Per-device body under shard_map: the full ring-flash composition.

    Local Q stays put; K/V blocks rotate around the ring
    (``ops/ring_attention.py``'s topology), but each hop's block pair
    is computed by the Pallas flash kernel instead of a materialized
    einsum — so the per-hop ``(T/N, T/N)`` scores live only in VMEM.
    Hops combine through their logsumexps in an online-softmax carry
    (plain jnp, so the whole thing reverse-differentiates: each hop's
    cotangents re-enter the kernel's custom VJP, including the lse
    term).

    Causal structure per hop: a block strictly left of the diagonal is
    plain full attention, the diagonal block is locally-causal (equal
    global offsets make local masking exact), and blocks right of the
    diagonal contribute nothing (lse = -inf sentinel → zero weight).
    """
    b, t_loc, h, d = q.shape
    my = jax.lax.axis_index(axis_name)
    flat = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t_loc, d)
    qf = flat(q)

    from multidisttorch_tpu.parallel.collectives import pvary

    m0 = pvary(jnp.full((b * h, t_loc), _NEG_INF, jnp.float32), axis_name)
    l0 = pvary(jnp.zeros((b * h, t_loc), jnp.float32), axis_name)
    acc0 = pvary(
        jnp.zeros((b * h, t_loc, d), jnp.float32), axis_name
    )
    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    def body(carry, step):
        kf, vf, m, l, acc = carry

        def full():
            return _flash_flat_lse(qf, kf, vf, scale, False)

        def diag():
            return _flash_flat_lse(qf, kf, vf, scale, True)

        def skip():
            return (
                jnp.zeros_like(qf),
                jnp.full((b * h, t_loc), _NEG_INF, jnp.float32),
            )

        if causal:
            src = (my - step) % num_devices
            mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            o_h, lse_h = jax.lax.switch(mode, [full, diag, skip])
        else:
            o_h, lse_h = full()

        m_new = jnp.maximum(m, lse_h)
        c = jnp.exp(m - m_new)
        w = jnp.exp(lse_h - m_new)
        l_new = l * c + w
        acc_new = acc * c[..., None] + w[..., None] * o_h.astype(jnp.float32)
        kf_next = jax.lax.ppermute(kf, axis_name, perm)
        vf_next = jax.lax.ppermute(vf, axis_name, perm)
        return (kf_next, vf_next, m_new, l_new, acc_new), None

    (_, _, _, l, acc), _ = jax.lax.scan(
        body, (flat(k), flat(v), m0, l0, acc0), jnp.arange(num_devices)
    )
    out = acc / jnp.where(l > 0, l, 1.0)[..., None]
    return (
        out.reshape(b, h, t_loc, d).transpose(0, 2, 1, 3).astype(q.dtype)
    )


@lru_cache(maxsize=None)
def _make_ring_flash_cached(mesh, causal: bool, head_axis=None):
    from jax.sharding import PartitionSpec as P

    from multidisttorch_tpu.parallel.mesh import DATA_AXIS

    num_devices = int(mesh.shape[DATA_AXIS])
    spec = P(None, DATA_AXIS, head_axis, None)

    def fn(q, k, v):
        scale = 1.0 / (q.shape[-1] ** 0.5)
        return compat_shard_map(
            partial(
                _ring_flash_local,
                axis_name=DATA_AXIS,
                num_devices=num_devices,
                causal=causal,
                scale=scale,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # pallas_call's out_shape carries no VMA annotation, so the
            # varying-axis checker can't type the per-hop kernel
            # results (same constraint as the fused ELBO loss under
            # shard_map — train/steps.py).
            check_vma=False,
        )(q, k, v)

    return jax.jit(fn)


def make_ring_flash_attention(trial, *, causal: bool = False,
                              shard_heads="auto"):
    """Sequence-parallel exact attention with flash-kernel hops.

    Same contract and sharding as
    :func:`ops.ring_attention.make_ring_attention` — ``(batch, seq,
    heads, head_dim)`` with ``seq`` sharded over the trial's data axis,
    and on a 2-D ``(data x model)`` mesh heads additionally sharded
    over the model axis (``shard_heads="auto"``) — but the per-hop
    block computation is the Pallas kernel, so no device ever
    materializes even a ``(T/N, T/N)`` score block in HBM. This is the
    composition the long-context design is built around: ICI ring for
    the cross-chip half, VMEM blocking for the within-chip half.
    Compiled functions are memoized per ``(mesh, causal, head_axis)``
    like :func:`make_ring_attention`; without Pallas the plain ring
    (HBM-block hops) is returned instead. The returned callable
    exposes ``.head_sharded``.
    """
    from multidisttorch_tpu.ops.ring_attention import (
        _resolve_head_axis,
        _wrap_head_check,
    )
    from multidisttorch_tpu.parallel.mesh import TrialMesh

    if not _HAVE_PALLAS:
        from multidisttorch_tpu.ops.ring_attention import make_ring_attention

        return make_ring_attention(trial, causal=causal,
                                   shard_heads=shard_heads)
    mesh = trial.mesh if isinstance(trial, TrialMesh) else trial
    head_axis = _resolve_head_axis(mesh, shard_heads)
    return _wrap_head_check(
        _make_ring_flash_cached(mesh, causal, head_axis), mesh, head_axis
    )
