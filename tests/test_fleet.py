"""Fleet observability plane (ISSUE 6, docs/OBSERVABILITY.md "Fleet"):
bus-level host/world identity, the cross-host shard merge with its
skew model, world/restart books, the fleet Perfetto trace, the
preflight verdict taxonomy, and the ``sweep_top --fleet`` console.

Everything here is plain files + fabricated streams — no device
runtime, no subprocess worlds (the live kill-one-of-3 drill that
exercises the same layer end-to-end is tests/test_elastic.py's
``multihost`` tier and the CI elastic job). The two exceptions are the
real-CPU preflight smokes, which spawn the probe's own bounded
subprocesses exactly as production does.
"""

import importlib.util
import json
import os
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------
# bus-level fleet identity (the satellite fix + its regression tests)
# --------------------------------------------------------------------


def test_bus_stamps_host_world_on_every_event(tmp_path):
    from multidisttorch_tpu.telemetry import events as E

    path = str(tmp_path / "events.jsonl")
    bus = E.Bus(path=path, host=3, world=1)
    bus.emit("epoch", trial_id=0, step=5)
    bus.emit("fault_injected", trial_id=-1, fault_kind="host_lost")
    bus.close()
    recs = E.read_events(path)
    assert [r["host"] for r in recs] == [3, 3]
    assert [r["world"] for r in recs] == [1, 1]


def test_untagged_single_host_stream_is_byte_stable(tmp_path):
    """The pre-fleet serialization contract, byte for byte: an untagged
    bus must never serialize host/world keys (or reorder the others) —
    a single-host trace written today is identical to one written
    before the fleet layer existed."""
    from multidisttorch_tpu.telemetry import events as E

    path = str(tmp_path / "events.jsonl")
    bus = E.Bus(path=path)
    ev = bus.emit("epoch", trial_id=1, step=2, loss=0.5)
    bus.close()
    line = open(path).read().splitlines()[0]
    expected = json.dumps(
        {
            "kind": "epoch",
            "ts": ev.ts,
            "trial_id": 1,
            "step": 2,
            "data": {"loss": 0.5},
        }
    )
    assert line == expected
    assert "host" not in line and "world" not in line


def test_configure_defaults_tags_from_supervisor_env(tmp_path, monkeypatch):
    from multidisttorch_tpu.telemetry import events as E

    monkeypatch.setenv("MDT_HOST_SLOT", "2")
    monkeypatch.setenv("MDT_WORLD_EPOCH", "1")
    bus = E.configure(path=None)
    try:
        assert bus.host == 2 and bus.world == 1
    finally:
        E.disable()
    # explicit wins over env; garbage env degrades to untagged
    bus = E.configure(path=None, host=7)
    try:
        assert bus.host == 7
    finally:
        E.disable()
    monkeypatch.setenv("MDT_HOST_SLOT", "not-a-slot")
    monkeypatch.delenv("MDT_WORLD_EPOCH")
    bus = E.configure(path=None)
    try:
        assert bus.host is None and bus.world is None
    finally:
        E.disable()


# --------------------------------------------------------------------
# fabricated fleet run dirs
# --------------------------------------------------------------------


def _ev(kind, ts, host=None, world=None, trial_id=None, attempt=None,
        step=None, **data):
    d = {"kind": kind, "ts": ts}
    if trial_id is not None:
        d["trial_id"] = trial_id
    if attempt is not None:
        d["attempt"] = attempt
    if step is not None:
        d["step"] = step
    if host is not None:
        d["host"] = host
    if world is not None:
        d["world"] = world
    if data:
        d["data"] = data
    return d


def _write_shard(run_dir, rel, events, torn_tail=False):
    path = os.path.join(run_dir, "telemetry", rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
        if torn_tail:
            f.write('{"kind": "epoch", "ts": 99.0, "tr')
    return path


def _attempt_pair(t0, host, world, trial_id, steps=10, status="completed"):
    return [
        _ev("attempt_start", t0, host=host, world=world,
            trial_id=trial_id, attempt=1),
        _ev("attempt_end", t0 + 1.0, host=host, world=world,
            trial_id=trial_id, attempt=1, status=status,
            summary={"steps": steps, "resumed_from_step": 0}),
    ]


def _fleet_run_dir(tmp_path, *, torn=False):
    """A 3-host, 2-world elastic run: host 1 dies after world 0, trial
    5 migrates host 1 -> host 0, the supervisor emits the restart-tax
    event, and world 1 restores + steps (the evidence the tax report
    joins)."""
    from multidisttorch_tpu.parallel import membership as m

    run_dir = str(tmp_path / "run")
    os.makedirs(m.membership_dir(run_dir))
    worlds_path = os.path.join(m.membership_dir(run_dir), m.WORLDS_NAME)
    with open(worlds_path, "w") as f:
        f.write(json.dumps({"epoch": 0, "hosts": [0, 1, 2], "lost": [],
                            "reason": "", "ts": 9.5}) + "\n")
        f.write(json.dumps({"epoch": 1, "hosts": [0, 2], "lost": [1],
                            "reason": "host_lost", "ts": 20.0}) + "\n")
    # mtime == newest record ts: a zero supervisor skew anchor, like a
    # live run where the fs stamps the append as it happens
    os.utime(worlds_path, (20.0, 20.0))

    # world 0: all three hosts work; trial 5 is host 1's
    w0 = []
    for h in range(3):
        evs = [_ev("sweep_start", 10.0 + h * 0.01, host=h, world=0,
                   configs=6)]
        tid = h  # trials 0..2 settle in world 0
        evs += _attempt_pair(11.0 + h * 0.01, h, 0, tid)
        if h == 1:
            evs.append(_ev("epoch", 12.0, host=1, world=0, trial_id=5,
                           step=8))
        _write_shard(run_dir, f"w0/events.p{h}.jsonl", evs,
                     torn_tail=torn and h == 1)
        w0.append(evs)

    # supervisor stream: untagged; restart_tax marks world 1's launch
    sup = [
        _ev("world_start", 10.0, epoch=0, hosts=[0, 1, 2]),
        _ev("host_lost", 19.5, slot=1, stale_s=3.2, world_epoch=0),
        _ev("world_end", 19.6, epoch=0, outcome="host_lost"),
        _ev("restart_tax", 20.0, world_epoch=1, trigger="host_lost",
            lost=[1], detect_s=3.2, drain_s=0.3, relaunch_s=0.5),
        _ev("world_start", 20.0, epoch=1, hosts=[0, 2]),
    ]
    _write_shard(run_dir, "sup/events.jsonl", sup)

    # world 1: survivors; trial 5 now owned by host 0 (migrated),
    # restores from checkpoint then steps
    w1_h0 = [
        _ev("trial_migrated", 20.5, host=0, world=1, trial_id=5,
            from_host=1, to_host=0),
        _ev("ckpt_restore", 22.0, host=0, world=1, trial_id=5, step=8),
        _ev("epoch", 25.0, host=0, world=1, trial_id=5, step=16),
    ]
    w1_h0 += _attempt_pair(26.0, 0, 1, 5, steps=20,
                           status="completed")
    w1_h2 = _attempt_pair(21.0, 2, 1, 4)
    _write_shard(run_dir, "w1/events.p0.jsonl", w1_h0)
    _write_shard(run_dir, "w1/events.p1.jsonl", w1_h2)
    return run_dir


# --------------------------------------------------------------------
# shard discovery + merge semantics
# --------------------------------------------------------------------


def test_merge_is_deterministic_and_complete(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    run_dir = _fleet_run_dir(tmp_path)
    a = fleet.merge_fleet(run_dir)
    b = fleet.merge_fleet(run_dir)
    assert json.dumps(a["events"]) == json.dumps(b["events"])
    ts = [e["ts"] for e in a["events"]]
    assert ts == sorted(ts)
    assert a["expected_hosts"] == [0, 1, 2]
    assert a["hosts_seen"] == [0, 1, 2]
    assert a["all_hosts_traced"] is True
    assert a["torn_lines_total"] == 0
    n_in = sum(s["events"] for s in a["shards"])
    assert len(a["events"]) == n_in


def test_merge_counts_torn_tail_per_shard(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    run_dir = _fleet_run_dir(tmp_path, torn=True)
    merged = fleet.merge_fleet(run_dir)
    assert merged["torn_lines_total"] == 1
    torn_shards = [s for s in merged["shards"] if s["torn_lines"]]
    assert len(torn_shards) == 1
    assert "w0" in torn_shards[0]["shard"]
    # the decodable prefix of the torn shard still merged
    assert any(
        e.get("kind") == "epoch" and e.get("host") == 1
        for e in merged["events"]
    )


def test_merge_world_falls_back_to_shard_directory(tmp_path):
    """A writer that lost its world tag (pre-fleet stream in a w{k}
    dir) is still attributed to the world its shard lives under."""
    from multidisttorch_tpu.telemetry import fleet

    run_dir = str(tmp_path / "run")
    _write_shard(run_dir, "w2/events.jsonl",
                 [_ev("epoch", 1.0, host=0, trial_id=0, step=1)])
    merged = fleet.merge_fleet(run_dir)
    assert merged["events"][0]["world"] == 2


def test_merge_excludes_its_own_previous_output(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    run_dir = _fleet_run_dir(tmp_path)
    first = fleet.export_fleet(run_dir)
    again = fleet.merge_fleet(run_dir)
    assert len(again["events"]) == first["summary"]["events"]
    assert not any("fleet" in s["shard"] for s in again["shards"])


def test_missing_host_shard_fails_the_traced_gate(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    run_dir = _fleet_run_dir(tmp_path)
    # host 1 wrote only the world-0 shard (it died in the shrink):
    # losing that file means the merged timeline no longer covers it
    os.remove(os.path.join(run_dir, "telemetry", "w0",
                           "events.p1.jsonl"))
    merged = fleet.merge_fleet(run_dir)
    assert merged["all_hosts_traced"] is False
    assert 1 not in merged["hosts_seen"]


# --------------------------------------------------------------------
# the skew model
# --------------------------------------------------------------------


def test_skew_from_anchors_clamps_noise_and_keeps_real_offsets():
    from multidisttorch_tpu.telemetry import fleet

    applied = fleet.skew_from_anchors(
        {0: 0.1, 1: -0.2, 2: 5.0, 3: -1.5, "sup": 0.01},
        min_skew_s=0.25,
    )
    assert applied == {0: 0.0, 1: 0.0, 2: 5.0, 3: -1.5, "sup": 0.0}
    # pure + deterministic: same anchors, same corrections
    assert applied == fleet.skew_from_anchors(
        {0: 0.1, 1: -0.2, 2: 5.0, 3: -1.5, "sup": 0.01},
        min_skew_s=0.25,
    )


def test_merge_applies_lease_anchored_skew_correction(tmp_path):
    """Host 0's wall clock runs 5 s behind the shared fs clock (its
    lease's newest ts is 5 s older than the file's mtime): its events
    must shift forward by 5 s onto the fleet clock, keeping the raw
    stamp in ts_raw; the in-sync host is untouched."""
    from multidisttorch_tpu.parallel import membership as m
    from multidisttorch_tpu.telemetry import fleet

    run_dir = str(tmp_path / "run")
    os.makedirs(m.membership_dir(run_dir))
    now = time.time()
    for slot, skew in ((0, -5.0), (1, 0.0)):
        path = m.lease_path(run_dir, slot)
        with open(path, "w") as f:
            for i in range(3):
                f.write(json.dumps({
                    "slot": slot, "ts": now + skew + i * 0.25,
                    "mono": 100.0 + i * 0.25, "status": "alive",
                }) + "\n")
        newest = now + skew + 2 * 0.25
        os.utime(path, (newest - skew, newest - skew))
    _write_shard(run_dir, "w0/events.p0.jsonl",
                 [_ev("epoch", now - 5.0, host=0, trial_id=0, step=1)])
    _write_shard(run_dir, "w0/events.p1.jsonl",
                 [_ev("epoch", now, host=1, trial_id=1, step=1)])

    merged = fleet.merge_fleet(run_dir)
    by_host = {e["host"]: e for e in merged["events"]}
    assert by_host[0]["ts"] == pytest.approx(now, abs=0.05)
    assert by_host[0]["ts_raw"] == pytest.approx(now - 5.0, abs=1e-9)
    assert "ts_raw" not in by_host[1]
    assert merged["skew"]["0"]["applied_offset_s"] == pytest.approx(
        5.0, abs=0.05
    )
    assert merged["skew"]["1"]["applied_offset_s"] == 0.0


def test_wall_clock_step_reported_not_folded():
    from multidisttorch_tpu.telemetry.fleet import _wall_step_diagnostics

    steady = [
        {"ts": 100.0 + i, "mono": 50.0 + i} for i in range(5)
    ]
    assert _wall_step_diagnostics(steady)["wall_clock_steps"] == 0
    jumped = list(steady)
    # NTP yanks the wall clock 30 s forward between beats 4 and 5
    jumped.append({"ts": 135.0, "mono": 55.0})
    diag = _wall_step_diagnostics(jumped)
    assert diag["wall_clock_steps"] == 1
    assert diag["max_wall_mono_drift_s"] == pytest.approx(30.0, abs=0.1)


# --------------------------------------------------------------------
# lineage, per-world books, restart tax
# --------------------------------------------------------------------


def test_trial_lineage_tracks_migration_across_worlds(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    merged = fleet.merge_fleet(_fleet_run_dir(tmp_path))
    lineage = fleet.trial_lineage(merged["events"])
    chain = lineage[5]
    assert [(c["world"], c["host"]) for c in chain] == [(0, 1), (1, 0)]
    assert chain[0]["last_ts"] <= chain[1]["first_ts"]


def test_per_world_books_fold_goodput_and_dedup_echoes(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    merged = fleet.merge_fleet(_fleet_run_dir(tmp_path))
    # a multi-controller echo of an already-counted attempt_end
    events = merged["events"] + [
        _ev("attempt_end", 26.9, host=2, world=1, trial_id=5, attempt=1,
            status="completed",
            summary={"steps": 20, "resumed_from_step": 0}),
    ]
    books = fleet.per_world_books(events)
    assert books["0"]["attempt_ends"] == 3
    assert books["1"]["attempt_ends"] == 2  # echo deduplicated
    assert books["1"]["useful_steps"] == 30
    assert books["0"]["goodput"] == 1.0
    assert books["1"]["hosts"] == [0, 2]


def test_restart_tax_joins_live_phases_with_worker_evidence(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    merged = fleet.merge_fleet(_fleet_run_dir(tmp_path))
    (tax,) = fleet.restart_tax_report(merged["events"])
    assert tax["world_epoch"] == 1
    assert tax["trigger"] == "host_lost" and tax["lost"] == [1]
    # live phases straight off the supervisor's event
    assert tax["detect_s"] == 3.2
    assert tax["drain_s"] == 0.3
    assert tax["relaunch_s"] == 0.5
    # joined phases: launch at ts=20, first restore at 22, first epoch
    # completion at 25
    assert tax["restore_s"] == pytest.approx(2.0)
    assert tax["first_useful_step_s"] == pytest.approx(5.0)
    assert tax["total_s"] == pytest.approx(3.2 + 0.3 + 0.5 + 2.0)


# --------------------------------------------------------------------
# the fleet trace
# --------------------------------------------------------------------


def test_fleet_trace_one_process_per_host_with_world_spans(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    merged = fleet.merge_fleet(_fleet_run_dir(tmp_path))
    trace = json.loads(json.dumps(fleet.build_fleet_trace(merged)))
    te = trace["traceEvents"]
    names = {
        e["pid"]: e["args"]["name"]
        for e in te
        if e.get("name") == "process_name"
    }
    assert names[1] == "supervisor"
    assert {names[fleet._host_pid(h)] for h in (0, 1, 2)} == {
        "host 0", "host 1", "host 2",
    }
    # world-epoch SPANS (ph X) on the supervisor track; the sup
    # stream's world_start/world_end instants share the category
    worlds = [e for e in te
              if e.get("cat") == "world" and e.get("ph") == "X"]
    assert [w["name"].split()[1] for w in worlds] == ["0", "1"]
    assert all(w["pid"] == 1 for w in worlds)
    assert worlds[0]["ts"] >= 0  # explicit t0 covers pre-event spans
    assert worlds[0]["dur"] > 0
    # the open final world runs to the last merged event
    assert worlds[1]["ts"] + worlds[1]["dur"] >= max(
        e["ts"] for e in te if "ts" in e
    ) - 1.0
    # non-negative, monotonically ordered timeline
    ts = [e["ts"] for e in te if "ts" in e]
    assert ts == sorted(ts) and ts[0] >= 0


def test_fleet_trace_draws_migration_flow_arrows(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    merged = fleet.merge_fleet(_fleet_run_dir(tmp_path))
    te = fleet.build_fleet_trace(merged)["traceEvents"]
    flows = [e for e in te if e.get("cat") == "migration"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    start = next(e for e in flows if e["ph"] == "s")
    finish = next(e for e in flows if e["ph"] == "f")
    assert start["pid"] == fleet._host_pid(1)  # from host 1...
    assert finish["pid"] == fleet._host_pid(0)  # ...to host 0
    assert start["id"] == finish["id"]
    assert start["ts"] <= finish["ts"]


# --------------------------------------------------------------------
# summary + export
# --------------------------------------------------------------------


def test_fleet_summary_books_and_gates(tmp_path):
    from multidisttorch_tpu.telemetry import fleet

    run_dir = _fleet_run_dir(tmp_path)
    s = fleet.fleet_summary(run_dir, now=lambda: 30.0)
    assert s["protocol"] == "fleet_v1"
    assert s["all_hosts_traced"] is True
    assert s["world_transitions"] == 1
    assert s["world_shrunk_traced"] is False  # fabricated sup stream
    assert set(s["hosts"]) == {"0", "1", "2"}
    assert s["hosts"]["1"]["worlds"] == [0]
    assert s["goodput"] == 1.0
    assert s["restart_tax"][0]["world_epoch"] == 1
    assert s["lineage"]["5"][-1]["host"] == 0
    assert s["migrations"][0]["trial_id"] == 5
    assert s["faults"] == {
        "fired": 0, "traced": 0, "all_faults_traced": True,
    }


def test_export_fleet_writes_all_three_artifacts(tmp_path):
    from multidisttorch_tpu.telemetry import fleet
    from multidisttorch_tpu.telemetry.events import read_events

    run_dir = _fleet_run_dir(tmp_path)
    out = fleet.export_fleet(run_dir)
    paths = out["paths"]
    merged_events = read_events(paths["events"])
    assert len(merged_events) == out["summary"]["events"]
    trace = json.load(open(paths["trace"]))
    assert trace["traceEvents"]
    summary = json.load(open(paths["summary"]))
    assert summary["all_hosts_traced"] is True
    assert summary["restart_tax"]


# --------------------------------------------------------------------
# preflight classification (fake backends — pure classification logic)
# --------------------------------------------------------------------

_OK_PROBE = {"ok": True, "platform": "cpu", "device_kind": "cpu",
             "n_devices": 2, "elapsed_s": 0.1}
_TIMEOUT_PROBE = {"ok": False, "timeout": True, "elapsed_s": 5.0,
                  "error": "backend init still blocked after 5s",
                  "stderr_tail": ""}
_ABSENT_PROBE = {"ok": False, "timeout": False, "elapsed_s": 0.2,
                 "error": "backend init failed (rc=1)",
                 "stderr_tail": "RuntimeError: Unknown backend axon9"}
_BROKEN_PROBE = {"ok": False, "timeout": False, "elapsed_s": 0.2,
                 "error": "backend init failed (rc=1)",
                 "stderr_tail": "Aborted (core dumped)"}
# jax's generic wrapper around a PRESENT backend that crashed fast —
# must NOT classify as absent (the wrapper prefix alone is ambiguous;
# absence says "... is not in the list of known backends")
_CRASHED_PROBE = {"ok": False, "timeout": False, "elapsed_s": 0.3,
                 "error": "backend init failed (rc=1)",
                 "stderr_tail": "RuntimeError: Unable to initialize "
                 "backend 'tpu': UNAVAILABLE: connection failed"}
_OK_CANARY = {"ok": True, "canary_ok": True, "canary_value": 512.0,
              "n_devices": 2, "platform": "cpu", "device_kind": "cpu",
              "memory_stats": None, "elapsed_s": 0.2}
_BAD_CANARY = {"ok": False, "timeout": False, "elapsed_s": 0.2,
               "error": "canary failed (rc=1)", "stderr_tail": "boom"}


def _triage(holders=(), plugin_procs=(), listeners=(), so=False):
    return {
        "device_nodes": "absent",
        "accel_node_holders": list(holders),
        "pjrt_plugin_processes": list(plugin_procs),
        "loopback_listeners": list(listeners),
        "axon": {"plugin_so_present": so, "pool_ips": "", "tpu_gen": "",
                 "remote_compile": ""},
    }


def _fake_preflight(monkeypatch, probes, canary=_OK_CANARY,
                    triage=None):
    """Drive run_preflight against a scripted backend: ``probes`` is
    consumed one init probe per call."""
    from multidisttorch_tpu.utils import preflight as pf

    seq = list(probes)
    monkeypatch.setattr(pf, "probe_init",
                        lambda t, platform=None: seq.pop(0))
    monkeypatch.setattr(pf, "probe_canary",
                        lambda t, platform=None: dict(canary))
    monkeypatch.setattr(pf, "plugin_scan",
                        lambda: triage or _triage())
    return pf


@pytest.mark.parametrize(
    "probes,canary,triage,verdict,usable",
    [
        ([_OK_PROBE], _OK_CANARY, None, "healthy", True),
        ([_TIMEOUT_PROBE, _OK_PROBE], _OK_CANARY, None,
         "transient_recovered", True),
        ([_TIMEOUT_PROBE, _TIMEOUT_PROBE], _OK_CANARY,
         _triage(holders=[{"pid": 1, "cmdline": "leaker"}], so=True),
         "wedged_leaked_plugin", False),
        ([_TIMEOUT_PROBE, _TIMEOUT_PROBE], _OK_CANARY,
         _triage(so=True, listeners=()),
         "wedged_unreachable", False),
        ([_TIMEOUT_PROBE, _TIMEOUT_PROBE], _OK_CANARY,
         _triage(so=True, listeners=(8476,)),
         "wedged_init_timeout", False),
        ([_ABSENT_PROBE], _OK_CANARY, None, "backend_absent", False),
        ([_BROKEN_PROBE, _BROKEN_PROBE], _OK_CANARY, None,
         "init_failed", False),
        ([_CRASHED_PROBE, _CRASHED_PROBE], _OK_CANARY, None,
         "init_failed", False),
        ([_CRASHED_PROBE, _OK_PROBE], _OK_CANARY, None,
         "transient_recovered", True),
        ([_OK_PROBE], _BAD_CANARY, None, "canary_failed", False),
    ],
    ids=["healthy", "transient", "leaked", "unreachable",
         "init_timeout", "absent", "init_failed",
         "crashed_not_absent", "crashed_then_recovered",
         "canary_failed"],
)
def test_preflight_verdict_taxonomy(monkeypatch, probes, canary,
                                    triage, verdict, usable):
    pf = _fake_preflight(monkeypatch, probes, canary=canary,
                         triage=triage)
    report = pf.run_preflight(retry_delay_s=0)
    assert report["verdict"] == verdict
    assert report["usable"] is usable
    assert report["verdict_reason"]
    assert report["verdict"] in pf.VERDICTS
    assert (verdict in pf.USABLE_VERDICTS) == usable


def test_preflight_healthy_skips_the_proc_scan(monkeypatch):
    """The /proc evidence walk is failure-path only: a healthy probe
    (the supervisor's every-world case) must not pay it."""
    from multidisttorch_tpu.utils import preflight as pf

    monkeypatch.setattr(pf, "probe_init",
                        lambda t, platform=None: dict(_OK_PROBE))
    monkeypatch.setattr(pf, "probe_canary",
                        lambda t, platform=None: dict(_OK_CANARY))

    def boom():
        raise AssertionError("plugin_scan must not run on a healthy probe")

    monkeypatch.setattr(pf, "plugin_scan", boom)
    report = pf.run_preflight(retry_delay_s=0)
    assert report["verdict"] == "healthy"
    assert report["triage"] is None


def test_preflight_absent_platform_skips_the_retry_sleep(monkeypatch):
    """An absent platform fails fast and deterministically — the probe
    must classify it WITHOUT the 30 s wedge-retry pause (the CI smoke
    asserts the classified-not-hanging contract end to end)."""
    pf = _fake_preflight(monkeypatch, [_ABSENT_PROBE])
    t0 = time.perf_counter()
    report = pf.run_preflight(retry_delay_s=30)
    assert time.perf_counter() - t0 < 5.0
    assert report["verdict"] == "backend_absent"
    assert all(s["stage"] != "init_retry" for s in report["stages"])


def test_preflight_emits_classified_verdict_events(monkeypatch, tmp_path):
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.telemetry.events import read_events

    pf = _fake_preflight(monkeypatch, [_OK_PROBE])
    with telemetry.telemetry_run(str(tmp_path)):
        pf.run_preflight(retry_delay_s=0)
    recs = read_events(str(tmp_path / "events.jsonl"))
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "preflight_start"
    assert "preflight_stage" in kinds
    verdict = next(r for r in recs if r["kind"] == "preflight_verdict")
    assert verdict["data"]["verdict"] == "healthy"
    assert verdict["data"]["usable"] is True


def test_preflight_real_cpu_smoke():
    """The out-of-process probe against the real CPU backend: healthy,
    canary executes, bounded wall time."""
    from multidisttorch_tpu.utils import preflight as pf

    report = pf.run_preflight(
        "cpu", init_timeout_s=120, canary_timeout_s=120,
        retry_delay_s=0, scan=False,
    )
    assert report["verdict"] == "healthy" and report["usable"]
    assert report["device"]["platform"] == "cpu"
    canary = next(s for s in report["stages"] if s["stage"] == "canary")
    assert canary["ok"] and canary["canary_value"] == 512.0


def test_supervisor_preflight_refuses_bad_backend(monkeypatch, tmp_path):
    """A non-usable verdict aborts the launch with the classified
    reason instead of wedging N workers into the boot grace."""
    from multidisttorch_tpu.utils import preflight as pf

    sweep_supervisor = _load_tool("sweep_supervisor")
    monkeypatch.setattr(
        pf, "run_preflight",
        lambda *a, **k: {
            "verdict": pf.WEDGED_INIT_TIMEOUT,
            "verdict_reason": "init blocked after 5s",
            "usable": False,
        },
    )
    sup = sweep_supervisor.ElasticSupervisor(
        ["true"], str(tmp_path), 2, preflight=True,
    )
    with pytest.raises(RuntimeError, match="wedged_init_timeout"):
        sup._run_preflight()
    assert sup.preflight_report["usable"] is False


def test_preflight_cli_classifies_cpu_and_writes_report(tmp_path, capsys):
    preflight_cli = _load_tool("preflight")
    out_path = str(tmp_path / "preflight.json")
    rc = preflight_cli.main([
        "--platform", "cpu", "--no-scan", "--retry-delay", "0",
        "--json", "--out", out_path,
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["verdict"] == "healthy"
    assert json.load(open(out_path))["verdict"] == "healthy"


# --------------------------------------------------------------------
# the fleet console
# --------------------------------------------------------------------


def test_host_health_verdicts():
    from multidisttorch_tpu.telemetry.console import host_health

    assert host_health("alive", 0.5) == "up"
    assert host_health("alive", 10.0) == "STALE"
    assert host_health("left", 100.0) == "left"
    assert host_health("draining", 0.1) == "drain"
    assert host_health("alive", None) == "?"


def test_sweep_top_fleet_render(tmp_path, capsys):
    sweep_top = _load_tool("sweep_top")
    run_dir = _fleet_run_dir(tmp_path)
    assert sweep_top.main([run_dir, "--fleet"]) == 0
    out = capsys.readouterr().out
    assert "hosts" in out and "worlds" in out
    assert "restart tax" in out
    assert "trial 5: w0@h1 -> w1@h0" in out
    # world history rows with the shrink reason
    assert "host_lost" in out


def test_sweep_top_fleet_json_snapshot(tmp_path, capsys):
    sweep_top = _load_tool("sweep_top")
    run_dir = _fleet_run_dir(tmp_path)
    assert sweep_top.main([run_dir, "--fleet", "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["all_hosts_traced"] is True
    assert snap["restart_tax"][0]["trigger"] == "host_lost"
    assert "5" in snap["lineage"]
    assert "trials" in snap and snap["trials"]


def test_sweep_top_fleet_rejects_non_directory(tmp_path, capsys):
    sweep_top = _load_tool("sweep_top")
    assert sweep_top.main([str(tmp_path / "nope"), "--fleet"]) == 1
