"""Anomaly layer (ISSUE 4): deterministic straggler detection on
synthetic step-time series (seeded, no sleeps), loss plateau /
divergence-precursor watches, anomaly event ordering across a retry
boundary, and profiler-capture rate limiting (never more than N
windows per series)."""

import os

import jax
import jax.numpy as jnp
import pytest

from multidisttorch_tpu import telemetry
from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.faults.plan import CRASH, SLOW, FaultPlan, FaultSpec
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.hpo.supervision import RetryPolicy
from multidisttorch_tpu.telemetry import anomaly as tele_anomaly
from multidisttorch_tpu.telemetry.anomaly import (
    AnomalyConfig,
    AnomalyMonitor,
    RollingRobustZ,
)
from multidisttorch_tpu.utils import profiling


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    yield
    telemetry.disable()


# -- the detector itself (pure, synthetic, no sleeps) -------------------


def test_rolling_robust_z_warmup_and_outlier():
    det = RollingRobustZ(window=16, min_samples=8)
    for _ in range(8):
        assert det.observe(0.010) is None  # warm-up: no verdict
    z, med = det.observe(0.010)
    assert med == pytest.approx(0.010)
    assert abs(z) < 1.0
    z, med = det.observe(0.200)  # 20x the median
    assert z > 100  # MAD floored at 5% of median -> z = 0.19/0.0005
    # The outlier is admitted AFTER scoring: the median barely moves.
    _z, med = det.observe(0.010)
    assert med == pytest.approx(0.010)


def test_straggler_detection_deterministic_series():
    """Seeded synthetic series: jittery-but-sane steps never flag;
    a single 10x step flags exactly once."""
    import numpy as np

    rng = np.random.default_rng(7)
    telemetry.configure(None)  # in-memory bus + registry + monitor
    tele_anomaly.configure(
        AnomalyConfig(min_samples=8, z_threshold=6.0, min_ratio=2.0)
    )
    mon = telemetry.get_monitor()
    base = 0.010
    fired = []
    for i in range(50):
        dt = base * float(rng.uniform(0.9, 1.1))
        rec = mon.observe_step("trial-0", dt, trial_id=0, step=i)
        if rec is not None:
            fired.append(rec)
    assert fired == []  # sane jitter never flags
    rec = mon.observe_step("trial-0", 10 * base, trial_id=0, step=50)
    assert rec is not None
    assert rec["ratio"] >= 9.0
    kinds = [e.kind for e in telemetry.get_bus().recent()]
    assert kinds.count(tele_anomaly.STRAGGLER) == 1
    reg = telemetry.get_registry()
    assert reg.counter("anomalies_total", kind="straggler").value == 1


def test_straggler_cooldown_suppresses_floods():
    telemetry.configure(None)
    tele_anomaly.configure(
        AnomalyConfig(min_samples=4, z_threshold=4.0, min_ratio=2.0,
                      cooldown_marks=8)
    )
    mon = telemetry.get_monitor()
    for i in range(6):
        mon.observe_step("k", 0.01, step=i)
    flagged = sum(
        mon.observe_step("k", 0.5, step=10 + i) is not None
        for i in range(6)
    )
    assert flagged == 1  # one slow PHASE = one anomaly, not six


def test_loss_plateau_and_divergence_precursor():
    telemetry.configure(None)
    tele_anomaly.configure(
        AnomalyConfig(plateau_epochs=3, plateau_rel_eps=1e-3,
                      diverge_ratio=2.0, diverge_epochs=3)
    )
    mon = telemetry.get_monitor()
    # Healthy descent: nothing fires.
    for e, loss in enumerate([100.0, 90.0, 80.0, 70.0], 1):
        assert mon.observe_loss(0, epoch=e, train_loss=loss) is None
    # Flat-lining for plateau_epochs: plateau, exactly once.
    assert mon.observe_loss(0, epoch=5, train_loss=70.0) is None
    assert mon.observe_loss(0, epoch=6, train_loss=70.0) is None
    assert mon.observe_loss(0, epoch=7, train_loss=70.0) == (
        tele_anomaly.LOSS_PLATEAU
    )
    assert mon.observe_loss(0, epoch=8, train_loss=70.0) is None
    # Blow past 2x best while still finite: precursor, exactly once.
    assert mon.observe_loss(0, epoch=9, train_loss=200.0) == (
        tele_anomaly.DIVERGENCE_PRECURSOR
    )
    assert mon.observe_loss(0, epoch=10, train_loss=400.0) is None
    kinds = [e.kind for e in telemetry.get_bus().recent()]
    assert kinds.count(tele_anomaly.LOSS_PLATEAU) == 1
    assert kinds.count(tele_anomaly.DIVERGENCE_PRECURSOR) == 1
    # Non-finite losses are the guards' business, not a precursor.
    assert mon.observe_loss(1, epoch=1, train_loss=float("nan")) is None


# -- profiler capture: bounded and rate-limited ------------------------


class _FakeWindow:
    instances = []

    def __init__(self, log_dir, steps):
        self.log_dir = log_dir
        self.remaining = steps
        self.active = True
        _FakeWindow.instances.append(self)

    def tick(self):
        if self.active:
            self.remaining -= 1
            if self.remaining <= 0:
                self.stop()

    def stop(self):
        self.active = False


def _fake_factory(log_dir, *, steps):
    return _FakeWindow(log_dir, steps)


def test_capture_rate_limited_per_key(tmp_path):
    """Never more than max_captures_per_key windows per series, no
    matter how many anomalies fire."""
    _FakeWindow.instances = []
    telemetry.configure(None)
    tele_anomaly.configure(
        AnomalyConfig(
            min_samples=4, z_threshold=4.0, min_ratio=2.0,
            cooldown_marks=0, capture_dir=str(tmp_path),
            capture_steps=2, max_captures_per_key=2,
            capture_cooldown_s=0.0,
        ),
        window_factory=_fake_factory,
    )
    mon = telemetry.get_monitor()
    for i in range(8):
        mon.observe_step("trial-0", 0.01, step=i)
    anomalies = 0
    for i in range(20):
        # Slow steps interleaved with fast ones so the window (tick'd
        # by every observe) closes between anomalies.
        if mon.observe_step("trial-0", 0.5, step=100 + i) is not None:
            anomalies += 1
        for j in range(4):
            mon.observe_step("trial-0", 0.01, step=200 + 10 * i + j)
    assert anomalies > 2  # plenty of anomalies...
    assert mon.captures_started("trial-0") == 2  # ...capped captures
    assert len(_FakeWindow.instances) == 2
    # Every opened window was bounded and closed itself.
    assert all(not w.active for w in _FakeWindow.instances)


def test_single_active_window_process_wide(tmp_path):
    _FakeWindow.instances = []
    telemetry.configure(None)
    tele_anomaly.configure(
        AnomalyConfig(
            min_samples=4, z_threshold=4.0, min_ratio=2.0,
            cooldown_marks=0, capture_dir=str(tmp_path),
            capture_steps=1000, max_captures_per_key=5,
            capture_cooldown_s=0.0,
        ),
        window_factory=_fake_factory,
    )
    mon = telemetry.get_monitor()
    for i in range(8):
        mon.observe_step("a", 0.01, step=i)
        mon.observe_step("b", 0.01, step=i)
    assert mon.observe_step("a", 0.5) is not None  # opens a window
    rec = mon.observe_step("b", 0.5)  # anomaly fires, but NO new window
    assert rec is not None and "capture" not in rec
    assert len(_FakeWindow.instances) == 1


def test_profile_window_real_capture(tmp_path):
    """The real jax.profiler window on CPU: starts, ticks, closes after
    N steps, leaves trace files; a second concurrent start degrades
    gracefully."""
    d = str(tmp_path / "win")
    w = profiling.profile_window(d, steps=3)
    assert w.active, w.error
    w2 = profiling.profile_window(str(tmp_path / "win2"), steps=3)
    assert not w2.active and "active" in w2.error
    f = jax.jit(lambda x: x * 2)
    x = jnp.ones((8,))
    for _ in range(3):
        jax.block_until_ready(f(x))
        w.tick()
    assert not w.active  # self-closed after 3 ticks
    found = [fn for _r, _d, files in os.walk(d) for fn in files]
    assert found, "profiler window must leave a trace on disk"


# -- ordering across a retry boundary (driver integration) --------------


def test_anomaly_ordering_across_retry(tmp_path):
    """A SLOW fault flags a straggler DURING attempt 1; the event lands
    between that attempt's start and its retrying end, and the stream
    stays monotone across the crash/retry boundary."""
    tdir = str(tmp_path / "tele")
    cfgs = [
        TrialConfig(trial_id=i, epochs=3, batch_size=16, hidden_dim=16,
                    latent_dim=4, seed=i, log_interval=10_000)
        for i in range(2)
    ]
    data = synthetic_mnist(128, seed=0)  # 8 steps/epoch
    plan = FaultPlan(specs=(
        FaultSpec(SLOW, 0, step=12, delay_s=0.25),
        FaultSpec(CRASH, 0, step=18),
    ))
    with telemetry.telemetry_run(tdir):
        tele_anomaly.configure(
            AnomalyConfig(min_samples=4, z_threshold=4.0, min_ratio=3.0)
        )
        results = run_hpo(
            cfgs, data, None, num_groups=2,
            out_dir=str(tmp_path / "out"),
            save_images=False, verbose=False,
            resilient=True,
            retry=RetryPolicy(max_retries=2, backoff_base_s=0.01),
            fault_plan=plan,
        )
    assert all(
        r.status in ("completed", "resumed_complete") for r in results
    )
    events = telemetry.read_events(os.path.join(tdir, "events.jsonl"))
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)  # monotone across the retry boundary
    seq = [
        (e["kind"], (e.get("data") or {}).get("status"))
        for e in events
        if e.get("trial_id") == 0
        and e["kind"] in ("attempt_start", "attempt_end",
                          tele_anomaly.STRAGGLER)
    ]
    kinds = [k for k, _ in seq]
    assert tele_anomaly.STRAGGLER in kinds
    first_straggler = kinds.index(tele_anomaly.STRAGGLER)
    # Straggler fired inside attempt 1: after its start, before the
    # retrying end; and the completed end comes after everything.
    assert first_straggler > kinds.index("attempt_start")
    assert first_straggler < seq.index(("attempt_end", "retrying"))
    assert seq.index(("attempt_end", "retrying")) < seq.index(
        ("attempt_end", "completed")
    )
