"""Generate the committed tiny MNIST-format IDX fixture pair.

An INDEPENDENT writer for `tests/fixtures/mnist/`: the bytes are
assembled here with bare ``struct.pack`` big-endian arithmetic — no
import of ``multidisttorch_tpu.data.datasets`` — so the fixture cannot
inherit a bug from the parser it exists to test (a writer built as the
parser's inverse would round-trip its own mistakes invisibly).

Layout per Yann LeCun's IDX spec:
  images: magic 0x00000803 (2 zero bytes, dtype 0x08 = ubyte, ndim 3),
          dims (N, 28, 28) as big-endian uint32, then N*28*28 raw bytes
  labels: magic 0x00000801, dim (N,), then N raw bytes

Content is a fixed formula (pixel = (7i + 3r + 5c) mod 256,
label = i mod 10) so the loader test can recompute expected values
from scratch instead of trusting any intermediate array.

Run from the repo root to (re)generate:
    python tests/fixtures/gen_mnist_idx.py
"""

from __future__ import annotations

import gzip
import os
import struct

N, H, W = 64, 28, 28
OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mnist")


def pixel(i: int, r: int, c: int) -> int:
    return (7 * i + 3 * r + 5 * c) % 256


def label(i: int) -> int:
    return i % 10


def image_bytes() -> bytes:
    header = struct.pack(">HBB", 0, 0x08, 3) + struct.pack(">III", N, H, W)
    body = bytes(
        pixel(i, r, c) for i in range(N) for r in range(H) for c in range(W)
    )
    return header + body


def label_bytes() -> bytes:
    header = struct.pack(">HBB", 0, 0x08, 1) + struct.pack(">I", N)
    return header + bytes(label(i) for i in range(N))


def main() -> None:
    os.makedirs(OUT_DIR, exist_ok=True)
    for name, payload in (
        ("train-images-idx3-ubyte.gz", image_bytes()),
        ("train-labels-idx1-ubyte.gz", label_bytes()),
    ):
        path = os.path.join(OUT_DIR, name)
        # mtime=0 keeps the gzip output byte-stable across regenerations
        with open(path, "wb") as raw:
            with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as f:
                f.write(payload)
        print(f"wrote {path} ({os.path.getsize(path)} bytes)")


if __name__ == "__main__":
    main()
