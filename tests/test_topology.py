"""Elastic shard topology invariants (ISSUE 17, docs/SERVICE.md
"Shard topology"): the extendible-hashing routing trie, the
epoch-versioned topology log (first-writer-wins appends, strict epoch
increase, torn-tail replay), the split/merge event protocol (pending
splits route nowhere until commit; aborted child ids are burned), the
exactly-one-owner property under ANY split/merge sequence, the
client's bounded wrong-shard retry, and the dynamic-topology loadgen
scenario zoo."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from multidisttorch_tpu.service import fabric, queue as squeue
from multidisttorch_tpu.service import topology as stopo

pytestmark = pytest.mark.fabric


# -- identity + routing ----------------------------------------------


def test_identity_topology_matches_static_routing():
    """An empty log folds to the identity topology: routing is
    byte-identical to the static CRC ``shard_of`` — a PR 12-era fabric
    directory keeps working unchanged."""
    for n in (1, 2, 3, 8):
        topo = stopo.Topology(n)
        assert topo.epoch == 0
        assert topo.live_shards() == list(range(n))
        for i in range(64):
            t = f"tenant-{i}"
            assert topo.route(t) == fabric.shard_of(t, n)


def test_load_topology_missing_log_is_identity(tmp_path):
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 3)
    topo = stopo.load_topology(d)
    assert topo.epoch == 0 and topo.live_shards() == [0, 1, 2]


# -- the exactly-one-owner property ----------------------------------


def _assert_exactly_one_owner(topo: stopo.Topology, tenants) -> None:
    """Every tenant routes to exactly one LIVE shard, and exactly one
    leaf of the trie matches its hash (the partition invariant the
    deepest-match walk relies on)."""
    live = set(topo.live_shards())
    for t in tenants:
        h = stopo.tenant_hash(t)
        owner = topo.route(t)
        assert owner in live
        matches = [
            leaf
            for leaf in topo.leaves.values()
            if leaf.matches(h, topo.n_base)
        ]
        assert len(matches) == 1, (t, matches, topo.describe())
        assert matches[0].shard == owner


def _mergeable_pairs(topo: stopo.Topology):
    """(parent, child) leaf pairs the MERGE event would accept."""
    out = []
    for p, pl in topo.leaves.items():
        if pl.depth < 1 or pl.bits & (1 << (pl.depth - 1)):
            continue
        for c, cl in topo.leaves.items():
            if (
                c != p
                and cl.base == pl.base
                and cl.depth == pl.depth
                and cl.bits == (pl.bits | (1 << (pl.depth - 1)))
            ):
                out.append((p, c))
    return sorted(out)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("n_base", [1, 2, 3])
def test_any_split_merge_sequence_keeps_one_owner(seed, n_base):
    """The property test: for ANY tenant set and ANY randomized
    split/merge sequence — begins, commits, aborts, merges — every
    tenant routes to exactly one live shard at EVERY epoch, including
    mid-split (a pending child is not routable until its commit)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, n_base]))
    topo = stopo.Topology(n_base)
    tenants = [f"t{seed}-{i}" for i in range(150)]
    _assert_exactly_one_owner(topo, tenants)

    applied = {"n": 0}

    def apply(event, parent, child):
        ok = topo.apply(
            {
                "event": event,
                "parent": parent,
                "child": child,
                "epoch": topo.epoch + 1,
            }
        )
        assert ok, (event, parent, child, topo.describe())
        applied["n"] += 1

    for _ in range(40):
        merges = _mergeable_pairs(topo)
        if merges and rng.random() < 0.3:
            p, c = merges[int(rng.integers(0, len(merges)))]
            apply(stopo.MERGE, p, c)
        else:
            live = topo.live_shards()
            parent = int(live[int(rng.integers(0, len(live)))])
            child = topo.next_shard_id()
            before = {t: topo.route(t) for t in tenants}
            apply(stopo.SPLIT_BEGIN, parent, child)
            # Mid-split: routing is UNCHANGED — the pending child owns
            # nothing until the commit lands.
            assert child not in topo.live_shards()
            assert {t: topo.route(t) for t in tenants} == before
            _assert_exactly_one_owner(topo, tenants)
            if rng.random() < 0.25:
                apply(stopo.SPLIT_ABORT, parent, child)
                assert {t: topo.route(t) for t in tenants} == before
            else:
                apply(stopo.SPLIT_COMMIT, parent, child)
                # The split partitions the parent's old range: every
                # tenant it owned now routes to parent XOR child.
                for t, old in before.items():
                    if old == parent:
                        assert topo.route(t) in (parent, child)
                    else:
                        assert topo.route(t) == old
        _assert_exactly_one_owner(topo, tenants)
    # The walk's epochs were strictly increasing by construction; the
    # fold must agree.
    assert topo.epoch == applied["n"]


def test_aborted_child_id_is_burned():
    topo = stopo.Topology(2)
    child = topo.next_shard_id()
    assert child == 2
    topo.apply(
        {"event": stopo.SPLIT_BEGIN, "parent": 0, "child": 2, "epoch": 1}
    )
    topo.apply(
        {"event": stopo.SPLIT_ABORT, "parent": 0, "child": 2, "epoch": 2}
    )
    assert topo.live_shards() == [0, 1]
    # A stale replica's references to shard 2 can never alias a new
    # shard: the id is never recycled.
    assert topo.next_shard_id() == 3


def test_epoch_must_strictly_increase():
    topo = stopo.Topology(2)
    ev = {"event": stopo.SPLIT_BEGIN, "parent": 0, "child": 2, "epoch": 1}
    assert topo.apply(ev)
    # Replays and epoch races are ignored, not applied twice.
    assert not topo.apply(ev)
    assert not topo.apply({**ev, "child": 3})
    assert topo.epoch == 1 and len(topo.pending) == 1


def test_merge_rejects_non_siblings():
    topo = stopo.Topology(2)
    for e, ev in enumerate(
        (
            {"event": stopo.SPLIT_BEGIN, "parent": 0, "child": 2},
            {"event": stopo.SPLIT_COMMIT, "parent": 0, "child": 2},
            {"event": stopo.SPLIT_BEGIN, "parent": 1, "child": 3},
            {"event": stopo.SPLIT_COMMIT, "parent": 1, "child": 3},
        )
    ):
        assert topo.apply({**ev, "epoch": e + 1})
    # Different base cells: never siblings.
    assert not topo.apply(
        {"event": stopo.MERGE, "parent": 0, "child": 3, "epoch": 5}
    )
    # True siblings merge; the child leaf dies, the parent widens.
    assert topo.apply(
        {"event": stopo.MERGE, "parent": 0, "child": 2, "epoch": 5}
    )
    assert topo.live_shards() == [0, 1, 3]
    assert topo.leaves[0].depth == 0


# -- the durable log --------------------------------------------------


def test_append_topology_event_epochs_and_fold(tmp_path):
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 2)
    won, epoch, topo = stopo.append_topology_event(
        d, {"event": stopo.SPLIT_BEGIN, "parent": 0, "child": 2}
    )
    assert won and epoch == 1
    won, epoch, topo = stopo.append_topology_event(
        d, {"event": stopo.SPLIT_COMMIT, "parent": 0, "child": 2}
    )
    assert won and epoch == 2
    assert topo.live_shards() == [0, 1, 2]
    assert stopo.load_topology(d).epoch == 2


def test_append_topology_event_lost_race(tmp_path, monkeypatch):
    """A replica whose pre-append read missed a rival's record picks
    the SAME epoch; the read-back sees the rival's line first and
    reports the race lost — the fold ignores the loser entirely."""
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 2)
    stopo.append_topology_event(
        d, {"event": stopo.SPLIT_BEGIN, "parent": 0, "child": 2}
    )
    real = stopo.load_topology_events
    calls = {"n": 0}

    def stale_first_read(service_dir):
        evs = real(service_dir)
        calls["n"] += 1
        if calls["n"] == 1:
            return evs[:-1]  # the rival's append isn't visible yet
        return evs

    monkeypatch.setattr(stopo, "load_topology_events", stale_first_read)
    won, epoch, topo = stopo.append_topology_event(
        d, {"event": stopo.SPLIT_BEGIN, "parent": 1, "child": 2}
    )
    assert not won and epoch == 1
    # The loser's record is in the file but no fold ever applies it.
    assert topo.pending_for(0) is not None
    assert topo.pending_for(1) is None


def test_torn_topology_log_tail_replay(tmp_path):
    """Crash mid-append: a torn final line (no newline, half a JSON
    object) and binary junk are skipped; every complete record before
    them folds — the queue journal's read contract."""
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 2)
    stopo.append_topology_event(
        d, {"event": stopo.SPLIT_BEGIN, "parent": 0, "child": 2}
    )
    stopo.append_topology_event(
        d, {"event": stopo.SPLIT_COMMIT, "parent": 0, "child": 2}
    )
    path = stopo.topology_path(d)
    with open(path, "a") as f:
        f.write("[1, 2, 3]\n")  # decodable but not a record: skipped
        f.write('{"event": "split_begin", "parent": 1, "ch')  # torn
    topo = stopo.load_topology(d)
    assert topo.epoch == 2
    assert topo.live_shards() == [0, 1, 2]
    assert not topo.pending
    # The NEXT append lands after the torn tail as its own complete
    # line and still folds (O_APPEND starts a fresh line boundary is
    # NOT guaranteed — the reader just skips the merged garbage line).
    won, epoch, topo2 = stopo.append_topology_event(
        d, {"event": stopo.SPLIT_BEGIN, "parent": 1, "child": 3}
    )
    assert won and epoch == 3
    assert topo2.pending_for(1) is not None


# -- client wrong-shard retry ----------------------------------------


def _tenant_routing_to(shard: int, n: int = 2) -> str:
    i = 0
    while True:
        t = f"wst{i}"
        if stopo.tenant_hash(t) % n == shard:
            return t
        i += 1


def test_client_wrong_shard_retry_bounded(tmp_path):
    """A ``rejected_wrong_shard`` verdict makes the client re-read the
    topology and resubmit the SAME id to the current owner — exactly
    once. The origin's rejection is superseded, not terminal; a
    second rejection AT THE RETRY DESTINATION is terminal (the
    one-retry bound)."""
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 2)
    tenant = _tenant_routing_to(1)
    sh1 = fabric.shard_dir(d, 1)

    # The tenant's submission landed on shard 0 (stale client) and the
    # shard-0 daemon journaled the wrong-shard rejection.
    sh0 = fabric.shard_dir(d, 0)
    c0 = squeue.SweepClient(sh0, tenant=tenant)
    sid = c0.submit({"hidden_dim": 16}, tenant=tenant)
    q0 = squeue.SubmissionQueue(sh0)
    drained = q0.drain_intake(known_ids=set())
    assert [s.submission_id for s in drained] == [sid]
    q0.rejected(
        sid,
        verdict=squeue.REJECT_WRONG_SHARD,
        reason="tenant routes to shard 1",
    )

    client = fabric.FabricClient(d, n_shards=2, tenant=tenant)
    folded = client._folds()
    assert folded[sid]["state"] == squeue.REJECTED
    assert client._retry_wrong_shard(folded) is True
    # One resubmit, spooled to the owner, same id.
    spool = os.path.join(squeue.intake_dir(sh1), sid + ".json")
    assert os.path.exists(spool)
    assert client._wrong_shard_retries[sid] == 1
    with open(spool) as f:
        assert json.load(f)["submission_id"] == sid

    # Bounded: another poll resubmits nothing.
    before = os.path.getmtime(spool)
    assert client._retry_wrong_shard(client._folds()) is False
    assert os.path.getmtime(spool) == before

    # The origin's stale rejection is NOT terminal while the retry is
    # in flight...
    folded = client._folds()
    assert folded[sid]["shard"] == 0
    assert not client._terminal(sid, folded[sid])
    # ...but a wrong-shard rejection at the retry destination is.
    q1 = squeue.SubmissionQueue(sh1)
    q1.drain_intake(known_ids=set())
    q1.rejected(
        sid, verdict=squeue.REJECT_WRONG_SHARD, reason="still wrong"
    )
    folded = client._folds()
    assert folded[sid]["shard"] == 1
    assert client._terminal(sid, folded[sid])


# -- dynamic-topology loadgen scenarios ------------------------------


def test_fabric_scenario_zoo_gates():
    """Both named scenarios replay a small seeded workload through the
    two-arm harness: the elastic arm actually splits/steals, both arms
    settle everything (zero lost, none double-owned), and the elastic
    arm holds the within-10%-of-static latency/deadline gates."""
    from multidisttorch_tpu.service.loadgen import (
        FABRIC_SCENARIOS,
        run_fabric_scenario,
    )

    assert set(FABRIC_SCENARIOS) == {"coordinated_burst", "split_storm"}
    for name in sorted(FABRIC_SCENARIOS):
        r = run_fabric_scenario(name, n_submissions=1500, seed=3)
        assert r["protocol"] == "fabric_loadgen_v1"
        assert r["scenario"] == name
        dyn, sta = r["dynamic"], r["static"]
        assert dyn["splits"] >= 1, name
        assert sta["splits"] == 0 and sta["steals"] == 0
        assert dyn["topology_epoch"] == 2 * dyn["splits"]
        assert len(dyn["final_shards"]) == 2 + dyn["splits"]
        for arm in (dyn, sta):
            assert arm["zero_lost"], (name, arm["unfinished"])
            assert arm["no_double_own"]
            assert arm["completed"] == arm["admitted"]
        assert all(r["gates"].values()), (name, r["gates"])
    with pytest.raises(ValueError):
        run_fabric_scenario("nope")


def test_fabric_scenario_seeded_reruns_identical():
    from multidisttorch_tpu.service.loadgen import run_fabric_scenario

    def strip_wall(rep):
        return {
            k: (
                {kk: vv for kk, vv in v.items() if kk != "wall_s"}
                if k in ("dynamic", "static")
                else v
            )
            for k, v in rep.items()
        }

    a = run_fabric_scenario("coordinated_burst", n_submissions=600, seed=7)
    b = run_fabric_scenario("coordinated_burst", n_submissions=600, seed=7)
    assert strip_wall(a) == strip_wall(b)
