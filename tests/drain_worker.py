"""Worker for the SIGTERM graceful-drain subprocess test.

Runs a single-process sweep with per-epoch checkpoints; the parent
test sends SIGTERM mid-sweep and asserts the exit-code contract
(``cluster.PREEMPTION_EXIT_CODE``), then relaunches with ``resume`` to
assert at most one checkpoint cadence of work was lost.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    out_dir = sys.argv[1]
    resume = len(sys.argv) > 2 and sys.argv[2] == "resume"

    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
    from multidisttorch_tpu.hpo.supervision import exit_code_for

    train = synthetic_mnist(1024, seed=0)
    cfgs = [
        TrialConfig(
            0, epochs=10, batch_size=32, hidden_dim=64, latent_dim=8,
            seed=0, log_interval=10_000,
        )
    ]
    try:
        rs = run_hpo(
            cfgs, train, None, num_groups=1, out_dir=out_dir,
            verbose=False, save_images=False, save_checkpoints=True,
            resume="scan" if resume else False,
        )
    except Exception as e:  # noqa: BLE001 — exit-code contract
        print(f"DRAIN-EXIT {type(e).__name__}: {e}", flush=True)
        return exit_code_for(e)
    r = rs[0]
    print(
        "RESULT "
        + json.dumps(
            {
                "status": r.status,
                "steps": r.steps,
                "resumed_from_step": r.resumed_from_step,
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
