"""Fused-lane PBT tests (ISSUE 8): the in-program exploit/explore must
be bit-identical to the host-side reference path under the shared
seeding contract (docs/PBT.md), NaN lanes must rank last and never
source an exploit, the degenerate ``n_exploit == 0`` population must
skip the exchange, the fused generation program must compile ONCE
through the registry with cache hits on generation 2+, and the stacked
host-gather prefetch must be bit-transparent."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.data.sampler import StackedTrialDataIterator
from multidisttorch_tpu.hpo.pbt import PBTConfig, n_exploit_for, run_pbt
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.steps import (
    TrainState,
    TrialHypers,
    pbt_exchange,
    pbt_explore_key,
    pbt_perturb_factor,
)

pytestmark = pytest.mark.pbt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    defaults = dict(
        population=4,
        generations=3,
        steps_per_generation=3,
        batch_size=16,
        hidden_dim=16,
        latent_dim=4,
        exploit_fraction=0.5,
        lr_min=1e-4,
        lr_max=1e-1,
        seed=0,
    )
    defaults.update(kw)
    return PBTConfig(**defaults)


def _tree_equal(a, b) -> bool:
    flags = jax.tree.map(
        lambda x, y: bool(
            np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
        ),
        a,
        b,
    )
    return all(jax.tree.leaves(flags))


def _toy_state(k: int) -> TrainState:
    # A recognizable per-lane state: lane i's rows are all i, so a
    # gather's provenance is readable off the values.
    return TrainState(
        params={
            "w": jnp.tile(
                jnp.arange(k, dtype=jnp.float32)[:, None], (1, 3)
            )
        },
        opt_state={"m": jnp.arange(k, dtype=jnp.float32) * 10.0},
        step=jnp.full((k,), 7, jnp.int32),
    )


def _exchange(losses, n_exploit=2, gen=0, k=4):
    state = _toy_state(k)
    hypers = TrialHypers.stack([1e-3] * k, [1.0] * k)
    return pbt_exchange(
        state,
        hypers,
        jnp.asarray(losses, jnp.float32),
        gen,
        pbt_explore_key(0),
        n_exploit=n_exploit,
        perturb_factors=(0.8, 1.25),
        lr_min=1e-4,
        lr_max=1e-1,
    )


def test_exchange_nan_ranks_last_and_never_sources():
    # lane 1 diverged (NaN): it must rank strictly last, be exploited
    # (replaced by a healthy top lane), and never appear as a source.
    state, hypers, stats = _exchange([1.0, np.nan, 0.5, 2.0])
    order = np.asarray(stats["order"])
    assert list(order) == [2, 0, 3, 1]  # NaN last
    exploited = np.asarray(stats["exploited"])
    src = np.asarray(stats["src"])
    assert exploited[1] and exploited[3]
    assert src[1] == 0 and src[3] == 2
    assert 1 not in src[exploited]  # never a source
    # lane 1's whole state became lane 0's; lane 3's became lane 2's
    w = np.asarray(state.params["w"])
    assert np.all(w[1] == 0.0) and np.all(w[3] == 2.0)
    m = np.asarray(state.opt_state["m"])
    assert m[1] == 0.0 and m[3] == 20.0
    # exploited lanes' lrs were perturbed within bounds; winners kept
    lr = np.asarray(hypers.lr)
    assert lr[0] == np.float32(1e-3) and lr[2] == np.float32(1e-3)
    for lane in (1, 3):
        assert 1e-4 <= lr[lane] <= 1e-1
        assert lr[lane] != np.float32(1e-3)


def test_exchange_nan_same_under_jit():
    # the exchange runs jitted inside the fused generation program —
    # the NaN contract must hold identically compiled, with gen traced
    eager_state, eager_hypers, eager_stats = _exchange(
        [1.0, np.nan, 0.5, 2.0], gen=3
    )
    state = _toy_state(4)
    hypers = TrialHypers.stack([1e-3] * 4, [1.0] * 4)

    @jax.jit
    def go(state, hypers, losses, gen):
        return pbt_exchange(
            state, hypers, losses, gen, pbt_explore_key(0),
            n_exploit=2, perturb_factors=(0.8, 1.25),
            lr_min=1e-4, lr_max=1e-1,
        )

    jit_state, jit_hypers, jit_stats = go(
        state, hypers,
        jnp.asarray([1.0, np.nan, 0.5, 2.0], jnp.float32),
        jnp.int32(3),
    )
    assert _tree_equal(eager_state, jit_state)
    assert _tree_equal(eager_hypers, jit_hypers)
    assert _tree_equal(eager_stats, jit_stats)


def test_exchange_all_nan_is_identity():
    # an all-diverged population sanitizes to all-inf: inf > inf never
    # holds, so nothing exchanges (there is no winner to clone).
    state, hypers, stats = _exchange([np.nan] * 4)
    assert not np.asarray(stats["exploited"]).any()
    assert _tree_equal(state, _toy_state(4))
    assert np.array_equal(
        np.asarray(hypers.lr), np.full(4, 1e-3, np.float32)
    )


def test_exchange_tie_skips():
    state, hypers, stats = _exchange([1.5, 1.5, 1.5, 1.5])
    assert not np.asarray(stats["exploited"]).any()
    assert _tree_equal(state, _toy_state(4))


def test_exchange_n_exploit_zero_identity():
    state, hypers, stats = _exchange([3.0, 1.0], n_exploit=0, k=2)
    assert not np.asarray(stats["exploited"]).any()
    assert list(np.asarray(stats["order"])) == [1, 0]
    assert _tree_equal(state, _toy_state(2))


def test_n_exploit_clamps():
    assert n_exploit_for(_cfg(population=1)) == 0
    assert n_exploit_for(_cfg(population=2, exploit_fraction=0.9)) == 1
    assert n_exploit_for(_cfg(population=4, exploit_fraction=0.5)) == 2
    assert n_exploit_for(_cfg(population=8, exploit_fraction=0.25)) == 2


def test_perturb_factor_pure_deterministic_eager_equals_traced():
    ek = pbt_explore_key(7)
    factors = (0.8, 1.25)
    traced = jax.jit(
        lambda g, lane: pbt_perturb_factor(ek, g, lane, factors)
    )
    seen = set()
    for g in range(4):
        for lane in range(4):
            eager = float(pbt_perturb_factor(ek, g, lane, factors))
            assert eager in [float(np.float32(f)) for f in factors]
            assert eager == float(
                traced(jnp.int32(g), jnp.int32(lane))
            )
            # pure: a second eager draw is identical
            assert eager == float(pbt_perturb_factor(ek, g, lane, factors))
            seen.add((g, lane, eager))
    # the stream actually varies over (gen, lane)
    assert len({v for (_, _, v) in seen}) == 2


def test_fused_matches_submesh_reference_bitwise():
    # THE parity contract: same seeds, same data, same explore draws —
    # the fused lane-axis exchange must reproduce the host-side
    # reference path bit-for-bit: per-generation loss sums, ranking,
    # exploit edges, lrs, and every member's final state.
    cfg = _cfg()
    train = synthetic_mnist(128, seed=0)
    evals = synthetic_mnist(40, seed=1)  # 3 eval batches, one padded
    groups = setup_groups(cfg.population)
    ref = run_pbt(
        cfg, train, evals, groups=groups, verbose=False,
        return_states=True,
    )
    fus = run_pbt(
        cfg, train, evals, groups=[groups[0]], fused=True,
        verbose=False, return_states=True,
    )
    assert ref.mode == "submesh" and fus.mode == "fused"
    for g in range(cfg.generations):
        r, f = ref.history[g], fus.history[g]
        assert r["loss_sums"] == f["loss_sums"], f"gen {g} sums"
        assert r["order"] == f["order"], f"gen {g} order"
        assert r["exploits"] == f["exploits"], f"gen {g} exploits"
        assert r["scores"] == f["scores"], f"gen {g} scores"
    assert ref.final_lrs == fus.final_lrs
    assert ref.best_member == fus.best_member
    assert ref.best_eval_loss == fus.best_eval_loss
    for k in range(cfg.population):
        assert _tree_equal(
            ref.final_states[k], fus.final_states[k]
        ), f"member {k} final state diverged"
    # at least one exploit actually fired, or the drill proves nothing
    assert sum(len(h["exploits"]) for h in ref.history) >= 1
    # and the dispatch collapse is real: one dispatch per generation
    # fused vs >= K train + K eval per generation on the reference path
    assert fus.dispatch_book["program_calls"] == cfg.generations
    assert (
        ref.dispatch_book["dispatches_per_generation"]
        >= 3 * fus.dispatch_book["dispatches_per_generation"]
    )


def test_fused_degenerate_population_one():
    # K=1: n_exploit clamps to 0, the exchange is identity, and the
    # single lane still trains and scores.
    cfg = _cfg(population=1, generations=2)
    train = synthetic_mnist(64, seed=0)
    evals = synthetic_mnist(16, seed=1)
    r = run_pbt(
        cfg, train, evals, groups=setup_groups(1), fused=True,
        verbose=False,
    )
    assert r.best_member == 0
    assert np.isfinite(r.best_eval_loss)
    assert all(h["exploits"] == [] for h in r.history)


def test_fused_registry_one_compile_cache_hit_gen2plus(tmp_path):
    # The pbt_gen program rides the PR 7 registry: ONE compile ever,
    # and generation 2+ admissions are registry cache hits — asserted
    # off both the registry snapshot and the emitted compile events.
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.compile.registry import (
        get_executable_registry,
    )

    # a protocol distinct from every other test in this module, so the
    # process-lifetime registry entry is provably THIS run's
    cfg = _cfg(generations=3, steps_per_generation=5)
    train = synthetic_mnist(128, seed=0)
    evals = synthetic_mnist(16, seed=1)
    with telemetry.telemetry_run(str(tmp_path)):
        run_pbt(
            cfg, train, evals, groups=setup_groups(1), fused=True,
            verbose=False,
        )
        events = telemetry.read_events(
            os.path.join(str(tmp_path), "events.jsonl")
        )
    snap = get_executable_registry().snapshot()
    mine = {
        label: v
        for label, v in snap.items()
        if label.startswith("pbt_gen") and "-S5-" in label
    }
    assert mine, f"pbt_gen program missing from registry: {list(snap)}"
    (entry,) = mine.values()
    assert entry["status"] == "ready"
    assert entry["hits"] >= cfg.generations - 1
    compile_ends = [
        e for e in events
        if e["kind"] == "compile_end"
        and str(e["data"].get("program", "")).startswith("pbt_gen")
    ]
    assert len(compile_ends) == 1
    assert compile_ends[0]["data"]["ok"] is True
    assert compile_ends[0]["data"]["program_kind"] == "pbt_gen"
    hits = [
        e for e in events
        if e["kind"] == "cache_hit"
        and "-S5-" in str(e["data"].get("program", ""))
    ]
    assert len(hits) >= cfg.generations - 1


def test_pbt_events_and_population_fold(tmp_path):
    # pbt_gen / pbt_exploit events feed the SweepFold population view
    # the console renders: per-generation best/median loss, exploit
    # count, rank churn, lr quantiles.
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.telemetry.export import SweepFold, run_summary

    cfg = _cfg(generations=2)
    train = synthetic_mnist(128, seed=0)
    evals = synthetic_mnist(16, seed=1)
    with telemetry.telemetry_run(str(tmp_path)):
        run_pbt(
            cfg, train, evals, groups=setup_groups(1), fused=True,
            verbose=False,
        )
        events = telemetry.read_events(
            os.path.join(str(tmp_path), "events.jsonl")
        )
    gens = [e for e in events if e["kind"] == "pbt_gen"]
    assert len(gens) == cfg.generations
    for e in gens:
        d = e["data"]
        assert d["mode"] == "fused" and d["population"] == cfg.population
        assert np.isfinite(d["best_loss"])
        assert d["lr_min"] <= d["lr_median"] <= d["lr_max"]
    # churn appears from generation 1 on (no previous ordering before)
    assert "rank_churn" not in gens[0]["data"]
    assert "rank_churn" in gens[1]["data"]
    exploits = [e for e in events if e["kind"] == "pbt_exploit"]
    assert len(exploits) == sum(
        g["data"]["exploit_count"] for g in gens
    )
    fold = SweepFold()
    for e in events:
        fold.feed(e)
    assert fold.pbt["mode"] == "fused"
    assert fold.pbt["population"] == cfg.population
    assert sorted(fold.pbt["generations"]) == list(
        range(cfg.generations)
    )
    assert fold.pbt["exploit_total"] == len(exploits)
    # run_summary carries the population view too
    assert run_summary(events)["pbt"]["population"] == cfg.population


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sweep_top_population_view(tmp_path, capsys):
    from multidisttorch_tpu import telemetry

    # distinct S so this test's registry key never collides with the
    # compile-count assertions of the registry test above
    cfg = _cfg(generations=2, steps_per_generation=4)
    train = synthetic_mnist(128, seed=0)
    evals = synthetic_mnist(16, seed=1)
    with telemetry.telemetry_run(str(tmp_path)):
        run_pbt(
            cfg, train, evals, groups=setup_groups(1), fused=True,
            verbose=False,
        )
    sweep_top = _load_tool("sweep_top")
    assert sweep_top.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "population" in out
    assert "mode fused" in out
    assert "lr min/med/max" in out
    # one-shot machine-readable snapshot carries the same fold
    assert sweep_top.main([str(tmp_path), "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["pbt"]["mode"] == "fused"
    assert len(snap["pbt"]["generations"]) == cfg.generations


def test_stacked_stream_chunks_crosses_rounds():
    # stream_chunks must replay exactly the per-round batches, in
    # order, across round boundaries (fresh permutation each round),
    # and every chunk must be full.
    trial = setup_groups(1)[0]
    ds = synthetic_mnist(96, seed=3)
    seeds = [11, 12]
    a = StackedTrialDataIterator(ds, trial, 16, list(seeds))
    b = StackedTrialDataIterator(ds, trial, 16, list(seeds))
    # a: 4 chunks of 3 steps = 12 steps = 2 full rounds of 6 batches
    chunks = [np.asarray(c) for _, c in zip(range(4), a.stream_chunks(3))]
    flat = np.concatenate(chunks, axis=0)
    rounds = []
    for _ in range(2):
        rounds.extend(np.asarray(x) for x in b.round_batches())
    assert np.array_equal(flat, np.stack(rounds))
    for c in chunks:
        assert c.shape == (3, 2, 16, 784)


def test_stacked_prefetch_bit_parity_and_kill_switch(monkeypatch):
    trial = setup_groups(1)[0]
    ds = synthetic_mnist(128, seed=4)
    seeds = [3, 9, 27]
    on = StackedTrialDataIterator(ds, trial, 16, list(seeds), prefetch=True)
    off = StackedTrialDataIterator(
        ds, trial, 16, list(seeds), prefetch=False
    )
    assert on._prefetch and not off._prefetch
    for _ in range(2):  # two rounds: prefetch threads come and go
        # drain each round fully (zip would leave the shorter-pulled
        # generator paused before its epoch advance)
        ra = [np.asarray(x) for x in on.round_batches()]
        rb = [np.asarray(y) for y in off.round_batches()]
        assert len(ra) == len(rb) == on.num_batches
        for x, y in zip(ra, rb):
            assert np.array_equal(x, y)
    # the env kill switch forces the inline path
    monkeypatch.setenv("MDT_STACKED_PREFETCH", "0")
    assert not StackedTrialDataIterator(
        ds, trial, 16, [1]
    )._prefetch
    monkeypatch.delenv("MDT_STACKED_PREFETCH")
    assert StackedTrialDataIterator(ds, trial, 16, [1])._prefetch


def test_stacked_prefetch_fault_hook_timing():
    # An injected loader fault must surface at the SAME batch index
    # with prefetch on as off (the hook runs consumer-side), and the
    # batches before it must still be delivered.
    trial = setup_groups(1)[0]
    ds = synthetic_mnist(96, seed=5)

    class Boom(RuntimeError):
        pass

    def hook(b, stacked):
        if b == 2:
            raise Boom(f"batch {b}")
        return stacked

    for prefetch in (True, False):
        it = StackedTrialDataIterator(
            ds, trial, 16, [1], fault_hook=hook, prefetch=prefetch
        )
        got = []
        with pytest.raises(Boom, match="batch 2"):
            for x in it.round_batches():
                got.append(np.asarray(x))
        assert len(got) == 2, f"prefetch={prefetch}"


def test_stacked_prefetch_abandon_does_not_wedge():
    # Abandoning a prefetched round mid-way (lane refill, retirement,
    # an exception upstream) must leave no stuck producer: the next
    # round iterates cleanly and matches a fresh iterator.
    trial = setup_groups(1)[0]
    ds = synthetic_mnist(128, seed=6)
    it = StackedTrialDataIterator(ds, trial, 16, [5], prefetch=True)
    gen = it.round_batches()
    next(gen)
    gen.close()  # abandon mid-round
    # iterating a new round still works and epochs stayed consistent
    n = sum(1 for _ in it.round_batches())
    assert n == it.num_batches
