"""Sweep-as-a-service: queue durability, scheduler invariants, defrag
policy, and the daemon runtime (docs/SERVICE.md).

The property-style invariants (ISSUE 10's test satellite):

- fair share never starves a nonempty tenant;
- bin-packing never splits a shape bucket across submeshes mid-pass;
- defrag never migrates a trial with an unflushed checkpoint;
- the queue survives ``kill -9`` mid-append (real subprocess SIGKILL).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from multidisttorch_tpu.service.defrag import PlacedBlock, plan_defrag
from multidisttorch_tpu.service.queue import (
    ADMITTED,
    PENDING,
    SETTLED,
    QueueStats,
    Submission,
    SubmissionQueue,
    SweepClient,
    fold_queue,
    intake_dir,
    load_queue,
    queue_path,
)
from multidisttorch_tpu.service.scheduler import (
    ADMIT,
    FairShareScheduler,
    PendingTrial,
    REJECT_BACKPRESSURE,
    REJECT_QUOTA,
    SlicePool,
    TenantPolicy,
)

pytestmark = pytest.mark.service


def entry(
    sub_id,
    tenant="t",
    *,
    priority=1,
    bucket=("b",),
    size=1,
    cost=10.0,
    **kw,
):
    return PendingTrial(
        sub_id=sub_id,
        tenant=tenant,
        priority=priority,
        cfg=None,
        bucket=bucket,
        size=size,
        cost=cost,
        submit_ts=0.0,
        **kw,
    )


# --------------------------------------------------------------------
# durable queue
# --------------------------------------------------------------------


class TestQueue:
    def test_submit_drain_settle_roundtrip(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d, tenant="alice")
        sid = c.submit({"epochs": 2}, priority=0, size=2, deadline_s=9.0)
        assert sid.startswith("alice-")
        # Committed before any daemon exists:
        assert c.status(sid)["state"] == PENDING
        q = SubmissionQueue(d)
        known = set()
        fresh = q.drain_intake(known_ids=known)
        assert [s.submission_id for s in fresh] == [sid]
        assert fresh[0].tenant == "alice"
        assert fresh[0].size == 2 and fresh[0].priority == 0
        assert fresh[0].deadline_s == 9.0
        # Spool file consumed; journal carries it now.
        assert not os.listdir(intake_dir(d))
        q.admitted(sid, trial_id=0, chash="h0", bucket="(b,)")
        q.placed(
            sid, trial_id=0, start=0, size=2, lanes=1,
            stacked=False, resumed=False,
        )
        q.settled(sid, trial_id=0, status="completed")
        rec = fold_queue(load_queue(d))[sid]
        assert rec["state"] == SETTLED
        assert rec["status"] == "completed"
        assert rec["trial_id"] == 0
        assert rec["placements"] == 1
        stats = QueueStats.of({sid: rec})
        assert stats.by_state == {SETTLED: 1}

    def test_unplaced_returns_to_admitted(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d)
        sid = c.submit({})
        q = SubmissionQueue(d)
        q.drain_intake(known_ids=set())
        q.admitted(sid, trial_id=0, chash="h", bucket="b")
        q.placed(sid, trial_id=0, start=0, size=1, lanes=1,
                 stacked=False, resumed=False)
        q.unplaced(sid, trial_id=0, reason="drain")
        rec = fold_queue(load_queue(d))[sid]
        assert rec["state"] == ADMITTED
        assert rec["unplaced_reason"] == "drain"

    def test_torn_tail_costs_one_transition_not_the_submission(
        self, tmp_path
    ):
        d = str(tmp_path)
        c = SweepClient(d)
        sid = c.submit({})
        q = SubmissionQueue(d)
        q.drain_intake(known_ids=set())
        q.admitted(sid, trial_id=0, chash="h", bucket="b")
        # Crash mid-append: the settled record tears.
        with open(queue_path(d), "a") as f:
            f.write('{"event": "settled", "submission_id": "' + sid)
        rec = fold_queue(load_queue(d))[sid]
        assert rec["state"] == ADMITTED  # the torn line is skipped

    def test_duplicate_spool_replay_is_idempotent(self, tmp_path):
        # Crash between the durable `submitted` append and the spool
        # unlink: the file replays but must not journal twice.
        d = str(tmp_path)
        c = SweepClient(d)
        sid = c.submit({})
        q = SubmissionQueue(d)
        q.drain_intake(known_ids=set())
        # Resurrect the spool file (as if unlink never happened).
        c2 = SweepClient(d)
        path = os.path.join(intake_dir(d), sid + ".json")
        with open(path, "w") as f:
            json.dump(
                Submission(
                    submission_id=sid, tenant="default", config={}
                ).to_dict(),
                f,
            )
        known = set(fold_queue(load_queue(d)))
        fresh = q.drain_intake(known_ids=known)
        assert fresh == []  # deduped
        assert not os.path.exists(path)  # but still cleaned up
        events = load_queue(d)
        assert (
            sum(1 for e in events if e.get("event") == "submitted") == 1
        )
        del c2

    def test_torn_tmp_spool_file_ignored(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(intake_dir(d), exist_ok=True)
        with open(os.path.join(intake_dir(d), "x.json.tmp"), "w") as f:
            f.write('{"submission_id": "x"')  # mid-write
        with open(os.path.join(intake_dir(d), "bad.json"), "w") as f:
            f.write("{garbled")  # renamed but undecodable (fs damage)
        q = SubmissionQueue(d)
        assert q.drain_intake(known_ids=set()) == []

    def test_queue_survives_kill9_mid_append(self, tmp_path):
        """A real SIGKILL against a child hammering submits + journal
        appends: afterwards the journal folds cleanly and every
        DURABLY-submitted id (client returned / journal holds it) is
        recoverable — the zero-lost-submissions contract."""
        d = str(tmp_path)
        code = (
            "import sys, os\n"
            "sys.path.insert(0, %r)\n"
            "from multidisttorch_tpu.service.queue import ("
            "SweepClient, SubmissionQueue)\n"
            "d = %r\n"
            "c = SweepClient(d, tenant='k9')\n"
            "q = SubmissionQueue(d)\n"
            "known = set()\n"
            "i = 0\n"
            "while True:\n"
            "    sid = c.submit({'seed': i})\n"
            "    print(sid, flush=True)\n"
            "    q.drain_intake(known_ids=known)\n"
            "    q.admitted(sid, trial_id=i, chash='h%%d' %% i, "
            "bucket='b')\n"
            "    i += 1\n"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
             d)
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        # Let it commit a few, then kill -9 mid-flight.
        printed = []
        deadline = time.time() + 30
        while len(printed) < 5 and time.time() < deadline:
            line = proc.stdout.readline().strip()
            if line:
                printed.append(line)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        assert printed, "child never committed a submission"
        folded = fold_queue(load_queue(d))
        spooled = {
            n[: -len(".json")]
            for n in os.listdir(intake_dir(d))
            if n.endswith(".json")
        }
        for sid in printed:
            # Every id the client observed as committed is either
            # journaled or still sitting durably in the spool.
            assert sid in folded or sid in spooled, sid
        # The journal itself folds without error (torn tail skipped).
        for rec in folded.values():
            assert rec["state"] in (PENDING, ADMITTED)


# --------------------------------------------------------------------
# scheduler: admission, fair share, packing
# --------------------------------------------------------------------


class TestAdmission:
    def test_quota_and_backpressure_verdicts(self):
        s = FairShareScheduler(
            {"a": TenantPolicy(max_pending=2)},
            max_total_pending=3,
        )
        assert s.admit_verdict("a")[0] == ADMIT
        s.push(entry("a0", "a"))
        s.push(entry("a1", "a"))
        v, reason = s.admit_verdict("a")
        assert v == REJECT_QUOTA and "quota" in reason
        # Other tenants still fine until the global cap...
        assert s.admit_verdict("b")[0] == ADMIT
        s.push(entry("b0", "b"))
        v, _ = s.admit_verdict("b")
        assert v == REJECT_BACKPRESSURE


class TestFairShare:
    def _drain(self, s, pool, max_iters=500, max_lanes=1):
        order = []
        for _ in range(max_iters):
            ps = s.schedule(pool, max_lanes=max_lanes)
            for p in ps:
                order.extend(e.tenant for e in p.members)
                pool.free(p.start, p.size)
            if not s.pending_count():
                break
        return order

    @pytest.mark.parametrize("n_slices", [1, 4])
    def test_weighted_share_under_contention(self, n_slices):
        # 2:1 weights, 2:1 demand, equal cost -> contended service
        # lands within 10% of the weights in BOTH slot regimes.
        pool = SlicePool(n_slices)
        s = FairShareScheduler(
            {"a": TenantPolicy(weight=2.0), "b": TenantPolicy(weight=1.0)}
        )
        for i in range(24):
            s.push(entry(f"a{i}", "a", bucket=("x", i)))
        for i in range(12):
            s.push(entry(f"b{i}", "b", bucket=("y", i)))
        self._drain(s, pool)
        rep = s.fair_share_report()
        for t in ("a", "b"):
            assert abs(rep[t]["ratio_to_weight"] - 1.0) <= 0.10, rep

    def test_nonempty_tenant_never_starves(self):
        # Property: under an adversarial weight gap and a single slot,
        # the tiny-weight tenant is still served in bounded time.
        pool = SlicePool(1)
        s = FairShareScheduler(
            {
                "whale": TenantPolicy(weight=1000.0),
                "shrimp": TenantPolicy(weight=0.001),
            }
        )
        for i in range(200):
            s.push(entry(f"w{i}", "whale", bucket=("w", i)))
        s.push(entry("s0", "shrimp", bucket=("s",)))
        served_shrimp = False
        for _ in range(250):
            for p in s.schedule(pool, max_lanes=1):
                if any(e.tenant == "shrimp" for e in p.members):
                    served_shrimp = True
                pool.free(p.start, p.size)
            if served_shrimp:
                break
        assert served_shrimp

    def test_idle_tenant_banks_no_credit(self):
        # A tenant idle while another is served must not later burst
        # past its weight share (virtual-time activation rule).
        pool = SlicePool(1)
        s = FairShareScheduler(
            {"a": TenantPolicy(weight=1.0), "b": TenantPolicy(weight=1.0)}
        )
        for i in range(20):
            s.push(entry(f"a{i}", "a", bucket=("x", i)))
        # Serve a alone for 10 opportunities.
        for _ in range(10):
            for p in s.schedule(pool, max_lanes=1):
                pool.free(p.start, p.size)
        for i in range(20):
            s.push(entry(f"b{i}", "b", bucket=("y", i)))
        order = self._drain(s, pool)
        # From b's arrival, service alternates ~1:1 — b does NOT get a
        # 10-placement catch-up monopoly.
        first10 = order[:10]
        assert first10.count("b") <= 6, order[:12]

    def test_priority_lane_strictness(self):
        pool = SlicePool(1)
        s = FairShareScheduler()
        s.push(entry("lo", "t", priority=2, bucket=("l",)))
        s.push(entry("hi", "u", priority=0, bucket=("h",)))
        ps = s.schedule(pool, max_lanes=1)
        assert ps[0].members[0].sub_id == "hi"

    def test_backoff_veto_does_not_block_tenant(self):
        pool = SlicePool(2)
        s = FairShareScheduler()
        late = entry("late", "t", bucket=("l",))
        late.not_before = time.time() + 3600
        s.push(late)
        s.push(entry("now", "t", bucket=("n",)))
        now = time.time()
        ps = s.schedule(
            pool, max_lanes=1, can_start=lambda e: now >= e.not_before
        )
        assert [p.members[0].sub_id for p in ps] == ["now"]


class TestPacking:
    def test_same_bucket_copacks_across_tenants(self):
        pool = SlicePool(4)
        s = FairShareScheduler()
        s.push(entry("a0", "a", bucket=("same",)))
        s.push(entry("b0", "b", bucket=("same",)))
        ps = s.schedule(pool, max_lanes=4)
        assert len(ps) == 1 and ps[0].lanes == 2
        assert {e.tenant for e in ps[0].members} == {"a", "b"}

    def test_never_splits_a_bucket_across_submeshes(self):
        # Invariant: one pass opens ceil(n/max_lanes) placements per
        # (bucket, size) — never two partially-filled submeshes.
        pool = SlicePool(8)
        s = FairShareScheduler()
        for i in range(11):
            s.push(entry(f"x{i}", f"t{i % 3}", bucket=("B",)))
        ps = s.schedule(pool, max_lanes=4)
        same = [p for p in ps if p.bucket == ("B",)]
        lanes = sorted(p.lanes for p in same)
        assert sum(lanes) == 11
        assert lanes == [3, 4, 4]
        underfull = [p for p in same if p.lanes < 4]
        assert len(underfull) <= 1
        for p in same:
            assert all(e.bucket == ("B",) for e in p.members)

    def test_resume_scan_never_copacks(self):
        pool = SlicePool(4)
        s = FairShareScheduler()
        s.push(entry("fresh", "a", bucket=("B",)))
        s.push(entry("recovered", "a", bucket=("B",), resume_scan=True))
        ps = s.schedule(pool, max_lanes=4)
        assert len(ps) == 2  # the scan-resume trial runs classic

    def test_blocked_large_stamps_starvation_clock(self):
        s = FairShareScheduler()
        # occupy 0 and 2 so no 2-contiguous run exists
        pool2 = SlicePool(4)
        assert pool2.alloc_at(0, 1) and pool2.alloc_at(2, 1)
        big = entry("big", "t", bucket=("big",), size=2)
        s.push(big)
        t0 = 1000.0
        assert s.schedule(pool2, max_lanes=1, now=t0) == []
        assert big.blocked_since == t0
        starved = s.starved_entries(threshold_s=5.0, now=t0 + 6.0)
        assert [e.sub_id for e in starved] == ["big"]
        # Fragmentation gauge sees it too.
        assert pool2.fragmentation() == 0.5
        assert pool2.largest_free_run() == 1 and pool2.free_total == 2


class TestSlicePool:
    def test_alloc_contiguity_and_coalescing(self):
        p = SlicePool(6)
        a = p.alloc(2)
        b = p.alloc(3)
        assert (a, b) == (0, 2)
        p.free(a, 2)
        assert p.free_runs() == [(0, 2), (5, 1)]
        assert p.alloc(3) is None  # only 2+1 available
        p.free(b, 3)
        assert p.free_runs() == [(0, 6)]  # coalesced
        with pytest.raises(ValueError):
            p.free(0, 1)  # double free

    def test_alloc_at(self):
        p = SlicePool(4)
        assert p.alloc_at(2, 2)
        assert not p.alloc_at(1, 2)  # overlaps
        assert not p.alloc_at(3, 2)  # out of range
        assert p.alloc(2) == 0


# --------------------------------------------------------------------
# defrag planner
# --------------------------------------------------------------------


class TestDefragPlanner:
    def _pool(self, n, occupied):
        p = SlicePool(n)
        for start, size in occupied:
            assert p.alloc_at(start, size)
        return p

    def test_min_moves_window(self):
        # occupied: A@1(1), B@3(1); free {0,2}. Want 2: either window
        # works with ONE move; the plan picks the lowest feasible
        # window and re-homes the victim outside it.
        pool = self._pool(4, [(1, 1), (3, 1)])
        blocks = [
            PlacedBlock(0, 1, 1, True),
            PlacedBlock(1, 3, 1, True),
        ]
        plan = plan_defrag(pool, blocks, 2)
        assert plan is not None and len(plan.moves) == 1
        (pid, dst) = plan.moves[0]
        assert plan.window_start == 0 and pid == 0 and dst == 2

    def test_never_moves_unflushed_checkpoint(self):
        # The unflushed (movable=False) placement is never a victim —
        # even when that makes the plan infeasible.
        pool = self._pool(4, [(1, 1), (3, 1)])
        blocks = [
            PlacedBlock(0, 1, 1, False),  # unflushed
            PlacedBlock(1, 3, 1, False),
        ]
        assert plan_defrag(pool, blocks, 2) is None
        # movable_fn veto at PLAN time wins over a stale flag too.
        blocks = [
            PlacedBlock(0, 1, 1, True),
            PlacedBlock(1, 3, 1, True),
        ]
        assert (
            plan_defrag(pool, blocks, 2, movable_fn=lambda b: False)
            is None
        )
        plan = plan_defrag(
            pool, blocks, 2, movable_fn=lambda b: b.placement_id == 1
        )
        assert plan is not None
        assert [pid for pid, _ in plan.moves] == [1]

    def test_victims_rehome_outside_window(self):
        # 6 slices: occupied A@1(1), B@4(1); free {0,2,3,5}. Want 3:
        # cheapest window is {0,1,2} (one move), and A must re-home in
        # free space OUTSIDE that window ({3} first-fit).
        pool = self._pool(6, [(1, 1), (4, 1)])
        blocks = [
            PlacedBlock(0, 1, 1, True),
            PlacedBlock(1, 4, 1, True),
        ]
        plan = plan_defrag(pool, blocks, 3)
        assert plan is not None
        assert plan.window_start == 0 and plan.window_size == 3
        assert plan.moves == [(0, 3)]
        # And genuinely infeasible layouts return None: every window
        # holds work, and the one free slice cannot absorb a 2-wide
        # victim.
        pool2 = self._pool(6, [(0, 2), (3, 1), (5, 1)])
        blocks2 = [
            PlacedBlock(0, 0, 2, True),
            PlacedBlock(1, 3, 1, True),
            PlacedBlock(2, 5, 1, True),
        ]
        assert plan_defrag(pool2, blocks2, 3) is None

    def test_zero_move_plan_when_already_fits(self):
        pool = self._pool(4, [(0, 1)])
        plan = plan_defrag(pool, [PlacedBlock(0, 0, 1, True)], 2)
        assert plan is not None and plan.moves == []
        assert plan.window_start == 1

    def test_infeasible_capacity_returns_none(self):
        pool = self._pool(2, [(0, 2)])
        assert plan_defrag(
            pool, [PlacedBlock(0, 0, 2, True)], 2
        ) is None


# --------------------------------------------------------------------
# ledger satellites: tags + concurrent compaction
# --------------------------------------------------------------------


class TestLedgerSatellites:
    def test_tenant_tags_on_attempt_records(self, tmp_path):
        from multidisttorch_tpu.hpo.ledger import SweepLedger

        led = SweepLedger(str(tmp_path))
        led.attempt_start(
            0, "h0", 1, tenant="alice", priority=0, submit_ts=123.5
        )
        led.attempt_end(
            0, "h0", 1, "completed",
            summary={"steps": 4},
            tenant="alice", priority=0, submit_ts=123.5,
        )
        led.attempt_start(1, "h1", 1)  # untagged — old callers
        evs = led.load()
        assert evs[0]["tenant"] == "alice"
        assert evs[0]["priority"] == 0
        assert evs[0]["submit_ts"] == 123.5
        assert evs[1]["tenant"] == "alice"
        assert "tenant" not in evs[2]  # untagged stays byte-compatible
        # Old-style records (no tags) parse through every fold.
        assert led.attempts() == {"h0": 1, "h1": 1}
        assert set(led.finished()) == {"h0"}

    def test_tagged_events_feed_sweepfold_and_fleet(self, tmp_path):
        from multidisttorch_tpu import telemetry
        from multidisttorch_tpu.hpo.ledger import SweepLedger
        from multidisttorch_tpu.telemetry.export import (
            SweepFold,
            run_summary,
        )
        from multidisttorch_tpu.telemetry.fleet import per_tenant_books

        tel = str(tmp_path / "tel")
        with telemetry.telemetry_run(tel):
            led = SweepLedger(str(tmp_path))
            for tid, ten in ((0, "alice"), (1, "bob")):
                led.attempt_start(tid, f"h{tid}", 1, tenant=ten)
                led.attempt_end(
                    tid, f"h{tid}", 1, "completed",
                    summary={"steps": 8, "resumed_from_step": 0},
                    tenant=ten,
                )
            events = [
                e.to_dict()
                for e in telemetry.get_bus().recent()
            ]
            summary = run_summary(events)
        fold = SweepFold()
        for e in events:
            fold.feed(e)
        books = fold.tenant_books()
        assert books["alice"]["useful_steps"] == 8
        assert books["alice"]["goodput"] == 1.0
        assert books["bob"]["trials"] == 1
        assert summary["tenants"]["bob"]["settled"] == 1
        assert fold.trials[0]["tenant"] == "alice"
        fleet = per_tenant_books(events)
        assert fleet["alice"]["goodput"] == 1.0
        assert fleet["bob"]["trials"] == 1

    def test_untagged_stream_has_no_tenant_keys(self):
        from multidisttorch_tpu.telemetry.export import run_summary

        summary = run_summary(
            [
                {
                    "kind": "attempt_end",
                    "ts": 1.0,
                    "trial_id": 0,
                    "attempt": 1,
                    "data": {
                        "status": "completed",
                        "summary": {"steps": 2},
                    },
                }
            ]
        )
        assert "tenants" not in summary

    def test_compact_concurrent_with_appender_loses_nothing(
        self, tmp_path
    ):
        """The satellite bugfix: a compaction racing a live appender
        must not drop the appended record. Without the mutate lock the
        append lands between compact()'s load and its os.replace and
        vanishes; with it, every hash appended by the writer thread
        survives every concurrent compaction."""
        import threading

        from multidisttorch_tpu.hpo.ledger import SweepLedger

        led = SweepLedger(str(tmp_path))
        N = 120
        stop = threading.Event()

        def appender():
            for i in range(N):
                led.attempt_start(i, f"h{i}", 1)
                led.attempt_end(
                    i, f"h{i}", 1, "completed", summary={"steps": 1}
                )
            stop.set()

        def compactor():
            while not stop.is_set():
                led.compact()
            led.compact()

        ta = threading.Thread(target=appender)
        tc = threading.Thread(target=compactor)
        ta.start()
        tc.start()
        ta.join(timeout=120)
        tc.join(timeout=120)
        assert stop.is_set()
        finished = led.finished()
        assert len(finished) == N, (
            f"compaction dropped {N - len(finished)} settled records"
        )
        attempts = led.attempts()
        assert all(attempts[f"h{i}"] == 1 for i in range(N))


# --------------------------------------------------------------------
# runtime: end-to-end service drills (real training on virtual CPUs)
# --------------------------------------------------------------------


BASE = dict(batch_size=32, latent_dim=4, log_interval=1000)


def make_service(d, **kw):
    from multidisttorch_tpu.service.runtime import SweepService

    kw.setdefault("data_rows", 128)
    kw.setdefault("verbose", False)
    return SweepService(str(d), **kw)


def run_until(svc, cond, timeout_s=180.0):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        svc.tick()
        if cond():
            return True
    return False


class TestServiceRuntime:
    def test_multi_tenant_e2e_with_copack(self, tmp_path):
        d = str(tmp_path)
        ca = SweepClient(d, tenant="alice")
        cb = SweepClient(d, tenant="bob")
        ids = [
            ca.submit({**BASE, "epochs": 1, "hidden_dim": 16, "seed": i})
            for i in range(2)
        ]
        ids.append(
            cb.submit({**BASE, "epochs": 1, "hidden_dim": 16, "seed": 9})
        )
        svc = make_service(tmp_path, n_slices=2, max_lanes=4)
        rep = svc.serve(exit_when_drained=True, max_wall_s=300)
        assert rep["outcome"] == "idle"
        assert sorted(rep["settled"]) == sorted(ids)
        assert set(rep["settled"].values()) == {"completed"}
        # Same shape bucket from DIFFERENT tenants co-packed into one
        # stacked placement:
        folded = fold_queue(load_queue(d))
        lanes = {folded[s]["last_placement"]["lanes"] for s in ids}
        assert lanes == {3}
        assert all(folded[s]["last_placement"]["stacked"] for s in ids)
        books = rep["books"]
        assert books["tenants"]["alice"]["goodput"] == 1.0
        assert books["tenants"]["bob"]["settled"] == 1
        assert books["queue_wait"]["count"] == 3
        assert books["placement_latency"]["count"] >= 1

    def test_invalid_config_rejected_not_crashed(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d)
        bad = c.submit({"no_such_field": 1})
        huge = c.submit({**BASE, "epochs": 1, "hidden_dim": 16}, size=99)
        ok = c.submit({**BASE, "epochs": 1, "hidden_dim": 16})
        svc = make_service(tmp_path, n_slices=2, max_lanes=2)
        rep = svc.serve(exit_when_drained=True, max_wall_s=300)
        assert rep["settled"][bad] == "rejected_invalid"
        assert rep["settled"][huge] == "rejected_invalid"
        assert rep["settled"][ok] == "completed"

    def test_quota_rejection_journaled(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d, tenant="q")
        ids = [
            c.submit({**BASE, "epochs": 1, "hidden_dim": 16, "seed": i})
            for i in range(3)
        ]
        svc = make_service(
            tmp_path,
            n_slices=2,
            max_lanes=2,
            policies={"q": TenantPolicy(max_pending=2)},
        )
        rep = svc.serve(exit_when_drained=True, max_wall_s=300)
        statuses = sorted(rep["settled"][s] for s in ids)
        assert statuses == ["completed", "completed", "rejected_quota"]

    def test_divergent_trial_settles_diverged(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d)
        sid = c.submit(
            {**BASE, "epochs": 1, "hidden_dim": 16, "lr": 1e18}
        )
        svc = make_service(tmp_path, n_slices=1, max_lanes=1)
        rep = svc.serve(exit_when_drained=True, max_wall_s=300)
        assert rep["settled"][sid] == "diverged"

    def test_restart_recovery_resumes_from_checkpoint(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d)
        ids = [
            c.submit({**BASE, "epochs": 4, "hidden_dim": 16, "seed": i})
            for i in range(4)
        ]
        svc = make_service(tmp_path, n_slices=2, max_lanes=1)
        # "Crash" once a checkpoint exists: no drain, just abandon.
        assert run_until(
            svc,
            lambda: any(
                os.path.exists(
                    os.path.join(d, f"trial-{t}", "state.msgpack")
                )
                for t in range(4)
            ),
        )
        assert not svc.settled or len(svc.settled) < 4
        del svc
        svc2 = make_service(tmp_path, n_slices=2, max_lanes=1)
        assert len(svc2.entries) >= 1  # recovered live submissions
        rep = svc2.serve(exit_when_drained=True, max_wall_s=300)
        assert sorted(rep["settled"]) == sorted(ids)
        assert set(rep["settled"].values()) == {"completed"}
        folded = fold_queue(load_queue(d))
        # At least one trial re-placed with the scan-back resume flag.
        resumed = [
            s for s in ids
            if (folded[s].get("last_placement") or {}).get("resumed")
        ]
        assert resumed
        # Goodput stays honest: useful <= executed.
        tb = rep["books"]["tenants"]["default"]
        assert tb["useful_steps"] <= tb["executed_steps"]

    def test_recovery_never_reuses_assigned_trial_ids(self, tmp_path):
        """Regression: a submission journaled `submitted` but killed
        before its `admitted` record goes through admission on
        restart — its fresh trial id must not collide with ids the
        previous incarnation already assigned."""
        d = str(tmp_path)
        c = SweepClient(d)
        q = SubmissionQueue(d)
        admitted_sid = c.submit({**BASE, "epochs": 1, "hidden_dim": 16})
        pending_sid = c.submit(
            {**BASE, "epochs": 1, "hidden_dim": 24, "seed": 7}
        )
        q.drain_intake(known_ids=set())
        # Previous incarnation admitted ONE (tid 3, a high id), then
        # died before admitting the other.
        q.admitted(admitted_sid, trial_id=3, chash="h3", bucket="b")
        svc = make_service(tmp_path, n_slices=2, max_lanes=1)
        folded = fold_queue(load_queue(d))
        tids = {
            folded[s]["trial_id"] for s in (admitted_sid, pending_sid)
        }
        assert folded[pending_sid]["trial_id"] not in (None, 3)
        assert len(tids) == 2  # no collision
        assert svc.next_trial_id > max(tids)
        rep = svc.serve(exit_when_drained=True, max_wall_s=300)
        assert set(rep["settled"].values()) == {"completed"}

    def test_drain_records_preempted_and_unplaced(self, tmp_path):
        d = str(tmp_path)
        c = SweepClient(d)
        sid = c.submit({**BASE, "epochs": 30, "hidden_dim": 16})
        svc = make_service(tmp_path, n_slices=1, max_lanes=1)
        assert run_until(svc, lambda: bool(svc.active))
        svc.stop()
        rep = svc.serve(exit_when_drained=True, max_wall_s=60)
        assert rep["outcome"] == "preempted"
        from multidisttorch_tpu.hpo.ledger import SweepLedger

        folded = fold_queue(load_queue(d))
        assert folded[sid]["state"] == ADMITTED  # unplaced, not lost
        led_events = [
            e
            for e in SweepLedger(d).load()
            if e.get("event") == "attempt_end"
        ]
        assert led_events and led_events[-1]["status"] == "preempted"
        assert led_events[-1]["tenant"] == "default"

    def test_defrag_unblocks_starved_large_trial(self, tmp_path):
        from multidisttorch_tpu import telemetry

        d = str(tmp_path)
        tel = os.path.join(d, "telemetry")
        c = SweepClient(d, tenant="t")
        with telemetry.telemetry_run(tel):
            svc = make_service(
                tmp_path,
                n_slices=4,
                max_lanes=1,
                starvation_s=0.3,
                defrag_cooldown_s=0.1,
            )
            # Pin the layout: short@0, long@1, short@2, long@3.
            for cfg in (
                {**BASE, "epochs": 1, "hidden_dim": 16},
                {**BASE, "epochs": 40, "hidden_dim": 24},
                {**BASE, "epochs": 1, "hidden_dim": 40},
                {**BASE, "epochs": 40, "hidden_dim": 56},
            ):
                c.submit(cfg)
                assert run_until(
                    svc, lambda: svc.sched.pending_count() == 0
                )
            # Shorts finish -> non-adjacent holes; big starves.
            assert run_until(
                svc,
                lambda: sum(
                    1 for s in svc.settled.values() if s == "completed"
                ) >= 2,
            )
            assert svc.pool.largest_free_run() < 2 <= svc.pool.free_total
            big = c.submit(
                {**BASE, "epochs": 1, "hidden_dim": 16, "seed": 9},
                size=2,
            )
            assert run_until(
                svc, lambda: svc.settled.get(big) == "completed"
            )
            # Migrated victims still finish (scan-back restore worked).
            assert run_until(svc, lambda: len(svc.settled) == 5, 300)
            assert set(svc.settled.values()) == {"completed"}
            svc._drain(reason="test end")
            events = telemetry.read_events(
                os.path.join(tel, "events.jsonl")
            )
        kinds = [e["kind"] for e in events]
        assert "defrag_start" in kinds
        assert "defrag_move" in kinds
        assert "defrag_end" in kinds
        assert "trial_migrated" in kinds
        end = next(e for e in events if e["kind"] == "defrag_end")
        assert end["data"]["freed_contiguous"] >= 2
        placed_big = [
            e
            for e in events
            if e["kind"] == "trial_placed"
            and (e.get("data") or {}).get("sub_id") == big
        ]
        assert placed_big and placed_big[-1]["ts"] >= end["ts"]

    def test_defrag_waits_for_unflushed_checkpoint(self, tmp_path):
        """Invariant at the RUNTIME level: a placement whose
        checkpoint write is in flight reports unmovable, so the
        planner cannot choose it."""
        import threading

        from multidisttorch_tpu.service.runtime import _Active

        class FakeRun:
            def __init__(self):
                self._ckpt_thread = threading.Thread(
                    target=time.sleep, args=(30,), daemon=True
                )
                self._step_no = 8

                class R:
                    checkpoint = "/some/ckpt"

                self.result = R()

        ap = _Active(
            placement_id=0, start=0, size=1, stacked=False,
            run=FakeRun(), gen=None, entries={}, place_ts=0.0,
            construct_s=0.0,
        )
        ap.run._ckpt_thread.start()
        assert not ap.movable()  # write in flight
        ap.run._ckpt_thread.join(timeout=0.01)
        ap.run._ckpt_thread = None
        assert ap.movable()  # flushed
        ap.run.result.checkpoint = ""
        assert not ap.movable()  # progress but nothing durable
        ap.run._step_no = 0
        assert ap.movable()  # nothing to lose
        # Stacked placements are movable now: the bucket drain
        # snapshots every live lane at its epoch boundary itself, so
        # only an in-flight lane persist defers them — and only under
        # the legacy join-drain (the snapshot drain adopts the write).
        ap.stacked = True
        assert ap.movable()
        ap.run._ckpt_thread = threading.Thread(
            target=time.sleep, args=(30,), daemon=True
        )
        ap.run._ckpt_thread.start()
        assert not ap.movable()  # legacy join-drain defers
        assert ap.movable(snapshot_drain=True)  # adopted in-flight write


# --------------------------------------------------------------------
# tools
# --------------------------------------------------------------------


class TestTools:
    def _seed_queue(self, d):
        c = SweepClient(str(d), tenant="alice")
        sid = c.submit({"epochs": 1, "hidden_dim": 16})
        q = SubmissionQueue(str(d))
        q.drain_intake(known_ids=set())
        q.admitted(sid, trial_id=0, chash="h", bucket="(32, 16)")
        return sid

    def test_ledger_view_queue_render_and_json(self, tmp_path, capsys):
        sys.path.insert(
            0,
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
            ),
        )
        import ledger_view

        sid = self._seed_queue(tmp_path)
        assert ledger_view.main([str(tmp_path), "--queue"]) == 0
        out = capsys.readouterr().out
        assert sid[:24] in out and "alice" in out and "admitted" in out
        assert ledger_view.main([str(tmp_path), "--queue", "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["by_submission"][sid]["state"] == "admitted"

    def test_sweep_top_service_panel(self, tmp_path, capsys):
        import importlib

        sys.path.insert(
            0,
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
            ),
        )
        sweep_top = importlib.import_module("sweep_top")
        sid = self._seed_queue(tmp_path)
        with open(tmp_path / "service_books.json", "w") as f:
            json.dump(
                {
                    "tenants": {
                        "alice": {"useful_steps": 4, "goodput": 1.0}
                    },
                    "fair_share": {
                        "alice": {
                            "weight": 2.0,
                            "contended_share": 0.5,
                            "ratio_to_weight": 1.0,
                        }
                    },
                    "queue_wait": {"count": 1, "p50_s": 0.5,
                                   "p99_s": 1.0, "max_s": 0.7},
                    "placement_latency": {"count": 1, "p50_s": 1.0,
                                          "p99_s": 2.0, "max_s": 1.5},
                    "fragmentation": {"now": 0.25, "max": 0.5,
                                      "free_slices": 2,
                                      "largest_free_run": 1},
                    "defrag": {"events": 1, "moved_slices": 1,
                               "unblocked": ["x"]},
                },
                f,
            )
        assert sweep_top.main([str(tmp_path), "--service"]) == 0
        out = capsys.readouterr().out
        assert "alice" in out and "queue-wait" in out
        assert "defrag" in out and "fragmentation" in out
        assert sweep_top.main([str(tmp_path), "--service", "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["queue"][sid]["tenant"] == "alice"
        assert payload["books"]["defrag"]["events"] == 1

    def test_sweep_submit_cli(self, tmp_path, capsys):
        sys.path.insert(
            0,
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
            ),
        )
        import sweep_submit

        rc = sweep_submit.main(
            [
                str(tmp_path), "--tenant", "cli", "--priority", "0",
                "--epochs", "2", "--hidden-dim", "32", "--count", "2",
                "--json",
            ]
        )
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        ids = payload["submitted"]
        assert len(ids) == 2 and all(s.startswith("cli-") for s in ids)
        q = SubmissionQueue(str(tmp_path))
        fresh = q.drain_intake(known_ids=set())
        assert len(fresh) == 2
        assert {s.config["seed"] for s in fresh} == {0, 1}
        assert all(s.priority == 0 for s in fresh)
        assert all(s.config["hidden_dim"] == 32 for s in fresh)
