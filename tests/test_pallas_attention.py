"""Flash (blockwise Pallas) attention: value + gradient parity with the
dense reference (interpreter mode on CPU; same kernels compile on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multidisttorch_tpu.ops.pallas_attention import (
    _BLOCK,
    flash_attention,
    make_flash_attention,
)
from multidisttorch_tpu.ops.ring_attention import dense_attention_reference


def _qkv(b=2, t=64, h=2, d=16, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, t, h, d)).astype(dtype))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_value_parity_single_block(causal):
    q, k, v = _qkv(t=64)  # t < _BLOCK: one whole-sequence block
    out = flash_attention(q, k, v, causal=causal)
    ref = dense_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("causal", [False, True])
def test_value_parity_multi_block(causal):
    # t = 2 * _BLOCK exercises the online-softmax carry across K blocks
    # and (causal) the skipped above-diagonal block.
    q, k, v = _qkv(t=2 * _BLOCK, h=1, d=8)
    out = flash_attention(q, k, v, causal=causal)
    ref = dense_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("causal", [False, True])
def test_gradient_parity(causal):
    q, k, v = _qkv(t=2 * _BLOCK, h=1, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(
            dense_attention_reference(q, k, v, causal=causal) ** 2
        )

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-6
        )


def test_odd_head_dim_and_seq():
    # Head dims off the VPU lane width (20) and non-128-divisible
    # sequences (96 -> one whole-sequence block) must still be exact.
    q, k, v = _qkv(b=1, t=96, h=2, d=20, seed=9)
    out = flash_attention(q, k, v, causal=True)
    ref = dense_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )
    g = jax.grad(
        lambda q: jnp.sum(flash_attention(q, k, v, causal=True) ** 2)
    )(q)
    g_ref = jax.grad(
        lambda q: jnp.sum(
            dense_attention_reference(q, k, v, causal=True) ** 2
        )
    )(q)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=5e-5, atol=5e-6
    )


def test_large_nondivisible_causal_pads_exactly(monkeypatch):
    # A non-128-divisible T above the whole-block threshold must take
    # the pad-to-tile-edge path and stay exact, values AND gradients
    # (padded keys are causally unreachable; sliced rows carry zero
    # cotangent). Shrink the threshold so T=200 exercises it cheaply.
    import multidisttorch_tpu.ops.pallas_attention as pa

    monkeypatch.setattr(pa, "_MAX_WHOLE_BLOCK", 64)
    q, k, v = _qkv(b=1, t=200, h=1, d=8, seed=3)
    out = flash_attention(q, k, v, causal=True)
    assert out.shape == q.shape
    ref = dense_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )
    loss = lambda fn: lambda q, k, v: jnp.sum(fn(q, k, v, causal=True) ** 2)
    g = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(dense_attention_reference), argnums=(0, 1, 2))(
        q, k, v
    )
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-6
        )


def test_large_nondivisible_noncausal_raises(monkeypatch):
    # Non-causal can't be padded exactly (appended keys WOULD be
    # attended); the documented contract is a clear error instead of a
    # VMEM blowup at Mosaic compile time (ADVICE r4).
    import multidisttorch_tpu.ops.pallas_attention as pa

    monkeypatch.setattr(pa, "_MAX_WHOLE_BLOCK", 64)
    q, k, v = _qkv(b=1, t=200, h=1, d=8)
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, k, v, causal=False)


def test_bf16_roundtrip():
    q, k, v = _qkv(t=64, dtype=np.float32)
    qb, kb, vb = (a.astype(jnp.bfloat16) for a in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref),
        rtol=3e-2, atol=3e-2,  # bf16 storage precision
    )
    # gradients flow and come back in the primal dtype
    g = jax.grad(
        lambda q: jnp.sum(
            flash_attention(q, kb, vb, causal=True).astype(jnp.float32) ** 2
        )
    )(qb)
    assert g.dtype == jnp.bfloat16 and bool(jnp.isfinite(g.astype(jnp.float32)).all())


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(causal):
    # The full composition: K/V ring over 8 devices, Pallas flash
    # kernel inside each hop, logsumexp combination across hops.
    from multidisttorch_tpu.ops.pallas_attention import (
        make_ring_flash_attention,
    )
    from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups

    (trial,) = setup_groups(1)
    t = 16 * trial.size
    q, k, v = _qkv(b=2, t=t, h=2, d=8, seed=3)
    q, k, v = (
        jax.device_put(a, trial.sharding(None, DATA_AXIS))
        for a in (q, k, v)
    )
    out = make_ring_flash_attention(trial, causal=causal)(q, k, v)
    ref = dense_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
    )


def test_ring_flash_gradient_matches_dense():
    # Gradients flow through the hop combination into the kernel's VJP
    # — including the lse cotangent (the hop-weight term), which only
    # this path exercises.
    from multidisttorch_tpu.ops.pallas_attention import (
        make_ring_flash_attention,
    )
    from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups

    (trial,) = setup_groups(1)
    t = 16 * trial.size
    q, k, v = _qkv(b=1, t=t, h=1, d=8, seed=4)
    sh = trial.sharding(None, DATA_AXIS)
    qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
    ring = make_ring_flash_attention(trial, causal=True)

    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(ring(q, k, v) ** 2), argnums=(0, 1, 2)
    )(qs, ks, vs)
    g_dense = jax.grad(
        lambda q, k, v: jnp.sum(
            dense_attention_reference(q, k, v, causal=True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )


def test_ring_flash_2d_sequence_x_head_parallel():
    # (data x model) mesh: flash-kernel hops with heads sharded over
    # the model axis — kernel grid rows shrink to BH/m per device.
    from multidisttorch_tpu.ops.pallas_attention import (
        make_ring_flash_attention,
    )
    from multidisttorch_tpu.parallel.mesh import setup_groups

    (trial,) = setup_groups(1, model_parallel=2)
    q, k, v = _qkv(b=2, t=16, h=4, d=8, seed=11)
    ring = make_ring_flash_attention(trial, causal=True)
    assert ring.head_sharded
    out = ring(q, k, v)
    ref = dense_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
    )
    g = jax.grad(lambda q: jnp.sum(ring(q, k, v) ** 2))(q)
    g_ref = jax.grad(
        lambda q: jnp.sum(
            dense_attention_reference(q, k, v, causal=True) ** 2
        )
    )(q)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=5e-4, atol=5e-5
    )


def test_ring_flash_drives_sequence_parallel_lm():
    # End to end: the TransformerLM trains sequence-parallel with
    # ring-flash as its attention — loss decreases over steps.
    import optax

    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.ops.pallas_attention import (
        make_ring_flash_attention,
    )
    from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups
    from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step

    (trial,) = setup_groups(1)
    t = 8 * trial.size
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=2, num_layers=1, max_len=t,
        attention=make_ring_flash_attention(trial, causal=True),
    )
    tx = optax.adam(3e-3)
    state = create_lm_state(trial, model, tx, jax.random.key(0),
                            example_len=t)
    step = make_lm_train_step(trial, model, tx, sequence_parallel=True)
    tokens = jax.device_put(
        jnp.asarray(
            np.tile(np.arange(t) % 32, (2, 1)).astype(np.int32)
        ),
        trial.sharding(None, DATA_AXIS),
    )
    state, m0 = step(state, tokens)
    for _ in range(10):
        state, m = step(state, tokens)
    assert float(m["loss"]) < float(m0["loss"])


def test_drives_transformer_lm():
    # The kernel is the TransformerLM's single-chip attention: one real
    # optimizer step decreases the loss and matches the dense-attention
    # model's loss on identical params.
    import optax

    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step

    (trial,) = setup_groups(1)
    mk = lambda attn: TransformerLM(
        vocab_size=64, d_model=32, num_heads=2, num_layers=2,
        max_len=64, attention=attn,
    )
    flash_model = mk(make_flash_attention(causal=True))
    dense_model = mk(None)
    tx = optax.adam(1e-3)
    state = create_lm_state(trial, flash_model, tx, jax.random.key(0),
                            example_len=64)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (8, 64), dtype=np.int32)
    )  # batch divisible by the trial's 8-device data axis

    step_flash = make_lm_train_step(trial, flash_model, tx)
    s1, m1 = step_flash(state, tokens)
    # identical params through the dense model -> same loss
    state_d = create_lm_state(trial, dense_model, tx, jax.random.key(0),
                              example_len=64)
    _, m2 = make_lm_train_step(trial, dense_model, tx)(state_d, tokens)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-4)
    # training continues and improves
    s2, m3 = step_flash(s1, tokens)
    assert float(m3["loss"]) < float(m1["loss"])
