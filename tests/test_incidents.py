"""Incident-plane drills: the root-cause detector's closed taxonomy,
correlation (dedup / flap-reopen / rank escalation), the torn-tail-
tolerant durable ledger, SIGKILL-mid-dump bundle quarantine, the
always-on flight ring's zero-cost-off contract, the offline causal
autopsy, and the console/CLI surfaces (docs/INCIDENTS.md)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from multidisttorch_tpu import telemetry
from multidisttorch_tpu.telemetry import incident as tincident
from multidisttorch_tpu.telemetry.events import get_bus
from multidisttorch_tpu.telemetry.incident import (
    BACKEND_WEDGED,
    CKPT_INTEGRITY,
    DIVERGENCE_STORM,
    FENCE_LOST,
    HOST_PREEMPTED,
    KINDS,
    REPLICA_LOST,
    SLO_BURN,
    SPLIT_TORN,
    STEAL_ANOMALY,
    WEDGED_COLLECTIVE,
    IncidentDetector,
    detect_incidents,
    fold_incidents,
    load_incidents,
    read_incident_records,
    sweep_partial_bundles,
)

pytestmark = pytest.mark.incidents


def _ev(kind, ts=1000.0, trial_id=None, **data):
    ev = {"kind": kind, "ts": ts}
    if trial_id is not None:
        ev["trial_id"] = trial_id
    if data:
        ev["data"] = data
    return ev


# -- taxonomy / classification rules ----------------------------------


def test_taxonomy_is_closed_and_complete():
    assert len(KINDS) == 10
    assert len(set(KINDS)) == 10


@pytest.mark.parametrize(
    "ev,kind,subject",
    [
        (
            _ev("shard_fence_lost", shard=2, replica=0, reason="outbid"),
            FENCE_LOST, "shard:2",
        ),
        (
            _ev("shard_adopted", shard=1, replica=3, epoch=2),
            REPLICA_LOST, "shard:1",
        ),
        (
            _ev("host_lost", slot=4, stale_s=2.5, world_epoch=1),
            REPLICA_LOST, "host:4",
        ),
        (
            _ev("shard_split_resolved", shard=0, child=2, replica=1,
                action="abort"),
            SPLIT_TORN, "shard:0",
        ),
        (
            _ev("failure_classified", trial_id=7,
                failure_class="preemption", exc_type="WedgedCollective",
                error="wedged"),
            WEDGED_COLLECTIVE, "trial:7",
        ),
        (
            _ev("failure_classified", trial_id=5,
                failure_class="preemption", exc_type="HostPreemption",
                error="preempted"),
            HOST_PREEMPTED, "trial:5",
        ),
        (
            _ev("preflight_verdict", platform="tpu",
                verdict="wedged_init_timeout", reason="deadline",
                usable=False, elapsed_s=30.0),
            BACKEND_WEDGED, "backend:tpu",
        ),
        (
            _ev("slo_alert", slo="queue_wait_p95_60s", label=None,
                state="firing", burn=4.0, compliance=0.5),
            SLO_BURN, "slo:queue_wait_p95_60s:None",
        ),
        (
            _ev("ckpt_scan_reject", path="/runs/t0/ckpt.msgpack",
                reason="crc mismatch"),
            CKPT_INTEGRITY, "ckpt:/runs/t0",
        ),
    ],
)
def test_single_event_rules(ev, kind, subject):
    folded = detect_incidents([ev])
    assert len(folded) == 1
    (inc,) = folded.values()
    assert inc["kind"] == kind
    assert inc["subject"] == subject


def test_first_claim_is_not_an_incident():
    folded = detect_incidents(
        [_ev("shard_adopted", shard=0, replica=0, epoch=1)]
    )
    assert folded == {}


def test_usable_preflight_is_not_an_incident():
    folded = detect_incidents(
        [
            _ev("preflight_verdict", platform="cpu", verdict="healthy",
                usable=True, elapsed_s=1.0)
        ]
    )
    assert folded == {}


def test_divergence_storm_needs_distinct_trials_in_window():
    def diverge(tid, ts):
        return _ev(
            "failure_classified", ts=ts, trial_id=tid,
            failure_class="divergence", exc_type="DivergenceError",
            error="nan",
        )

    # Same trial three times: attrition, not a storm.
    assert detect_incidents(
        [diverge(0, 1000.0 + i) for i in range(3)]
    ) == {}
    # Three distinct trials inside the window: one storm incident.
    folded = detect_incidents(
        [diverge(t, 1000.0 + t) for t in range(3)]
    )
    assert len(folded) == 1
    (inc,) = folded.values()
    assert inc["kind"] == DIVERGENCE_STORM
    assert inc["subject"] == "sweep"
    # Spread past the window: never accumulates.
    assert detect_incidents(
        [diverge(t, 1000.0 + 500.0 * t) for t in range(3)],
        storm_window_s=120.0,
    ) == {}


def test_steal_anomaly_duplicate_grant_and_ungranted_execute():
    dup = detect_incidents(
        [
            _ev("steal_grant", ts=1.0, victim_shard=0, thief_shard=1,
                seq=7, n=2),
            _ev("steal_grant", ts=2.0, victim_shard=0, thief_shard=1,
                seq=7, n=2),
        ]
    )
    assert [i["kind"] for i in dup.values()] == [STEAL_ANOMALY]
    (inc,) = dup.values()
    assert inc["detail"]["why"] == "duplicate_grant"

    ungranted = detect_incidents(
        [
            _ev("steal_executed", ts=1.0, victim_shard=3, thief_shard=4,
                sub_ids=["s-1"]),
        ]
    )
    (inc,) = ungranted.values()
    assert inc["kind"] == STEAL_ANOMALY
    assert inc["detail"]["why"] == "executed_without_grant"

    # The healthy protocol — grant then execute — is silent.
    assert detect_incidents(
        [
            _ev("steal_grant", ts=1.0, victim_shard=0, thief_shard=1,
                seq=1, n=1),
            _ev("steal_executed", ts=2.0, victim_shard=0, thief_shard=1,
                sub_ids=["s-1"]),
        ]
    ) == {}


# -- correlation: dedup, escalation, flap reopen ----------------------


def test_takeover_chain_is_one_incident(tmp_path):
    """The fence-loss + adoption echo of ONE takeover lands in one
    incident, and the torn-split resolution ESCALATES it in place."""
    det = IncidentDetector(str(tmp_path), emit_events=False)
    det.observe(_ev("shard_fence_lost", ts=1.0, shard=0, replica=0,
                    reason="lease expired"))
    det.observe(_ev("shard_adopted", ts=2.0, shard=0, replica=1,
                    epoch=2))
    det.observe(_ev("shard_split_resolved", ts=3.0, shard=0, child=2,
                    replica=1, action="abort"))
    assert det.opened == 1
    (inc,) = det.open_incidents()
    assert inc.kind == SPLIT_TORN  # escalated from fence_lost
    assert inc.count == 3
    # Durable history: open + escalate, folded back to the same state.
    folded = load_incidents(str(tmp_path))
    assert folded[inc.id]["kind"] == SPLIT_TORN
    assert folded[inc.id]["count"] == 3
    recs, torn = read_incident_records(
        os.path.join(str(tmp_path), tincident.INCIDENTS_NAME)
    )
    assert not torn
    assert [r["rec"] for r in recs] == ["open", "escalate"]


def test_lower_rank_absorbs_without_escalation(tmp_path):
    det = IncidentDetector(str(tmp_path), emit_events=False)
    det.observe(_ev("shard_fence_lost", ts=1.0, shard=0, replica=0,
                    reason="outbid"))
    det.observe(_ev("shard_adopted", ts=2.0, shard=0, replica=1,
                    epoch=2))
    (inc,) = det.open_incidents()
    assert inc.kind == FENCE_LOST  # replica_lost ranks below
    assert inc.count == 2


def test_flapping_lease_reopens_one_incident(tmp_path):
    """resolve -> re-fire inside flap_window_s reopens the SAME id
    (flaps++) instead of minting a ledger flood."""
    det = IncidentDetector(
        str(tmp_path), emit_events=False, flap_window_s=60.0
    )
    t = 1000.0
    first = det.observe(
        _ev("shard_fence_lost", ts=t, shard=0, replica=0, reason="flap")
    )
    for i in range(1, 4):
        det.resolve_subject("shard:0", ts=t + 10.0 * i,
                            reason="lease re-won")
        again = det.observe(
            _ev("shard_fence_lost", ts=t + 10.0 * i + 5.0, shard=0,
                replica=0, reason="flap")
        )
        assert again.id == first.id
        assert again.flaps == i
    assert det.opened == 1
    folded = load_incidents(str(tmp_path))
    assert list(folded) == [first.id]
    assert folded[first.id]["flaps"] == 3
    assert folded[first.id]["status"] == "open"
    # Past the flap window a fresh fire is a NEW incident.
    det.resolve_subject("shard:0", ts=t + 100.0, reason="stable")
    fresh = det.observe(
        _ev("shard_fence_lost", ts=t + 500.0, shard=0, replica=0,
            reason="new fault")
    )
    assert fresh.id != first.id


def test_slo_resolve_event_resolves_subject(tmp_path):
    det = IncidentDetector(str(tmp_path), emit_events=False)
    det.observe(_ev("slo_alert", ts=1.0, slo="q", label=None,
                    state="firing", burn=5.0))
    assert len(det.open_incidents()) == 1
    det.observe(_ev("slo_alert", ts=2.0, slo="q", label=None,
                    state="resolved", burn=0.1))
    assert det.open_incidents() == []
    folded = load_incidents(str(tmp_path))
    (inc,) = folded.values()
    assert inc["status"] == "resolved"


def test_quiet_resolve_auto_closes(tmp_path):
    det = IncidentDetector(
        str(tmp_path), emit_events=False, quiet_resolve_s=30.0
    )
    det.observe(_ev("shard_fence_lost", ts=1000.0, shard=0, replica=0,
                    reason="outbid"))
    # Any later observation past the quiet window sweeps the stale one.
    det.observe(_ev("epoch", ts=1100.0))
    assert det.open_incidents() == []


# -- durable ledger ---------------------------------------------------


def test_torn_tail_replay_and_heal(tmp_path):
    d = str(tmp_path)
    det = IncidentDetector(d, emit_events=False)
    det.observe(_ev("shard_fence_lost", ts=1.0, shard=0, replica=0,
                    reason="outbid"))
    det.observe(_ev("ckpt_scan_reject", ts=2.0, path="/r/t0/c.msgpack",
                    reason="crc"))
    path = os.path.join(d, tincident.INCIDENTS_NAME)
    with open(path, "a") as f:
        f.write('{"rec": "open", "id": "inc-9999", "kind": "tru')
    # Reader: torn tail detected, whole lines intact.
    recs, torn = read_incident_records(path)
    assert torn
    assert len(recs) == 2
    assert "inc-9999" not in fold_incidents(recs)
    # A new session over the torn ledger heals the tail, resumes the
    # id sequence past every banked id, and appends cleanly.
    det2 = IncidentDetector(d, emit_events=False)
    assert det2.tail_repaired
    inc = det2.observe(
        _ev("host_lost", ts=3.0, slot=1, stale_s=9.0, world_epoch=0)
    )
    assert int(inc.id.split("-")[1]) > 2
    # The repair newline-terminates the garbage (it stays countable as
    # exactly one torn line) so the new append is a FRESH whole line.
    recs2, torn2 = read_incident_records(path)
    assert torn2 == 1
    assert [r["rec"] for r in recs2] == ["open", "open", "open"]


def test_counts_flushed_on_resolve(tmp_path):
    """Absorbs are memory-only (per-absorb appends would defeat the
    flood protection); the resolve record flushes the final count."""
    d = str(tmp_path)
    det = IncidentDetector(d, emit_events=False)
    for i in range(5):
        det.observe(
            _ev("shard_fence_lost", ts=1.0 + i, shard=0, replica=0,
                reason="outbid")
        )
    assert load_incidents(d)[det.open_incidents()[0].id]["count"] == 1
    det.resolve_subject("shard:0", ts=10.0, reason="done")
    (inc,) = load_incidents(d).values()
    assert inc["count"] == 5
    assert inc["status"] == "resolved"


def test_id_sequence_never_recycled_across_sessions(tmp_path):
    d = str(tmp_path)
    det = IncidentDetector(d, emit_events=False)
    a = det.observe(_ev("shard_fence_lost", ts=1.0, shard=0, replica=0,
                        reason="x"))
    det2 = IncidentDetector(d, emit_events=False)
    b = det2.observe(_ev("shard_fence_lost", ts=2.0, shard=1, replica=0,
                         reason="x"))
    assert b.id != a.id
    assert int(b.id.split("-")[1]) == int(a.id.split("-")[1]) + 1


# -- bundles ----------------------------------------------------------


def test_bundle_published_atomically(tmp_path):
    d = str(tmp_path)
    ring = tincident.FlightRing(maxlen=8)
    for i in range(20):
        ring.note({"kind": "epoch", "ts": float(i)})
    det = IncidentDetector(d, emit_events=False, ring=ring)
    inc = det.observe(
        _ev("shard_fence_lost", ts=30.0, shard=0, replica=0,
            reason="outbid")
    )
    bdir = os.path.join(d, tincident.BUNDLE_DIRNAME, inc.id)
    assert os.path.isdir(bdir)
    assert not os.path.isdir(bdir + ".partial")
    with open(os.path.join(bdir, "flight_ring.json")) as f:
        dump = json.load(f)
    # Bounded black box: the ring held only the newest maxlen events
    # but counted everything it saw.
    assert len(dump["events"]) == 8
    assert dump["noted"] == 20
    with open(os.path.join(bdir, "trigger.json")) as f:
        trig = json.load(f)
    assert trig["incident"]["id"] == inc.id
    assert trig["trigger_event"]["kind"] == "shard_fence_lost"


def test_sigkill_mid_dump_leaves_valid_ledger_and_quarantines(tmp_path):
    """The black-box crash drill: a child stalls inside the bundle
    dump (MDT_INCIDENT_DUMP_STALL) and is SIGKILLed before the
    publish rename. The ledger must already hold the fsync'd open
    record; the bundle must be a ``.partial`` dir that the sweep
    renames to ``.quarantined`` — never a half-bundle that looks
    whole."""
    d = str(tmp_path / "scope")
    child = textwrap.dedent(
        f"""
        import sys
        sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})
        from multidisttorch_tpu.telemetry.incident import (
            FlightRing, IncidentDetector,
        )
        ring = FlightRing(maxlen=8)
        ring.note({{"kind": "epoch", "ts": 0.5}})
        det = IncidentDetector({d!r}, emit_events=False, ring=ring)
        det.observe({{"kind": "shard_fence_lost", "ts": 1.0,
                      "data": {{"shard": 0, "replica": 0,
                                "reason": "outbid"}}}})
        print("UNREACHABLE", flush=True)
        """
    )
    env = dict(os.environ, MDT_INCIDENT_DUMP_STALL="60")
    proc = subprocess.Popen(
        [sys.executable, "-c", child], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        bundles = os.path.join(d, tincident.BUNDLE_DIRNAME)
        deadline = time.monotonic() + 30.0
        part = None
        while time.monotonic() < deadline:
            if os.path.isdir(bundles):
                parts = [
                    n for n in os.listdir(bundles)
                    if n.endswith(".partial")
                ]
                if parts and os.path.exists(
                    os.path.join(bundles, parts[0], "flight_ring.json")
                ):
                    part = parts[0]
                    break
            time.sleep(0.02)
        assert part is not None, "child never reached the dump stall"
        proc.send_signal(signal.SIGKILL)
    finally:
        proc.kill()
        proc.wait(timeout=30)
    # Ledger: whole, already holding the open record.
    recs, torn = read_incident_records(
        os.path.join(d, tincident.INCIDENTS_NAME)
    )
    assert not torn
    assert [r["rec"] for r in recs] == ["open"]
    # Bundle: still partial; the sweep quarantines it.
    iid = part[: -len(".partial")]
    assert not os.path.isdir(os.path.join(bundles, iid))
    swept = sweep_partial_bundles(d)
    assert len(swept) == 1
    assert swept[0].endswith(".quarantined")
    assert not os.path.isdir(os.path.join(bundles, part))
    # Re-arming over the crash scene replays the incident as open.
    folded = load_incidents(d)
    assert folded[iid]["status"] == "open"


# -- flight ring + zero-cost-off --------------------------------------


def test_ring_is_bounded_and_counts_drops():
    ring = tincident.FlightRing(maxlen=4)
    for i in range(10):
        ring.note({"i": i})
    snap = ring.snapshot()
    assert len(snap) == 4
    assert [r["i"] for r in snap] == [6, 7, 8, 9]
    assert ring.noted == 10


def test_zero_cost_when_off(monkeypatch):
    """Telemetry OFF: no ring, no detector, and the incident module's
    clock is never read on any production seam."""
    assert not telemetry.enabled()
    assert telemetry.get_flight_ring() is None
    assert telemetry.get_incident_detector() is None

    def _boom():
        raise AssertionError("incident clock read while telemetry off")

    monkeypatch.setattr(tincident, "_clock", _boom)
    from multidisttorch_tpu.hpo.supervision import classify_failure
    from multidisttorch_tpu.train.guards import DivergenceError

    exc = DivergenceError("epoch_loss", float("nan"))
    assert classify_failure(exc) == "divergence"


def test_telemetry_scope_arms_and_disarms_incident_plane(tmp_path):
    d = str(tmp_path)
    with telemetry.telemetry_run(d):
        assert telemetry.get_flight_ring() is not None
        det = telemetry.get_incident_detector()
        assert det is not None
        bus = get_bus()
        bus.emit("shard_fence_lost", shard=0, replica=0, reason="outbid")
        # The tap fed the ring and the detector through the same emit.
        assert telemetry.get_flight_ring().noted >= 1
        assert len(det.open_incidents()) == 1
        # The detector's own incident event must not re-trigger it.
        kinds = [e.kind for e in bus.recent()]
        assert "incident" in kinds
        assert det.opened == 1
    assert telemetry.get_flight_ring() is None
    assert telemetry.get_incident_detector() is None
    assert os.path.exists(os.path.join(d, tincident.INCIDENTS_NAME))


def test_offline_replay_matches_live_fold(tmp_path):
    d = str(tmp_path)
    events = [
        _ev("shard_fence_lost", ts=1.0, shard=0, replica=0,
            reason="outbid"),
        _ev("shard_adopted", ts=2.0, shard=0, replica=1, epoch=2),
        _ev("ckpt_scan_reject", ts=3.0, path="/r/t1/c.msgpack",
            reason="crc"),
    ]
    det = IncidentDetector(d, emit_events=False)
    for ev in events:
        det.observe(ev)
    # Compare (kind, subject): counts differ by design — the live
    # ledger flushes absorbed-echo counts only on escalate/resolve.
    live = {
        (i["kind"], i["subject"]) for i in load_incidents(d).values()
    }
    offline = {
        (i["kind"], i["subject"])
        for i in detect_incidents(events).values()
    }
    assert live == offline


# -- causal autopsy ---------------------------------------------------


def test_autopsy_report_and_exports(tmp_path):
    d = str(tmp_path)
    with telemetry.telemetry_run(d):
        bus = get_bus()
        bus.emit("shard_fence_lost", shard=0, replica=0,
                 reason="lease expired")
        bus.emit("shard_adopted", shard=0, replica=1, epoch=2,
                 replayed_submissions=3)
    folded = load_incidents(d)
    (iid,) = folded
    report = tincident.build_incident_report(d, iid)
    assert report["verdict"] == FENCE_LOST
    assert report["incident"]["id"] == iid
    # The event stream next to the ledger is a cited surface, and the
    # causal chain includes both halves of the takeover.
    assert "events" in report["corroborating_surfaces"]
    cited = [
        r["rec"].get("kind")
        for r in report["timeline"]
        if r["source"] == "events"
    ]
    assert "shard_fence_lost" in cited
    assert "shard_adopted" in cited
    out = report["bundle_dir"]
    for name in ("report.json", "perfetto.json", "affected_traces.json"):
        assert os.path.isfile(os.path.join(out, name))
    with open(os.path.join(out, "perfetto.json")) as f:
        perf = json.load(f)
    assert any(e.get("ph") == "X" for e in perf["traceEvents"])
    # Unknown id: loud, with the known ids in the message.
    with pytest.raises(KeyError):
        tincident.build_incident_report(d, "inc-nope")


# -- slo_alert exemplar satellite -------------------------------------


def test_slo_alert_exemplar_present_and_byte_compat(tmp_path):
    from multidisttorch_tpu.telemetry.metrics import Histogram
    from multidisttorch_tpu.telemetry.slo import LATENCY, SloEngine, SloSpec

    def spec():
        return SloSpec(
            name="q", kind=LATENCY, source="queue_wait",
            threshold_s=0.1, objective=0.9, windows=((5.0, 1.0),),
        )

    def burn(eng):
        t = 1000.0
        for i in range(20):
            eng.observe_latency("queue_wait", 3.0, ts=t + i * 0.1)
        eng.evaluate(now=t + 2.5)

    d0 = str(tmp_path / "bare")
    with telemetry.telemetry_run(d0):
        burn(SloEngine((spec(),)))
    d1 = str(tmp_path / "exemplar")
    with telemetry.telemetry_run(d1):
        eng = SloEngine((spec(),))
        hist = Histogram((0.1, 1.0, 10.0))
        for i in range(20):
            hist.observe(3.0, exemplar=f"sub-{i:03d}")
        eng.attach_exemplar("queue_wait", hist)
        burn(eng)

    def alert(d):
        evs = telemetry.read_events(os.path.join(d, "events.jsonl"))
        return next(e for e in evs if e["kind"] == "slo_alert")

    bare, rich = alert(d0), alert(d1)
    # Nothing attached => the field is NEVER serialized (byte-compat
    # with pre-exemplar streams).
    assert "exemplar" not in bare["data"]
    ex = rich["data"]["exemplar"]
    assert ex["id"].startswith("sub-")
    assert ex["value_s"] == pytest.approx(3.0)
    # And the incident carries the citation into its detail.
    (inc,) = load_incidents(d1).values()
    assert inc["kind"] == SLO_BURN
    assert inc["detail"]["exemplar"]["id"] == ex["id"]
    (inc0,) = load_incidents(d0).values()
    assert "exemplar" not in inc0["detail"]


# -- console + CLI ----------------------------------------------------


def _scripted_service_dir(tmp_path) -> str:
    d = str(tmp_path / "svc")
    with telemetry.telemetry_run(os.path.join(d, "telemetry")):
        bus = get_bus()
        bus.emit("shard_fence_lost", shard=0, replica=1, reason="outbid")
        bus.emit(
            "failure_classified", trial_id=4,
            failure_class="preemption", exc_type="HostPreemption",
            error="gone",
        )
    return d


def test_sweep_top_incidents_panel_and_json(tmp_path, capsys):
    import importlib

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        ),
    )
    sweep_top = importlib.import_module("sweep_top")

    d = _scripted_service_dir(tmp_path)
    follow = sweep_top.ServiceFollow(d)
    _q, _b, _s, incidents = follow.refresh()
    assert len(incidents) == 2
    panel = sweep_top.render_incidents_panel(incidents)
    assert "open 2" in panel
    assert "fence_lost" in panel and "host_preempted" in panel
    assert "trial:4" in panel

    # Incremental: an operator resolve appended after the first fold
    # lands on the next refresh without re-reading history.
    iid = next(
        i for i, v in incidents.items() if v["kind"] == FENCE_LOST
    )
    tincident._fsync_append(
        os.path.join(d, "telemetry", tincident.INCIDENTS_NAME),
        {"rec": "resolve", "id": iid, "ts": time.time(),
         "reason": "mitigated", "count": 1, "flaps": 0},
    )
    offset_before = follow.ioffset
    _q, _b, _s, incidents = follow.refresh()
    assert follow.ioffset > offset_before
    assert incidents[iid]["status"] == "resolved"
    assert "resolved 1" in sweep_top.render_incidents_panel(incidents)

    # --json --service carries the incidents block.
    rc = sweep_top.main([d, "--service", "--json"])
    assert rc == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["incidents"][iid]["status"] == "resolved"


def test_incident_cli_list_show_report_resolve_sweep(tmp_path, capsys):
    import importlib

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        ),
    )
    cli = importlib.import_module("incident")

    d = _scripted_service_dir(tmp_path)
    assert cli.main([d]) == 0
    out = capsys.readouterr().out
    assert "fence_lost" in out and "host_preempted" in out

    folded = load_incidents(d)
    iid = next(i for i, v in folded.items() if v["kind"] == FENCE_LOST)
    assert cli.main([d, "show", iid]) == 0
    assert iid in capsys.readouterr().out

    assert cli.main([d, "report", iid, "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["verdict"] == FENCE_LOST

    assert cli.main([d, "resolve", iid, "--reason", "fixed"]) == 0
    capsys.readouterr()
    assert load_incidents(d)[iid]["status"] == "resolved"
    # Resolving again is a polite no-op.
    assert cli.main([d, "resolve", iid]) == 0
    assert "already resolved" in capsys.readouterr().out

    # sweep quarantines a planted partial bundle.
    part = os.path.join(
        d, "telemetry", tincident.BUNDLE_DIRNAME, "inc-0042.partial"
    )
    os.makedirs(part)
    assert cli.main([d, "sweep"]) == 0
    assert "1 partial bundle(s) quarantined" in capsys.readouterr().out
    assert not os.path.isdir(part)

    with pytest.raises(SystemExit):
        cli.main([d, "show", "inc-nope"])
