"""Imaging, profiling, checkpoint utility tests."""

import os

import jax
import numpy as np
import optax
import pytest

from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.checkpoint import restore_state, save_state
from multidisttorch_tpu.train.steps import create_train_state, make_train_step
from multidisttorch_tpu.utils.imaging import save_image_grid
from multidisttorch_tpu.utils.profiling import StepTimer, trial_timer


class TestImaging:
    def test_grayscale_grid(self, tmp_path):
        imgs = np.random.default_rng(0).uniform(0, 1, (16, 784))
        path = save_image_grid(imgs, str(tmp_path / "grid.png"), nrow=8)
        assert path.endswith(".png") or path.endswith(".npy")
        assert os.path.exists(path)
        if path.endswith(".png"):
            from PIL import Image

            im = Image.open(path)
            assert im.size == (8 * 28, 2 * 28)

    def test_rgb_grid(self, tmp_path):
        imgs = np.random.default_rng(0).uniform(0, 1, (4, 32 * 32 * 3))
        path = save_image_grid(imgs, str(tmp_path / "rgb.png"), nrow=4)
        if path.endswith(".png"):
            from PIL import Image

            im = Image.open(path)
            assert im.mode == "RGB"
            assert im.size == (4 * 32, 32)

    def test_3d_input(self, tmp_path):
        imgs = np.zeros((3, 28, 28))
        path = save_image_grid(imgs, str(tmp_path / "g3.png"), nrow=2)
        assert os.path.exists(path)


class TestCheckpoint:
    def test_roundtrip_across_submeshes(self, tmp_path):
        # Save a trained state from one submesh, restore onto another —
        # the checkpoint-restart and PBT-transfer mechanism.
        model = VAE(hidden_dim=16, latent_dim=4)
        tx = optax.adam(1e-3)
        g0, g1 = setup_groups(2)
        state = create_train_state(g0, model, tx, jax.random.key(0))
        step = make_train_step(g0, model, tx)
        batch = jax.numpy.asarray(
            np.random.default_rng(0).uniform(0, 1, (8, 784)).astype(np.float32)
        )
        state, _ = step(state, batch, jax.random.key(1))

        path = save_state(state, str(tmp_path / "ck" / "state.msgpack"),
                          metadata={"trial": 0})
        assert os.path.exists(path)
        assert os.path.exists(path + ".json")

        template = create_train_state(g1, model, tx, jax.random.key(9))
        restored = restore_state(template, path, trial=g1)
        assert int(restored.step) == 1
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            jax.device_get(restored.params),
            jax.device_get(state.params),
        )
        # restored state is live on the new submesh: take a step with it
        step1 = make_train_step(g1, model, tx)
        restored, m = step1(restored, batch, jax.random.key(2))
        assert np.isfinite(float(m["loss_sum"]))

    def test_sharded_state_roundtrip_keeps_sharding(self, tmp_path):
        # A TP-sharded state must restore SHARDED (round-4: restore_state
        # grew a shardings= arg; without it the restore lands replicated
        # and the memory benefit silently evaporates).
        from multidisttorch_tpu.models.vae import vae_tp_shardings
        from multidisttorch_tpu.train.steps import state_shardings

        model = VAE(hidden_dim=16, latent_dim=4)
        tx = optax.adam(1e-3)
        (g,) = setup_groups(1, model_parallel=4)
        state = create_train_state(
            g, model, tx, jax.random.key(0),
            param_shardings=vae_tp_shardings(g),
        )
        sh = state_shardings(state)
        step = make_train_step(g, model, tx, shardings=sh)
        batch = jax.device_put(
            jax.numpy.asarray(
                np.random.default_rng(1)
                .uniform(0, 1, (8, 784))
                .astype(np.float32)
            ),
            g.batch_sharding,
        )
        state, _ = step(state, batch, jax.random.key(1))

        path = save_state(state, str(tmp_path / "tp" / "state.msgpack"))
        restored = restore_state(state, path, trial=g, shardings=sh)
        k = restored.params["fc1"]["kernel"]
        assert k.addressable_shards[0].data.shape == (784, 4)  # 16/4
        # values identical and training continues sharded
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            jax.device_get(restored.params),
            jax.device_get(state.params),
        )
        restored, m = step(restored, batch, jax.random.key(2))
        assert np.isfinite(float(m["loss_sum"]))


class TestProfiling:
    def test_trial_timer_prints_reference_format(self, capsys):
        with trial_timer("trial 3", printer=print):
            pass
        out = capsys.readouterr().out
        assert "trial 3 Done. time:" in out

    def test_step_timer_stats(self):
        t = StepTimer()
        for _ in range(5):
            t.mark()
        s = t.stats()
        assert s["steps"] == 5
        assert s["total_s"] >= 0
        assert s["p95_s"] >= s["p50_s"] or s["steps"] < 3

    def test_empty_stats(self):
        assert StepTimer().stats() == {}
