"""Native C++ fastloader tests: build, bit-parity with numpy path,
prefetch correctness across epochs."""

import numpy as np
import pytest

from multidisttorch_tpu.data import native
from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.data.sampler import TrialDataIterator
from multidisttorch_tpu.parallel.mesh import setup_groups

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native fastloader toolchain unavailable"
)


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    images = rng.normal(size=(100, 17)).astype(np.float32)
    labels = rng.integers(0, 10, 100).astype(np.int32)
    g = native.NativeBatchGatherer(images, labels)
    perm = rng.permutation(100)
    n = g.start_epoch(perm, batch_size=8)
    assert n == 12
    for b in range(n):
        imgs, lbls = g.next_batch()
        idx = perm[b * 8 : (b + 1) * 8]
        np.testing.assert_array_equal(imgs, images[idx])
        np.testing.assert_array_equal(lbls, labels[idx])
    g.close()


def test_epoch_end_raises_stopiteration():
    images = np.ones((16, 4), np.float32)
    g = native.NativeBatchGatherer(images)
    n = g.start_epoch(np.arange(16), batch_size=8)
    for _ in range(n):
        g.next_batch()
    with pytest.raises(StopIteration):
        g.next_batch()
    g.close()


def test_multiple_epochs_reuse():
    rng = np.random.default_rng(1)
    images = rng.normal(size=(64, 8)).astype(np.float32)
    g = native.NativeBatchGatherer(images)
    for epoch in range(3):
        perm = rng.permutation(64)
        n = g.start_epoch(perm, batch_size=16)
        collected = np.concatenate([g.next_batch()[0] for _ in range(n)])
        np.testing.assert_array_equal(collected, images[perm])
    g.close()


def test_bad_permutation_rejected():
    g = native.NativeBatchGatherer(np.ones((4, 2), np.float32))
    with pytest.raises(ValueError):
        g.start_epoch(np.array([0, 1, 2, 99]), batch_size=2)
    g.close()


def test_iterator_native_vs_python_bit_identical():
    # The TrialDataIterator must yield identical batches whether the
    # native gatherer or the numpy path does the work.
    trial = setup_groups(8)[0]
    ds = synthetic_mnist(96, seed=0)
    it_native = TrialDataIterator(ds, trial, 32, seed=7, use_native=True)
    it_python = TrialDataIterator(ds, trial, 32, seed=7, use_native=False)
    assert it_native._use_native
    assert not it_python._use_native
    for a, b in zip(it_native.epoch(3), it_python.epoch(3)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_concurrent_epoch_generators_independent():
    # Regression (review finding): two live epoch() generators on one
    # iterator must not share native epoch state.
    trial = setup_groups(8)[0]
    ds = synthetic_mnist(96, seed=0)
    it = TrialDataIterator(ds, trial, 32, seed=7, use_native=True)
    ref = TrialDataIterator(ds, trial, 32, seed=7, use_native=False)
    a, b = it.epoch(0), it.epoch(1)
    ra, rb = ref.epoch(0), ref.epoch(1)
    # interleave consumption
    for pair in [(a, ra), (b, rb), (a, ra), (b, rb), (a, ra), (b, rb)]:
        got, want = next(pair[0]), next(pair[1])
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stacked_gatherer_matches_numpy_interleave():
    # StackedBatchGatherer = the flat gatherer over an interleaved
    # permutation; each next_stacked() must equal the lanes' batch-b
    # rows gathered by hand, including lanes on DIFFERENT permutations
    # (the mask-and-refill desync case).
    rng = np.random.default_rng(3)
    images = rng.normal(size=(100, 17)).astype(np.float32)
    perms = np.stack([rng.permutation(100) for _ in range(3)])
    g = native.StackedBatchGatherer(images)
    n = g.start_round(perms, batch_size=8)
    assert n == 12  # drop-tail per lane
    for b in range(n):
        got = g.next_stacked()
        assert got.shape == (3, 8, 17)
        for k in range(3):
            np.testing.assert_array_equal(
                got[k], images[perms[k, b * 8:(b + 1) * 8]]
            )
    g.close()


def test_stacked_iterator_native_vs_python_bit_identical():
    from multidisttorch_tpu.data.sampler import StackedTrialDataIterator

    trial = setup_groups(8)[0]
    ds = synthetic_mnist(96, seed=0)
    it_native = StackedTrialDataIterator(ds, trial, 16, [0, 5], use_native=True)
    it_python = StackedTrialDataIterator(ds, trial, 16, [0, 5], use_native=False)
    assert it_native._use_native and not it_python._use_native
    for a, b in zip(it_native.round_batches(), it_python.round_batches()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
