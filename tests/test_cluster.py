"""Cluster/env detection tests — parity with /root/reference/utils.py:9-144."""

import pytest

from multidisttorch_tpu.parallel.cluster import (
    ProcessEnv,
    coordinator_address,
    detect_process_env,
    find_ifname,
    parse_slurm_nodelist,
    process_world,
    select_platform,
)


class TestDetectProcessEnv:
    def test_openmpi_wins(self):
        env = {
            "OMPI_COMM_WORLD_SIZE": "12",
            "OMPI_COMM_WORLD_RANK": "7",
            "SLURM_NPROCS": "99",
            "SLURM_PROCID": "1",
        }
        assert detect_process_env(env) == ProcessEnv(12, 7, "openmpi")

    def test_slurm(self):
        env = {"SLURM_NPROCS": "4", "SLURM_PROCID": "3"}
        assert detect_process_env(env) == ProcessEnv(4, 3, "slurm")

    def test_tpu_multihost(self):
        env = {"TPU_WORKER_ID": "2", "TPU_WORKER_HOSTNAMES": "h0,h1,h2,h3"}
        assert detect_process_env(env) == ProcessEnv(4, 2, "tpu")

    def test_local_fallback(self):
        # Reference falls back to (1, 0) for sequential runs (utils.py:23-24).
        assert detect_process_env({}) == ProcessEnv(1, 0, "local")

    def test_rank_zero_openmpi_with_empty_rank_string_falls_through(self):
        # Reference quirk: getenv truthiness means OMPI rank "" falls through.
        env = {"OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": ""}
        assert detect_process_env(env).source == "local"


class TestParseSlurmNodelist:
    # Input examples straight from the reference docstring (utils.py:64-67).
    def test_single_node(self):
        assert parse_slurm_nodelist("or-condo-g04") == ["or-condo-g04"]

    def test_bracketed(self):
        assert parse_slurm_nodelist("or-condo-g[05,07-08,13]") == [
            "or-condo-g05",
            "or-condo-g07",
            "or-condo-g08",
            "or-condo-g13",
        ]

    def test_multiple_blocks(self):
        assert parse_slurm_nodelist("or-condo-g[05,07-08,13],or-condo-h[01,12]") == [
            "or-condo-g05",
            "or-condo-g07",
            "or-condo-g08",
            "or-condo-g13",
            "or-condo-h01",
            "or-condo-h12",
        ]

    def test_zero_padding_preserved(self):
        # The reference computes a %0Nd format from the range start
        # (utils.py:81-85); "008-011" keeps 3-digit padding.
        assert parse_slurm_nodelist("node[008-011]") == [
            "node008",
            "node009",
            "node010",
            "node011",
        ]

    def test_mixed_single_and_bracket(self):
        assert parse_slurm_nodelist("alpha,beta[1-3]") == [
            "alpha",
            "beta1",
            "beta2",
            "beta3",
        ]


class TestCoordinatorAddress:
    def test_lsb_hosts_token_1(self):
        # Summit jsrun: LSB_HOSTS token [1] (utils.py:111-114).
        env = {"LSB_HOSTS": "batch5 a01n01 a01n01 a01n02"}
        assert coordinator_address(env) == "a01n01:8889"

    def test_lsb_mcpu_hosts_token_2(self):
        env = {"LSB_MCPU_HOSTS": "batch5 42 a03n07 42"}
        assert coordinator_address(env) == "a03n07:8889"

    def test_slurm_nodelist_first_host(self):
        env = {"SLURM_NODELIST": "or-condo-g[05,07-08]"}
        assert coordinator_address(env) == "or-condo-g05:8889"

    def test_priority_lsb_over_slurm(self):
        env = {
            "LSB_HOSTS": "batch5 summit1 summit1",
            "SLURM_NODELIST": "cades1",
        }
        assert coordinator_address(env) == "summit1:8889"

    def test_default_and_port_override(self):
        # Reference defaults: 127.0.0.1:8889 (utils.py:108-109).
        assert coordinator_address({}) == "127.0.0.1:8889"
        assert coordinator_address({"MASTER_PORT": "1234"}) == "127.0.0.1:1234"
        assert coordinator_address({}, port=999) == "127.0.0.1:999"

    def test_master_addr_env(self):
        assert coordinator_address({"MASTER_ADDR": "10.0.0.5"}) == "10.0.0.5:8889"


def test_find_ifname_loopback():
    # Reference usage example: find_ifname("127.0.0.1") -> "lo"/"lo0"/...
    # (utils.py:40-45). On any Linux box loopback must resolve.
    pytest.importorskip("psutil")
    assert find_ifname("127.0.0.1") is not None


def test_find_ifname_unknown_returns_none():
    assert find_ifname("256.256.256.256") is None


def test_process_world_single_controller():
    assert process_world() == (1, 0)


class TestSelectPlatform:
    """MDT_PLATFORM — the DDP_BACKEND-style backend override
    (reference: /root/reference/utils.py:96-97)."""

    def test_unset_is_none_and_touches_nothing(self):
        assert select_platform({}) is None
        assert select_platform({"MDT_PLATFORM": ""}) is None

    def test_matching_platform_accepted_after_init(self):
        # The test harness already initialized the cpu backend; forcing
        # the same platform must succeed and report it.
        assert select_platform({"MDT_PLATFORM": "cpu"}) == "cpu"

    def test_mismatched_platform_after_init_raises(self):
        # Silent no-ops are the failure mode this knob exists to avoid:
        # jax.config.update ignores late changes, so the framework must
        # detect them and fail loudly — without mutating global config
        # on the error path.
        import jax

        jax.devices()  # order-independence: force backend init
        with pytest.raises(RuntimeError, match="already initialized"):
            select_platform({"MDT_PLATFORM": "tpu"})
        assert jax.default_backend() == "cpu"
        # config untouched: re-selecting the real platform still succeeds
        assert select_platform({"MDT_PLATFORM": "cpu"}) == "cpu"

    def test_default_argument(self):
        assert select_platform({}, default="cpu") == "cpu"
        assert select_platform({"MDT_PLATFORM": ""}, default="cpu") == "cpu"


class TestTimeouts:
    """Deadline-bounded cross-process coordination (satellite of the
    chaos-supervision PR): a dead peer must produce a diagnosable
    error, not an indefinite hang — the reference's lost-rank failure
    mode (SURVEY.md §5)."""

    def test_call_with_timeout_passes_value_and_errors_through(self):
        from multidisttorch_tpu.parallel.cluster import call_with_timeout

        assert call_with_timeout(lambda: 42, 5.0, "probe") == 42
        assert call_with_timeout(lambda: 42, None, "no deadline") == 42
        with pytest.raises(KeyError, match="boom"):
            call_with_timeout(
                lambda: (_ for _ in ()).throw(KeyError("boom")),
                5.0,
                "probe",
            )

    def test_call_with_timeout_raises_descriptive_timeout(self):
        import time as _time

        from multidisttorch_tpu.parallel.cluster import call_with_timeout

        with pytest.raises(TimeoutError, match="epoch-3 agreement"):
            call_with_timeout(
                lambda: _time.sleep(10), 0.1, "epoch-3 agreement"
            )

    def test_sync_hosts_times_out_on_slow_participant(self, monkeypatch):
        # Mocked slow participant: a 2-process world whose barrier
        # never returns. The timeout must name the barrier.
        import time as _time

        import jax
        from jax.experimental import multihost_utils

        from multidisttorch_tpu.parallel.cluster import sync_hosts

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            multihost_utils,
            "sync_global_devices",
            lambda name: _time.sleep(10),
        )
        with pytest.raises(TimeoutError, match="post-data-download"):
            sync_hosts("post-data-download", timeout_s=0.1)

    def test_sync_hosts_timeout_env_default(self, monkeypatch):
        import time as _time

        import jax
        from jax.experimental import multihost_utils

        from multidisttorch_tpu.parallel.cluster import sync_hosts

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            multihost_utils,
            "sync_global_devices",
            lambda name: _time.sleep(10),
        )
        monkeypatch.setenv("MDT_SYNC_TIMEOUT_S", "0.1")
        with pytest.raises(TimeoutError):
            sync_hosts("env-default")

    def test_group_all_ok_times_out_with_diagnosable_error(self, monkeypatch):
        # The driver's _agree_boundary primitive under a hung peer: the
        # reduction never resolves, the deadline turns it into an error
        # naming the agreement point.
        import time as _time

        from multidisttorch_tpu.parallel import collectives
        from multidisttorch_tpu.parallel.mesh import setup_groups

        (g,) = setup_groups(1)
        monkeypatch.setattr(
            collectives,
            "_sum_flags_fn",
            lambda mesh: lambda flags: _time.sleep(10),
        )
        with pytest.raises(
            TimeoutError, match="trial 7 epoch 2 boundary"
        ):
            collectives.group_all_ok(
                g, True, timeout_s=0.1,
                what="trial 7 epoch 2 boundary health agreement",
            )

    def test_group_all_ok_unbounded_still_works(self):
        from multidisttorch_tpu.parallel.collectives import group_all_ok
        from multidisttorch_tpu.parallel.mesh import setup_groups

        (g,) = setup_groups(1)
        assert group_all_ok(g, True) is True
        assert group_all_ok(g, False) is False
        assert group_all_ok(g, True, timeout_s=30.0) is True
