"""Group-scoped collective tests — parity with /root/reference/example-subgroup.py."""

import jax.numpy as jnp
import numpy as np

from multidisttorch_tpu.parallel.collectives import (
    group_all_gather,
    group_pmean,
    group_psum,
)
from multidisttorch_tpu.parallel.mesh import setup_groups


def test_all_gather_parity_with_reference_demo():
    # example-subgroup.py:25-33: group 1 (ranks 0-3) gathers [0,1,2,3],
    # group 2 (ranks 4-7) gathers [4,5,6,7], concurrently + independently.
    groups = setup_groups(2)
    results = []
    for g in groups:
        contrib = jnp.array(g.global_ranks, dtype=jnp.int32)  # rank i sends i
        results.append(np.asarray(group_all_gather(g, contrib)))
    np.testing.assert_array_equal(results[0], [0, 1, 2, 3])
    np.testing.assert_array_equal(results[1], [4, 5, 6, 7])


def test_all_gather_multidim():
    (g,) = setup_groups(1)
    x = jnp.arange(16.0).reshape(8, 2)
    out = np.asarray(group_all_gather(g, x))
    np.testing.assert_array_equal(out, np.arange(16.0).reshape(8, 2))


def test_psum_matches_numpy():
    groups = setup_groups(4)  # groups of 2
    g = groups[1]
    x = jnp.array([[1.0, 2.0], [10.0, 20.0]])  # one row per member
    out = np.asarray(group_psum(g, x))
    np.testing.assert_allclose(out, [11.0, 22.0])


def test_pmean_matches_numpy():
    groups = setup_groups(2)
    g = groups[0]
    x = jnp.arange(8.0).reshape(4, 2)
    out = np.asarray(group_pmean(g, x))
    np.testing.assert_allclose(out, x.mean(axis=0))


def test_collectives_are_group_scoped():
    # A group's psum must see only its own members' contributions.
    groups = setup_groups(2)
    for g, expected in zip(groups, [6.0, 22.0]):  # 0+1+2+3, 4+5+6+7
        contrib = jnp.array(g.global_ranks, dtype=jnp.float32)
        assert float(group_psum(g, contrib)) == expected
