"""Stretch-config models (BASELINE.md 3-4): conv β-VAE on CIFAR shapes,
ResNet-18 classifier on the subgroup scaffolding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.data.datasets import synthetic_cifar10
from multidisttorch_tpu.data.sampler import TrialDataIterator
from multidisttorch_tpu.models.conv_vae import ConvVAE
from multidisttorch_tpu.models.resnet import ResNet18
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.classifier import (
    create_classifier_state,
    make_classifier_eval_step,
    make_classifier_train_step,
)
from multidisttorch_tpu.train.steps import (
    create_train_state,
    make_eval_step,
    make_sample_step,
    make_train_step,
)


class TestConvVAE:
    def test_shapes(self):
        model = ConvVAE(latent_dim=16, base_channels=8)
        rng = jax.random.key(0)
        x = jnp.zeros((4, 32 * 32 * 3))
        params = model.init({"params": rng, "reparam": rng}, x)["params"]
        logits, mu, logvar = model.apply(
            {"params": params}, x, rngs={"reparam": rng}
        )
        assert logits.shape == (4, 3072)
        assert mu.shape == (4, 16)
        assert logvar.shape == (4, 16)

    def test_train_loss_decreases_on_submesh(self):
        model = ConvVAE(latent_dim=16, base_channels=8)
        tx = optax.adam(1e-3)
        trial = setup_groups(2)[0]
        state = create_train_state(trial, model, tx, jax.random.key(0))
        step = make_train_step(trial, model, tx, beta=1.0)
        ds = synthetic_cifar10(64, seed=0)
        it = TrialDataIterator(ds, trial, batch_size=32, seed=0)
        losses = []
        for e in range(8):
            for batch in it.epoch(e):
                state, m = step(
                    state, batch, jax.random.fold_in(jax.random.key(1), e)
                )
                losses.append(float(m["loss_sum"]) / 32)
        assert losses[-1] < losses[0]

    def test_eval_and_sample_steps_work(self):
        model = ConvVAE(latent_dim=16, base_channels=8)
        tx = optax.adam(1e-3)
        trial = setup_groups(4)[1]
        state = create_train_state(trial, model, tx, jax.random.key(0))
        ds = synthetic_cifar10(16, seed=0)
        ev = make_eval_step(trial, model, beta=4.0)
        out = ev(state, jnp.asarray(ds.images[:16]))
        assert out["recon"].shape == (16, 3072)
        samples = make_sample_step(trial, model, num_samples=4)(
            state, jax.random.key(2)
        )
        assert samples.shape == (4, 3072)


class TestResNet18:
    @pytest.fixture(scope="class")
    def setup(self):
        model = ResNet18(num_classes=10, base_channels=8)
        trial = setup_groups(2)[1]
        tx = optax.adam(1e-3)
        return model, trial, tx

    def _fresh_state(self, setup):
        model, trial, tx = setup
        # fresh per test: train steps donate their input state buffers
        return create_classifier_state(trial, model, tx, jax.random.key(0))

    def test_forward_shape(self, setup):
        model, trial, tx = setup
        state = self._fresh_state(setup)
        logits = model.apply(
            {"params": state.params}, jnp.zeros((4, 32 * 32 * 3))
        )
        assert logits.shape == (4, 10)

    def test_param_count_is_resnet18_scale(self):
        # Full-width ResNet-18 ~ 11M params; sanity-check the topology.
        model = ResNet18(num_classes=10)
        params = model.init(
            {"params": jax.random.key(0)}, jnp.zeros((1, 3072))
        )["params"]
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert 10e6 < n < 13e6

    def test_training_improves_accuracy(self, setup):
        model, trial, tx = setup
        state = self._fresh_state(setup)
        ds = synthetic_cifar10(256, seed=0)
        it = TrialDataIterator(ds, trial, batch_size=64, with_labels=True, seed=0)
        step = make_classifier_train_step(trial, model, tx)
        accs = []
        for e in range(6):
            for images, labels in it.epoch(e):
                state, m = step(state, images, labels)
                accs.append(float(m["accuracy"]))
        # synthetic classes are separable; must beat chance solidly
        assert np.mean(accs[-4:]) > 0.3
        assert np.mean(accs[-4:]) > np.mean(accs[:4])

    def test_eval_step(self, setup):
        model, trial, tx = setup
        state = self._fresh_state(setup)
        ds = synthetic_cifar10(64, seed=1)
        ev = make_classifier_eval_step(trial, model)
        out = ev(
            state, jnp.asarray(ds.images[:64]), jnp.asarray(ds.labels[:64])
        )
        assert 0.0 <= float(out["correct"]) <= 64.0
        assert np.isfinite(float(out["loss"]))


class TestClassifierMultiStep:
    def test_matches_sequential_steps(self):
        from multidisttorch_tpu.train.classifier import (
            make_classifier_multi_step,
        )

        model = ResNet18(num_classes=10, base_channels=4)
        trial = setup_groups(4)[0]
        tx = optax.adam(1e-3)
        ds = synthetic_cifar10(96, seed=2)
        it = TrialDataIterator(ds, trial, batch_size=16, with_labels=True, seed=3)

        s_seq = create_classifier_state(trial, model, tx, jax.random.key(1))
        step = make_classifier_train_step(trial, model, tx)
        seq_losses = []
        flat = list(it.epoch(0))[:3]
        for images, labels in flat:
            s_seq, m = step(s_seq, images, labels)
            seq_losses.append(float(m["loss"]))

        s_multi = create_classifier_state(trial, model, tx, jax.random.key(1))
        multi = make_classifier_multi_step(trial, model, tx)
        _, images, labels = next(it.epoch_chunks(0, 3))
        s_multi, metrics = multi(s_multi, images, labels)

        assert metrics["loss"].shape == (3,)
        np.testing.assert_allclose(
            np.asarray(metrics["loss"]), seq_losses, rtol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            s_multi.params,
            s_seq.params,
        )
